"""Batched preemption planning for failure waves.

Reference: pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go — dryRunPreemption (:320) runs selectVictimsOnNode
(:592) per candidate node on parallel goroutines, re-running the whole
filter chain once per removed/re-added victim. For a saturated cluster
that is O(candidates x victims) full filter-chain runs PER PREEMPTOR
(~80ms of host Python here — the r3 Preemption-500n-500hi crawl at 5.6
pods/s).

The TPU build's answer: a failure wave is planned as a BATCH. For
preemptors whose filter set reduces to statically-checkable node gates
plus resource fit (no pod-affinity terms, no topology spread, no host
ports, no PVCs — and no required-anti-affinity pods or matching PDBs in
the cluster), victim removal can only affect the preemptor through the
node's free-resource vector, so:

  * base feasibility ("all lower-priority pods removed") is ONE numpy
    comparison over every node at once — the per-node count/utilization
    deltas the dry-run simulates pod-by-pod collapse into per-priority
    prefix sums;
  * the reprieve loop (victims added back highest-priority-first while
    the preemptor still fits, :633) needs only vector arithmetic on the
    preemptor's request — no filter re-runs;
  * candidate choice reuses DefaultPreemption._pick_one verbatim, so the
    chosen node and victim set match the oracle plugin exactly (pinned
    by tests/test_preemption_fast.py parity fuzz);
  * pods planned earlier in the wave are accounted as nominated load for
    later pods (the sequential nominator semantics of the serial path),
    and their victims leave the books — two preemptors never claim the
    same victim, which the serial oracle only achieves by informer echo
    luck.

Anything outside that envelope (dense-constraint preemptors, PDBs,
required anti-affinity in the cluster) falls back to the oracle
DefaultPreemption plugin per pod — correctness is never traded.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import types as v1
from .framework.interface import CycleState
from .framework.types import NodeInfo, calculate_resource
from .plugins.defaultpreemption import (
    Candidate,
    DefaultPreemption,
    MIN_CANDIDATE_NODES_ABSOLUTE,
    MIN_CANDIDATE_NODES_PERCENTAGE,
)


def _prio(pod: v1.Pod) -> int:
    return pod.spec.priority or 0


class WaveAntiTerms:
    """ONE cluster pass per failure wave over the pods with required
    anti-affinity, memoized per preemptor identity.

    fast_eligible's existing-anti check used to re-walk every
    pod-with-anti-affinity node list for EACH failed pod in the wave —
    O(wave x cluster) for a check whose inputs repeat: the terms are a
    wave-constant cluster property, and wave pods are stamped from a
    handful of templates, so the match verdict depends only on the
    preemptor's (namespace, labels) row. The memo key is that row (the
    template-identity analog of _affinity_fingerprint for the
    label-match side): template-stamped waves pay one term walk per
    template instead of one cluster walk per pod."""

    def __init__(self, snapshot):
        self.terms = [
            term
            for ni in snapshot.have_pods_with_required_anti_affinity_list
            for existing in ni.pods_with_required_anti_affinity
            for term in existing.required_anti_affinity_terms
        ]
        self._memo: Dict[Tuple, bool] = {}

    def matches(self, pod: v1.Pod) -> bool:
        """True when ANY existing pod's required anti-affinity term
        matches this preemptor (the filtering.go existing-anti check the
        planner envelopes cannot express under victim eviction)."""
        if not self.terms:
            return False
        key = (
            pod.metadata.namespace,
            tuple(sorted((pod.metadata.labels or {}).items())),
        )
        hit = self._memo.get(key)
        if hit is None:
            hit = any(t.matches(pod) for t in self.terms)
            self._memo[key] = hit
        return hit


def fast_eligible(pod: v1.Pod, snapshot, pdbs: Sequence, extenders: Sequence,
                  anti_terms: Optional[WaveAntiTerms] = None) -> bool:
    """True when the planner's envelope provably matches the oracle
    dry-run for this pod: every filter that victims could influence is
    the resource-fit filter. PDBs are INSIDE the envelope (the planner
    vectorizes filterPodsWithPDBViolation + the violating-first reprieve);
    required anti-affinity bails per POD, not per cluster — an existing
    pod's anti term can only block this preemptor (or change under victim
    removal) if the term MATCHES the preemptor's labels+namespace
    (filtering.go existing-anti check); unmatched anti pods elsewhere in
    the cluster are irrelevant to this pod's dry-run."""
    if extenders:
        return False
    if anti_terms is None:
        anti_terms = WaveAntiTerms(snapshot)  # single-pod callers
    if anti_terms.matches(pod):
        return False
    if not eviction_invariant_gates(pod):
        return False
    spec = pod.spec
    if spec.affinity is not None and (
        spec.affinity.pod_affinity is not None
        or spec.affinity.pod_anti_affinity is not None
    ):
        return False
    if spec.topology_spread_constraints:
        return False
    return True


def eviction_invariant_gates(pod: v1.Pod) -> bool:
    """The planner-envelope gates victim EVICTION cannot express —
    shared by fast_eligible and device_eligible so the two envelopes
    cannot drift: Never-policy, a pinned spec.nodeName, host ports
    (NodePorts reads the preemptor's wants, not the victims'), and PVC
    volumes (binding decisions are host-side)."""
    spec = pod.spec
    if spec.preemption_policy == "Never":
        return False
    if spec.node_name:
        return False
    for c in spec.containers:
        for port in c.ports or []:
            if (port.host_port or 0) > 0:
                return False
    for vol in spec.volumes or []:
        if (vol.source or {}).get("persistentVolumeClaim"):
            return False
    return True


_PRIO_SENTINEL = np.iinfo(np.int64).max  # padding rows never match `< prio`


class FastPreemptionPlanner:
    """Plans preemption for a wave of failed pods against one snapshot.

    Resource dimensions are discovered from the preemptors' requests:
    cpu (milli), memory, ephemeral storage, pod count, plus any scalar
    resource a wave pod requests. Victim bookkeeping tracks the same
    dims. All arrays are [D, N] int64.
    """

    def __init__(self, snapshot, nominator, framework=None,
                 args: Optional[dict] = None,
                 claimed_victims: Optional[Set[str]] = None,
                 pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None):
        self.snapshot = snapshot
        self.nominator = nominator
        self.framework = framework
        self.pdbs = list(pdbs or [])
        # victims claimed by earlier waves still dying in the cache:
        # treated as already-removed (their resources left the books the
        # moment they were claimed; the claimer's nominated load covers
        # the replacement)
        self.claimed_victims = claimed_victims or set()
        args = args or {}
        self.min_pct = args.get(
            "minCandidateNodesPercentage", MIN_CANDIDATE_NODES_PERCENTAGE
        )
        self.min_abs = args.get(
            "minCandidateNodesAbsolute", MIN_CANDIDATE_NODES_ABSOLUTE
        )
        self.nodes: List[NodeInfo] = snapshot.list()
        self.n = len(self.nodes)
        self._name_to_idx = {
            ni.node.metadata.name: i for i, ni in enumerate(self.nodes)
        }
        self.fits_now: List[bool] = []
        self._static_cache: Dict[Tuple, np.ndarray] = {}
        # nominated load per node: [(prio, req_vec, key)] — seeded from
        # the nominator, grown as the wave claims nodes
        self._nominated: Dict[int, List[Tuple[int, np.ndarray, str]]] = {}
        self._dims: List[str] = []
        self._alloc: Optional[np.ndarray] = None
        self._used: Optional[np.ndarray] = None
        self._npods: Optional[np.ndarray] = None
        self._max_pods: Optional[np.ndarray] = None
        # per-distinct-priority caches
        self._lower_sum: Dict[int, np.ndarray] = {}
        self._lower_cnt: Dict[int, np.ndarray] = {}

    # -- wave setup --------------------------------------------------------

    def _req_vec(self, pod: v1.Pod) -> np.ndarray:
        res, _, _ = calculate_resource(pod)
        vec = np.zeros(len(self._dims), dtype=np.int64)
        for d, name in enumerate(self._dims):
            if name == "cpu":
                vec[d] = res.milli_cpu
            elif name == "memory":
                vec[d] = res.memory
            elif name == "ephemeral-storage":
                vec[d] = res.ephemeral_storage
            else:
                vec[d] = res.scalar_resources.get(name, 0)
        return vec

    def _build(self, wave: List[v1.Pod]) -> None:
        dims = ["cpu", "memory", "ephemeral-storage"]
        scalars: Set[str] = set()
        for pod in wave:
            res, _, _ = calculate_resource(pod)
            scalars.update(res.scalar_resources)
        self._dims = dims + sorted(scalars)
        D, N = len(self._dims), self.n
        self._alloc = np.zeros((D, N), dtype=np.int64)
        self._used = np.zeros((D, N), dtype=np.int64)
        self._npods = np.zeros(N, dtype=np.int64)
        self._max_pods = np.zeros(N, dtype=np.int64)

        wave_prios = sorted({_prio(p) for p in wave})
        cols = getattr(self.snapshot, "columnar_util", None)
        col_base = (
            cols is not None
            and [ni.node.metadata.name for ni in self.nodes] == cols["names"]
        )
        if col_base:
            # the base dims (cpu/memory/ephemeral — the columnar cache's
            # fixed row layout) land as one transposed array copy off
            # the snapshot's utilization gather instead of a per-node
            # Python attribute walk; scalar dims (wave-discovered, not
            # columnar) still walk below
            self._alloc[0:3, :] = cols["alloc"].T
            self._used[0:3, :] = cols["requested"].T
        for d in range(D):
            name = self._dims[d]
            if col_base and d < 3:
                continue
            for i, ni in enumerate(self.nodes):
                if name == "cpu":
                    self._alloc[d, i] = ni.allocatable.milli_cpu
                    self._used[d, i] = ni.requested.milli_cpu
                elif name == "memory":
                    self._alloc[d, i] = ni.allocatable.memory
                    self._used[d, i] = ni.requested.memory
                elif name == "ephemeral-storage":
                    self._alloc[d, i] = ni.allocatable.ephemeral_storage
                    self._used[d, i] = ni.requested.ephemeral_storage
                else:
                    self._alloc[d, i] = ni.allocatable.scalar_resources.get(name, 0)
                    self._used[d, i] = ni.requested.scalar_resources.get(name, 0)
        lo_sum = {p: np.zeros((D, N), dtype=np.int64) for p in wave_prios}
        lo_cnt = {p: np.zeros(N, dtype=np.int64) for p in wave_prios}
        per_node: List[List] = []
        from .plugins.coscheduling import pod_group

        for i, ni in enumerate(self.nodes):
            self._npods[i] = len(ni.pods)
            self._max_pods[i] = ni.allocatable.allowed_pod_number
            # victim slots are same-node eviction UNITS: singletons for
            # plain pods, whole gangs for co-located gang members (the
            # oracle's _victim_units — whole gangs or none). A unit's
            # slot carries the members' summed request vector; its
            # priority is the members' MAX so the `< prio` validity
            # check admits a gang only when EVERY member is outranked
            gang_units: Dict[Tuple[str, str], List[v1.Pod]] = {}
            victims = []
            for pi in ni.pods:
                if v1.pod_key(pi.pod) in self.claimed_victims:
                    # an in-flight wave already evicted it: neither
                    # present (its resources are spoken for) nor
                    # evictable again
                    self._used[:, i] -= self._req_vec(pi.pod)
                    self._npods[i] -= 1
                    continue
                group, min_available = pod_group(pi.pod)
                if group and min_available > 1:
                    gang_units.setdefault(
                        (pi.pod.metadata.namespace, group), []
                    ).append(pi.pod)
                    continue
                vp = _prio(pi.pod)
                if vp >= wave_prios[-1]:
                    continue
                vec = self._req_vec(pi.pod)
                victims.append(
                    (vp, pi.pod.status.start_time or 0.0, vec, [pi.pod])
                )
                for p in wave_prios:
                    if vp < p:
                        lo_sum[p][:, i] += vec
                        lo_cnt[p][i] += 1
            for members in gang_units.values():
                vp = max(_prio(m) for m in members)
                if vp >= wave_prios[-1]:
                    continue
                members.sort(
                    key=lambda m: (-_prio(m), m.status.start_time or 0.0)
                )
                vec = np.sum(
                    [self._req_vec(m) for m in members], axis=0
                ).astype(np.int64)
                start = min(
                    m.status.start_time or 0.0
                    for m in members if _prio(m) == vp
                )
                victims.append((vp, start, vec, members))
                for p in wave_prios:
                    if vp < p:
                        lo_sum[p][:, i] += vec
                        lo_cnt[p][i] += len(members)
            # victims stored in ni.pods ORDER; both PDB allowance
            # consumption (:612 sorts by MoreImportantPod BEFORE
            # filterPodsWithPDBViolation) and the reprieve (highest
            # priority, earliest start, :633) walk the _vsort permutation
            per_node.append(victims)
        self._lower_sum = lo_sum
        self._lower_cnt = lo_cnt
        # padded victim books [N, Vmax, ...] — the reprieve loop runs
        # vectorized over every candidate node at once (per-candidate
        # Python iteration was the wave's dominant cost at 500x100x4)
        Vmax = max((len(v) for v in per_node), default=0)
        self._vmax = Vmax
        self._vvec = np.zeros((N, max(Vmax, 1), D), dtype=np.int64)
        # pad priority with a sentinel above any real priority so the
        # `< prio` validity check rejects padding rows
        self._vprio = np.full((N, max(Vmax, 1)), _PRIO_SENTINEL, dtype=np.int64)
        self._vstart = np.zeros((N, max(Vmax, 1)), dtype=np.float64)
        self._valive = np.zeros((N, max(Vmax, 1)), dtype=bool)
        # per-slot unit shape: member count (pod-count arithmetic +
        # victim tallies), summed member priority (the pick ladder's
        # sum_prio is per POD), and the LATEST start among the slot's
        # highest-priority members (_vstart keeps the EARLIEST — the
        # MoreImportantPod sort key — while the ladder's latest-start
        # tiebreak reads per-pod maxima)
        self._vsize = np.zeros((N, max(Vmax, 1)), dtype=np.int64)
        self._vpriosum = np.zeros((N, max(Vmax, 1)), dtype=np.int64)
        self._vlatest_hi = np.zeros((N, max(Vmax, 1)), dtype=np.float64)
        self._vpods: List[List[List[v1.Pod]]] = []
        # PDB match tensor [N, Vmax, P]: how many of slot (i, j)'s
        # members consume pdb p's budget (same namespace + selector
        # match)? Counts, not booleans — a gang unit can hold several
        # matching members
        P = len(self.pdbs)
        self._pdb_match = np.zeros((N, max(Vmax, 1), max(P, 1)), dtype=np.int64)
        self._pdb_allowed = np.zeros(max(P, 1), dtype=np.int64)
        sels = []
        if P:
            from ..api.labels import Selector

            for p_i, pdb in enumerate(self.pdbs):
                self._pdb_allowed[p_i] = pdb.status.disruptions_allowed
                sels.append(
                    Selector.from_label_selector(pdb.spec.selector)
                    if pdb.spec.selector else None
                )
        for i, victims in enumerate(per_node):
            pods_row: List[List[v1.Pod]] = []
            for j, (vp, start, vec, members) in enumerate(victims):
                self._vvec[i, j] = vec
                self._vprio[i, j] = vp
                self._vstart[i, j] = start
                self._valive[i, j] = True
                self._vsize[i, j] = len(members)
                self._vpriosum[i, j] = sum(_prio(m) for m in members)
                self._vlatest_hi[i, j] = max(
                    m.status.start_time or 0.0
                    for m in members if _prio(m) == vp
                )
                pods_row.append(members)
                for vpod in members:
                    for p_i, pdb in enumerate(self.pdbs):
                        if pdb.metadata.namespace != vpod.metadata.namespace:
                            continue
                        sel = sels[p_i]
                        if sel is not None and sel.matches(
                                vpod.metadata.labels):
                            self._pdb_match[i, j, p_i] += 1
            self._vpods.append(pods_row)
        # reprieve permutation: order victims (highest priority, earliest
        # start); padding rows sort last
        skey = np.where(
            self._valive, self._vprio, np.int64(-(2 ** 62))
        )
        self._vsort = np.lexsort(
            (self._vstart, -skey), axis=1
        )
        # seed nominated load (RunFilterPluginsWithNominatedPods adds
        # nominated pods with priority >= preemptor's, framework.go:610).
        # Running totals make the uniform-priority wave O(1) per pod —
        # rebuilding a [D, N] matrix from the entry lists per planned pod
        # was O(wave^2) and dominated the 500-pod wave
        self._nominated = {}
        self._nom_sum = np.zeros((D, N), dtype=np.int64)
        self._nom_cnt = np.zeros(N, dtype=np.int64)
        self._nom_min_prio: Optional[int] = None  # min prio among entries
        if self.nominator is not None:
            wave_keys = {v1.pod_key(p) for p in wave}
            for i, ni in enumerate(self.nodes):
                for np_pod in self.nominator.nominated_pods_for_node(
                    ni.node.metadata.name
                ):
                    key = v1.pod_key(np_pod)
                    if key in wave_keys:
                        continue  # re-planning pods don't self-block
                    p, vec = _prio(np_pod), self._req_vec(np_pod)
                    self._nominated.setdefault(i, []).append((p, vec, key))
                    self._nom_sum[:, i] += vec
                    self._nom_cnt[i] += 1
                    self._nom_min_prio = (
                        p if self._nom_min_prio is None
                        else min(self._nom_min_prio, p)
                    )

    # -- static node gates (victim-independent filters) --------------------

    def _static_mask(self, pod: v1.Pod) -> np.ndarray:
        """Per-node pass/fail for the preemptor's victim-independent
        filters: NodeUnschedulable, TaintToleration, NodeAffinity — one
        host evaluation per (template, node), cached by the pod fields
        those filters read."""
        key = (
            tuple(sorted((pod.spec.node_selector or {}).items())),
            _affinity_fingerprint(pod),
            _tolerations_fingerprint(pod),
        )
        mask = self._static_cache.get(key)
        if mask is not None:
            return mask
        from .plugins.nodebasic import NodeAffinity, NodeUnschedulable, TaintToleration

        unsched = NodeUnschedulable()
        taints = TaintToleration()
        affinity = NodeAffinity()
        mask = np.zeros(self.n, dtype=bool)
        state = CycleState()
        for i, ni in enumerate(self.nodes):
            ok = (
                unsched.filter(state, pod, ni) is None
                and taints.filter(state, pod, ni) is None
                and affinity.filter(state, pod, ni) is None
            )
            mask[i] = ok
        self._static_cache[key] = mask
        return mask

    # -- planning ----------------------------------------------------------

    def plan(
        self, wave: List[v1.Pod]
    ) -> List[Optional[Candidate]]:
        """One Candidate (nominated node + victims) per pod, or None when
        preemption cannot help. Pods are planned in order; earlier plans
        are visible to later ones as nominated load + claimed victims."""
        self.fits_now: List[bool] = []
        if not wave:
            return []
        self._build(wave)
        limit = self._num_candidates()
        out: List[Optional[Candidate]] = []
        for pod in wave:
            out.append(self._plan_one(pod, limit))
        return out

    def _num_candidates(self) -> int:
        n = self.n * self.min_pct // 100
        n = max(n, self.min_abs)
        return min(n, self.n)

    def _nom_arrays(self, prio: int) -> Tuple[np.ndarray, np.ndarray]:
        """Nominated load per node as [D, N] / [N] arrays for entries
        with priority >= prio. Uniform waves hit the running totals;
        a preemptor outranked by some nominee rebuilds (rare)."""
        if self._nom_min_prio is None or prio <= self._nom_min_prio:
            return self._nom_sum, self._nom_cnt
        vec = np.zeros_like(self._nom_sum)
        cnt = np.zeros_like(self._nom_cnt)
        for i, entries in self._nominated.items():
            for p, req, _ in entries:
                if p >= prio:
                    vec[:, i] += req
                    cnt[i] += 1
        return vec, cnt

    def _plan_one(self, pod: v1.Pod, limit: int) -> Optional[Candidate]:
        from . import metrics

        metrics.preemption_planner.inc(path="fast")
        prio = _prio(pod)
        req = self._req_vec(pod)
        static = self._static_mask(pod)
        lower_sum = self._lower_sum[prio]
        lower_cnt = self._lower_cnt[prio]
        # free with EVERY lower-priority pod removed (the dry-run's base
        # state, :626), before nominated load
        free_all = self._alloc - self._used + lower_sum
        cnt_all = self._npods - lower_cnt
        nom_vec, nom_cnt = self._nom_arrays(prio)
        # fits WITHOUT any eviction (cluster state moved since the batch
        # dispatched): not preemption's business — the caller re-runs the
        # pod through the kernel for a scored placement
        fits_now = bool(
            np.any(
                static
                & np.all(
                    self._alloc - self._used - nom_vec >= req[:, None], axis=0
                )
                & (self._npods + nom_cnt + 1 <= self._max_pods)
            )
        )
        self.fits_now.append(fits_now)
        if fits_now:
            return None
        feasible = (
            static
            & (lower_cnt > 0)
            & np.all(free_all - nom_vec >= req[:, None], axis=0)
            & (cnt_all + nom_cnt + 1 <= self._max_pods)
        )
        idxs = np.flatnonzero(feasible)
        if idxs.size == 0 or self._vmax == 0:
            return None
        # every feasible node yields >=1 victim (all-reprieved would mean
        # the pod fits with nobody removed — excluded by fits_now above),
        # so the oracle's first-`limit`-candidates cut is just a slice
        C = idxs[:limit]
        Csz = C.size
        rows = np.arange(Csz)
        violating = self._pdb_violating(C, prio)
        # -- vectorized reprieve (:633) over all candidates at once, in
        # the oracle's order: the VIOLATING group first, then the rest,
        # each (highest priority, earliest start) via the _vsort
        # permutation; nodes are independent, so per-node sequential
        # semantics hold exactly
        free = free_all[:, C] - nom_vec[:, C] - req[:, None]  # [D, C]
        slots = (
            self._max_pods[C] - cnt_all[C] - nom_cnt[C] - 1
        )  # remaining re-add slots [C]
        n_vict = np.zeros(Csz, dtype=np.int64)
        n_pdbv = np.zeros(Csz, dtype=np.int64)
        sum_prio = np.zeros(Csz, dtype=np.int64)
        max_prio = np.full(Csz, np.iinfo(np.int64).min, dtype=np.int64)
        victim_mask = np.zeros((Csz, self._vmax), dtype=bool)
        for in_violating_group in (True, False):
            for v in range(self._vmax):
                j = self._vsort[C, v]  # per-candidate column [C]
                valid = (
                    self._valive[C, j]
                    & (self._vprio[C, j] < prio)
                    & (violating[rows, j] == in_violating_group)
                )
                vec = self._vvec[C, j].T  # [D, C]
                size = self._vsize[C, j]  # unit member count [C]
                can = valid & (slots >= size) & np.all(vec <= free, axis=0)
                free = free - np.where(can, vec, 0)
                slots = slots - np.where(can, size, 0)
                vic = valid & ~can
                victim_mask[rows, j] |= vic
                n_vict += np.where(vic, size, 0)
                if in_violating_group:
                    n_pdbv += np.where(vic, size, 0)
                sum_prio += np.where(vic, self._vpriosum[C, j], 0)
                vp = self._vprio[C, j]
                max_prio = np.maximum(
                    max_prio, np.where(vic, vp, np.iinfo(np.int64).min))
        # latest start among each candidate's HIGHEST-priority victims
        hi_mask = victim_mask & (self._vprio[C] == max_prio[:, None])
        latest = np.max(
            np.where(hi_mask, self._vlatest_hi[C], -np.inf), axis=1
        )
        ci = self._pick_index(n_vict > 0, n_pdbv, max_prio, sum_prio,
                              n_vict, latest)
        if ci is None:
            return None
        i = int(C[ci])
        victims = _ordered_victims(
            self._vpods[i], victim_mask[ci], violating[ci],
            self._vsort[i], self._vmax,
        )
        best = Candidate(
            self.nodes[i].node.metadata.name, victims,
            num_pdb_violations=int(n_pdbv[ci]),
        )
        self._claim(best, pod, prio, req)
        return best

    def _pdb_violating(self, C: np.ndarray, prio: int) -> np.ndarray:
        """filterPodsWithPDBViolation (:660), vectorized per candidate:
        victims consume PDB allowances in MoreImportantPod order
        (priority desc, earlier start first — the :612 sort runs BEFORE
        the split in the reference), i.e. column-by-column through the
        _vsort permutation; a victim whose matched budget is already
        exhausted at its turn is "violating". Shared verbatim by the
        numpy reprieve and the device what-if planner (PDB accounting
        is host bookkeeping on both rungs)."""
        Csz = C.size
        rows = np.arange(Csz)
        # width max(vmax, 1) like every sibling wave-book array
        # (_valive/_vprio/_vsort): the device rung gathers through the
        # _vsort permutation even when ZERO eviction units exist
        # cluster-wide (e.g. every resident pod sits inside a mixed
        # gang) — it still owes the caller the launch's fits_now
        # verdict — and a width-0 row here would throw the gather
        violating = np.zeros((Csz, max(self._vmax, 1)), dtype=bool)
        if self.pdbs:
            allowed_rem = np.repeat(
                self._pdb_allowed[:, None], Csz, axis=1
            )  # [P, C]
            for v in range(self._vmax):
                j = self._vsort[C, v]  # per-candidate column [C]
                valid_o = self._valive[C, j] & (self._vprio[C, j] < prio)
                # per-slot MATCH COUNTS (a gang unit may hold several
                # members of one budget): the unit violates when its
                # members outnumber the remaining allowance — the exact
                # member-sequential consumption the oracle runs, since
                # members beyond the allowance each hit an exhausted
                # budget at their turn
                m = self._pdb_match[C, j, :].T * valid_o[None, :]  # [P, C]
                avail = np.maximum(allowed_rem, 0)
                violating[rows, j] = np.any(m > avail, axis=0)
                allowed_rem -= np.minimum(m, avail)
        return violating

    @staticmethod
    def _pick_index(alive, n_pdbv, max_prio, sum_prio, n_vict, latest):
        """pickOneNodeForPreemption (:457), vectorized with the same
        tie-break ladder as DefaultPreemption._pick_one (fewest PDB
        violations first); final tie -> first candidate in snapshot
        order. Returns the winning index into the candidate axis, or
        None when no candidate is alive."""
        if not alive.any():
            return None
        best_mask = alive
        for crit, reverse in (
            (n_pdbv, False),
            (max_prio, False), (sum_prio, False),
            (n_vict, False), (latest, True),
        ):
            vals = np.where(best_mask, crit, np.inf if not reverse else -np.inf)
            target = vals.max() if reverse else vals.min()
            best_mask = best_mask & (vals == target)
            if best_mask.sum() == 1:
                break
        return int(np.flatnonzero(best_mask)[0])

    def _claim(self, cand: Candidate, pod: v1.Pod, prio: int, req: np.ndarray) -> None:
        """Apply a chosen candidate to the wave books: the preemptor
        becomes nominated load on the node; its victims leave every
        per-priority prefix (they are being evicted — later wave pods
        must not count them as either present or evictable)."""
        i = self._name_to_idx[cand.node_name]
        self._nominated.setdefault(i, []).append((prio, req, v1.pod_key(pod)))
        self._nom_sum[:, i] += req
        self._nom_cnt[i] += 1
        self._nom_min_prio = (
            prio if self._nom_min_prio is None
            else min(self._nom_min_prio, prio)
        )
        victim_keys = {v1.pod_key(v) for v in cand.victims}
        for j, slot_pods in enumerate(self._vpods[i]):
            if not slot_pods or not any(
                v1.pod_key(vp) in victim_keys for vp in slot_pods
            ):
                continue
            # gone from the node: present-resources AND the
            # lower-priority prefixes both drop. Units leave WHOLE
            # (candidates only ever contain complete units)
            vp = int(self._vprio[i, j])
            vec = self._vvec[i, j]
            size = int(self._vsize[i, j])
            self._valive[i, j] = False
            self._vpods[i][j] = []
            self._used[:, i] -= vec
            self._npods[i] -= size
            for p in self._lower_sum:
                if vp < p:
                    self._lower_sum[p][:, i] -= vec
                    self._lower_cnt[p][i] -= size


def _ordered_victims(pods_row, victim_mask, violating_row, vsort, vmax):
    """Victims in the oracle's append order: the violating group first,
    then the rest, each in reprieve (priority desc, start asc) order —
    Candidate.victims ordering is observable (eviction order). A slot's
    members (one pod, or a whole gang unit pre-sorted by
    MoreImportantPod) append consecutively."""
    out = []
    for in_violating_group in (True, False):
        for v in range(vmax):
            j = int(vsort[v])
            if victim_mask[j] and bool(violating_row[j]) == in_violating_group:
                out.extend(pods_row[j])
    return out


def _affinity_fingerprint(pod: v1.Pod):
    a = pod.spec.affinity
    if a is None or a.node_affinity is None:
        return None
    from ..utils import serde

    return str(serde.to_dict(a.node_affinity))


def _tolerations_fingerprint(pod: v1.Pod):
    return tuple(
        (t.key or "", t.operator or "", t.value or "", t.effect or "")
        for t in pod.spec.tolerations or []
    )
