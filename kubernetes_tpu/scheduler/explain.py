"""Decision explainability: per-plugin attribution of scheduling decisions.

Shared machinery for three consumers:

- the ``KTPU_EXPLAIN`` harvest path (TPUBackend decodes the hoisted
  session's explain payload into per-plugin filter verdicts and weighted
  score splits, attached to level-2 trace provenance),
- the shadow parity sentinel (``KTPU_SHADOW_SAMPLE``: the completion
  worker replays sampled decisions through the oracle filter/score chain
  and diffs per plugin), and
- the triage CLIs (``scripts/explain_decision.py`` renders a decision as
  the oracle would log it; ``scripts/replay_drift.py`` re-runs a frozen
  repro bundle through both paths).

Both paths produce the same *breakdown* shape so they diff directly:

    {"filters": {node: {plugin: passed}},   # per-plugin verdicts
     "scores":  {plugin: {node: weighted}}, # feasible nodes only
     "totals":  {node: total},
     "best":    [nodes tied at max total]}

The oracle breakdown deliberately does NOT reuse
``Framework.run_filter_plugins``: that runner stops at the first failing
plugin (framework.go:530 semantics), which is correct for scheduling but
useless for attribution — a rejected node must report every plugin's
verdict so it can be diffed against the kernel's packed mask bits.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Sequence

from ..api import types as v1
from ..api.types import pod_key
from ..utils import knobs, serde

# explain score key (kernel/hoisted stack order) -> oracle plugin name.
# Must stay in lockstep with ops.hoisted.EXPLAIN_SCORE_KEYS and the score
# sections of ops.kernel.schedule_pod.
SCORE_PLUGIN_OF = {
    "balanced": "NodeResourcesBalancedAllocation",
    "image": "ImageLocality",
    "ipa": "InterPodAffinity",
    "least": "NodeResourcesLeastAllocated",
    "node_affinity": "NodeAffinity",
    "prefer_avoid": "NodePreferAvoidPods",
    "pts": "PodTopologySpread",
    "taint": "TaintToleration",
}

BUNDLE_DIR_ENV = "KTPU_SHADOW_BUNDLE_DIR"


def bundle_dir() -> str:
    import tempfile

    return knobs.get_str(BUNDLE_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), "ktpu-shadow-bundles"
    )


def _best(totals: Dict[str, int]) -> List[str]:
    if not totals:
        return []
    mx = max(totals.values())
    return [n for n, t in totals.items() if t == mx]


# ---------------------------------------------------------------------------
# oracle path


def oracle_breakdown(snapshot, pod: v1.Pod) -> Dict:
    """Replay the oracle filter/score chain read-only against ``snapshot``.

    Unlike a scheduling cycle, every filter plugin is run on every node
    (no first-failure short circuit) so rejected nodes carry full
    per-plugin verdicts; scoring then runs on the feasible set exactly as
    RunScorePlugins would (raw -> normalize -> x weight).
    """
    from .framework import interface as fwkif
    from .framework.interface import CycleState
    from .framework.runtime import Framework
    from .plugins.registry import default_plugins, new_in_tree_registry

    fwk = Framework(
        new_in_tree_registry(), plugins=default_plugins(), snapshot_fn=lambda: snapshot
    )
    state = CycleState()
    prefilter = fwk.run_pre_filter_plugins(state, pod)
    filters: Dict[str, Dict[str, bool]] = {}
    feasible: List[v1.Node] = []
    if prefilter is not None:
        # PreFilter rejected the pod outright: attribute every node to the
        # failing plugin rather than guessing per-filter verdicts.
        plugin = prefilter.failed_plugin or "PreFilter"
        for ni in snapshot.list():
            filters[ni.node.metadata.name] = {plugin: False}
    else:
        for ni in snapshot.list():
            verdicts: Dict[str, bool] = {}
            ok = True
            for pl in fwk.filter_plugins:
                passed = fwkif.is_success(pl.filter(state, pod, ni))
                verdicts[pl.name] = passed
                ok = ok and passed
            filters[ni.node.metadata.name] = verdicts
            if ok:
                feasible.append(ni.node)

    scores: Dict[str, Dict[str, int]] = {}
    totals: Dict[str, int] = {}
    if feasible:
        st = fwk.run_pre_score_plugins(state, pod, feasible)
        if st is not None:
            raise RuntimeError(f"PreScore failed during explain: {st}")
        scores_map, st = fwk.run_score_plugins(state, pod, feasible)
        if st is not None:
            raise RuntimeError(f"Score failed during explain: {st}")
        for plugin, node_scores in scores_map.items():
            scores[plugin] = {ns.name: int(ns.score) for ns in node_scores}
        for node in feasible:
            name = node.metadata.name
            totals[name] = sum(per_node[name] for per_node in scores.values())
    return {"filters": filters, "scores": scores, "totals": totals, "best": _best(totals)}


# ---------------------------------------------------------------------------
# device path


def device_breakdown(
    nodes: Sequence[v1.Node],
    pods: Sequence[v1.Pod],
    pod: v1.Pod,
    weights: Optional[Dict[str, int]] = None,
) -> Dict:
    """Run the fused kernel standalone (fresh encoding, one dispatch) and
    decode its per-plugin mask/score sections into a breakdown. This is the
    replay/triage path; the production harvest path decodes the session's
    explain payload instead (see ``payload_breakdown``)."""
    import numpy as np

    from ..models.encoding import ClusterEncoding
    from ..models.pod_encoder import PodEncoder
    # ktpu: allow-inert(read-only import: schedule_pod scores a copy for attribution, no state is written)
    from ..ops.kernel import schedule_pod
    # ktpu: allow-inert(read-only import: plugin mask table consulted, never mutated)
    from .tpu_backend import MASK_PLUGINS

    enc = ClusterEncoding()
    enc.set_cluster(list(nodes), list(pods))
    enc.device_state()  # build arrays FIRST: encode resolves tolerations
    # (and every other vocab lookup) against the built vocabularies
    pe = PodEncoder(enc)
    parrays = pe.encode(pod)
    cluster = enc.device_state()  # re-read: encode may grow vocab capacities
    out = {k: np.asarray(v) for k, v in schedule_pod(cluster, parrays, weights).items()}

    filters: Dict[str, Dict[str, bool]] = {}
    scores: Dict[str, Dict[str, int]] = {plugin: {} for plugin in SCORE_PLUGIN_OF.values()}
    totals: Dict[str, int] = {}
    decision = None
    decision_total = None
    for name, idx in enc.node_index.items():
        filters[name] = {plugin: bool(out[key][idx]) for key, plugin in MASK_PLUGINS}
        if bool(out["feasible"][idx]):
            for key, plugin in SCORE_PLUGIN_OF.items():
                scores[plugin][name] = int(out[f"score_{key}"][idx])
            total = int(out["total"][idx])
            totals[name] = total
            # first-max over encoding order: the device's own argmax convention
            if decision_total is None or total > decision_total:
                decision, decision_total = name, total
    return {
        "filters": filters,
        "scores": scores,
        "totals": totals,
        "best": _best(totals),
        "decision": decision,
    }


def payload_breakdown(payload: Dict, node_names: Sequence[str]) -> Dict:
    """Decode one pod's session explain payload (HoistedSession
    ``explain_payload`` entry: packed mask bits + top-k totals/score
    stacks) into the common breakdown shape. Scores cover only the top-k
    candidates — that is what the device shipped back."""
    # ktpu: allow-inert(read-only import: filter/score key tables consulted, never mutated)
    from ..ops.hoisted import EXPLAIN_FILTER_PLUGINS, EXPLAIN_SCORE_KEYS

    bits = payload["bits"]
    filters: Dict[str, Dict[str, bool]] = {}
    for i, name in enumerate(node_names):
        b = int(bits[i])
        filters[name] = {
            plugin: bool((b >> j) & 1) for j, plugin in enumerate(EXPLAIN_FILTER_PLUGINS)
        }
    scores: Dict[str, Dict[str, int]] = {
        SCORE_PLUGIN_OF[key]: {} for key in EXPLAIN_SCORE_KEYS
    }
    totals: Dict[str, int] = {}
    for j, idx in enumerate(payload["topk_idx"]):
        idx = int(idx)
        if idx < 0 or idx >= len(node_names):
            continue
        total = int(payload["topk_total"][j])
        if total < 0:  # padded/infeasible top-k slot
            continue
        name = node_names[idx]
        totals[name] = total
        for si, key in enumerate(EXPLAIN_SCORE_KEYS):
            scores[SCORE_PLUGIN_OF[key]][name] = int(payload["topk_scores"][j][si])
    return {"filters": filters, "scores": scores, "totals": totals, "best": _best(totals)}


# ---------------------------------------------------------------------------
# drift detection / diffing


def decision_drifts(oracle_bd: Dict, node: Optional[str]) -> bool:
    """True iff the device's chosen ``node`` disagrees with the oracle:
    infeasible under the oracle, or scored strictly below the oracle's
    max total (ties are fine — both sides break first-max over their own
    node order, which legitimately differs)."""
    if node is None:
        # device declined; oracle finding any feasible node is a drift
        return bool(oracle_bd["totals"])
    totals = oracle_bd["totals"]
    if node not in totals:
        return True
    return totals[node] != max(totals.values())


def drift_plugins(oracle_bd: Dict, device_bd: Optional[Dict], node: Optional[str]) -> List[str]:
    """Attribute a drift at ``node`` to plugins: filter verdicts that
    disagree there first, then weighted score components. Falls back to
    the catch-all ``decision`` label when no per-plugin signal survives
    (e.g. no device breakdown was captured)."""
    out: List[str] = []
    if device_bd is not None and node is not None:
        of = oracle_bd["filters"].get(node, {})
        df = device_bd["filters"].get(node, {})
        for plugin in sorted(set(of) & set(df)):
            if of[plugin] != df[plugin]:
                out.append(plugin)
        if not out:
            for plugin in sorted(set(oracle_bd["scores"]) | set(device_bd["scores"])):
                o = oracle_bd["scores"].get(plugin, {}).get(node)
                d = device_bd["scores"].get(plugin, {}).get(node)
                if o is not None and d is not None and o != d:
                    out.append(plugin)
    return out or ["decision"]


def attribution_diff(oracle_bd: Dict, device_bd: Dict) -> List[str]:
    """Bitwise per-plugin comparison on everything the device reported:
    filter verdicts on shared nodes and shared plugins (the oracle also
    runs volume plugins the device folds elsewhere — those are skipped),
    weighted scores on the device's top-k candidates. Returns the
    drifting plugin names, sorted; empty means clean. This is the check
    that catches a wrong weight or mask before it ever flips a decision."""
    out = set()
    for node, df in device_bd["filters"].items():
        of = oracle_bd["filters"].get(node)
        if of is None:
            continue
        for plugin, passed in df.items():
            if plugin in of and of[plugin] != passed:
                out.add(plugin)
    for plugin, per_node in device_bd["scores"].items():
        for node, score in per_node.items():
            oracle_score = oracle_bd["scores"].get(plugin, {}).get(node)
            if oracle_score is not None and oracle_score != score:
                out.add(plugin)
    return sorted(out)


def diff_table(oracle_bd: Dict, device_bd: Dict, node: str) -> str:
    """Per-plugin oracle-vs-device table at ``node`` for CLI output."""
    lines = [f"{'plugin':<40} {'oracle':>10} {'device':>10}  drift"]
    of = oracle_bd["filters"].get(node, {})
    df = device_bd["filters"].get(node, {})
    for plugin in sorted(set(of) | set(df)):
        o, d = of.get(plugin), df.get(plugin)
        mark = "  <--" if (o is not None and d is not None and o != d) else ""
        lines.append(
            f"{plugin:<40} {_verdict(o):>10} {_verdict(d):>10}{mark}"
        )
    for plugin in sorted(set(oracle_bd["scores"]) | set(device_bd["scores"])):
        o = oracle_bd["scores"].get(plugin, {}).get(node)
        d = device_bd["scores"].get(plugin, {}).get(node)
        mark = "  <--" if (o is not None and d is not None and o != d) else ""
        lines.append(
            f"{plugin + ' (score)':<40} {_num(o):>10} {_num(d):>10}{mark}"
        )
    ot = oracle_bd["totals"].get(node)
    dt = device_bd["totals"].get(node)
    mark = "  <--" if (ot is not None and dt is not None and ot != dt) else ""
    lines.append(f"{'total':<40} {_num(ot):>10} {_num(dt):>10}{mark}")
    return "\n".join(lines)


def _verdict(v) -> str:
    return "-" if v is None else ("pass" if v else "FAIL")


def _num(v) -> str:
    return "-" if v is None else str(v)


# ---------------------------------------------------------------------------
# rendering


def render_decision(bd: Dict, pod_name: str, node: Optional[str] = None, top: int = 3) -> str:
    """Render a breakdown the way the oracle scheduler would log the
    decision: feasibility summary, who rejected each infeasible node, and
    the per-plugin score split of the winner vs runners-up."""
    node = node or (bd["best"][0] if bd["best"] else None)
    lines = []
    n_total = len(bd["filters"])
    n_feasible = sum(1 for v in bd["filters"].values() if all(v.values()))
    if node is None:
        lines.append(f'pod "{pod_name}": unschedulable ({n_total} nodes, 0 feasible)')
    else:
        total = bd["totals"].get(node)
        lines.append(
            f'pod "{pod_name}": scheduled on "{node}" '
            f"(total {total}, {n_feasible}/{n_total} nodes feasible)"
        )
    rejected = {
        name: [plugin for plugin, ok in verdicts.items() if not ok]
        for name, verdicts in sorted(bd["filters"].items())
        if not all(verdicts.values())
    }
    if rejected:
        lines.append("  filtered:")
        for name, plugins in rejected.items():
            lines.append(f"    {name}: rejected by {', '.join(plugins)}")
    ranked = sorted(bd["totals"].items(), key=lambda kv: (-kv[1], kv[0]))[: max(top, 1)]
    if ranked:
        names = [name for name, _ in ranked]
        header = f"  scores ({' vs '.join(names)}):"
        lines.append(header)
        for plugin in sorted(bd["scores"]):
            row = [bd["scores"][plugin].get(name) for name in names]
            if not any(r is not None for r in row):
                continue
            cells = " ".join(f"{_num(r):>8}" for r in row)
            lines.append(f"    {plugin:<40} {cells}")
        cells = " ".join(f"{total:>8}" for _, total in ranked)
        lines.append(f"    {'total':<40} {cells}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# repro bundles


def write_bundle(
    pod: v1.Pod,
    nodes: Sequence[v1.Node],
    cluster_pods: Sequence[v1.Pod],
    node: Optional[str],
    plugins: Sequence[str],
    oracle_bd: Dict,
    device_bd: Optional[Dict] = None,
    weights: Optional[Dict[str, int]] = None,
    dir_path: Optional[str] = None,
) -> str:
    """Freeze a sentinel mismatch as a self-contained JSON bundle: the
    decision-time cluster objects (serde round-trippable), the pod, the
    device decision, and both per-plugin breakdowns. replay_drift.py
    re-runs it from scratch."""
    dir_path = dir_path or bundle_dir()
    os.makedirs(dir_path, exist_ok=True)
    payload = {
        "version": 1,
        "podKey": pod_key(pod),
        "node": node,
        "plugins": list(plugins),
        "weights": dict(weights) if weights else None,
        "pod": serde.to_dict(pod),
        "nodes": [serde.to_dict(n) for n in nodes],
        "clusterPods": [serde.to_dict(p) for p in cluster_pods],
        "oracle": oracle_bd,
        "device": device_bd,
    }
    slug = re.sub(r"[^A-Za-z0-9_.-]", "-", pod_key(pod))
    path = os.path.join(dir_path, f"shadow-drift-{slug}-{int(time.time() * 1e6):x}.json")
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    return path


def load_bundle(path: str) -> Dict:
    with open(path) as f:
        raw = json.load(f)
    raw["pod"] = serde.from_dict(v1.Pod, raw["pod"])
    raw["nodes"] = [serde.from_dict(v1.Node, n) for n in raw["nodes"]]
    raw["clusterPods"] = [serde.from_dict(v1.Pod, p) for p in raw["clusterPods"]]
    return raw
