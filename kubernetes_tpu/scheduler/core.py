"""Core scheduling algorithm: snapshot -> prefilter -> filter -> score -> select.

Reference: pkg/scheduler/core/generic_scheduler.go —
  Schedule (:95), findNodesThatFitPod (:201), findNodesThatPassFilters
  (:235) with the adaptive numFeasibleNodesToFind (:177: 50% - nodes/125,
  floor 5%, min 100) and the rotating nextStartNodeIndex, prioritizeNodes
  (:342), selectHost (:152, reservoir sampling across max-score ties).

This CPU path is the semantic oracle. The TPU path (ops/, parallel/)
replaces findNodesThatPassFilters + RunScorePlugins with one XLA dispatch
over all nodes — no subsampling — and must produce identical decisions when
percentageOfNodesToScore=100.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ..api.types import Node, Pod
from .framework.interface import CycleState, FitError, NodeScore, Status
from .framework.runtime import Framework
from .framework.snapshot import Snapshot

MIN_FEASIBLE_NODES_TO_FIND = 100  # generic_scheduler.go:45
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # generic_scheduler.go:50


class ScheduleResult:
    __slots__ = ("suggested_host", "evaluated_nodes", "feasible_nodes")

    def __init__(self, suggested_host: str, evaluated_nodes: int, feasible_nodes: int):
        self.suggested_host = suggested_host
        self.evaluated_nodes = evaluated_nodes
        self.feasible_nodes = feasible_nodes


class GenericScheduler:
    def __init__(
        self,
        percentage_of_nodes_to_score: int = 0,
        extenders: Optional[list] = None,
        rng: Optional[random.Random] = None,
    ):
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.extenders = extenders or []
        self.next_start_node_index = 0
        self.rng = rng or random.Random()

    # -- entry point (generic_scheduler.go:95 Schedule) --------------------
    def schedule(
        self,
        state: CycleState,
        fwk: Framework,
        pod: Pod,
        snapshot: Snapshot,
        nominator=None,
    ) -> ScheduleResult:
        if snapshot.num_nodes() == 0:
            raise FitError(pod, 0, {})
        feasible_nodes, filtered_statuses = self.find_nodes_that_fit_pod(
            state, fwk, pod, snapshot, nominator
        )
        if not feasible_nodes:
            raise FitError(pod, snapshot.num_nodes(), filtered_statuses)
        if len(feasible_nodes) == 1:
            return ScheduleResult(
                feasible_nodes[0].metadata.name,
                1 + len(filtered_statuses),
                1,
            )
        priority_list = self.prioritize_nodes(state, fwk, pod, feasible_nodes)
        host = self.select_host(priority_list)
        return ScheduleResult(
            host, len(feasible_nodes) + len(filtered_statuses), len(feasible_nodes)
        )

    # -- filtering ---------------------------------------------------------
    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        """generic_scheduler.go:177 adaptive subsampling."""
        if (
            num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND
            or self.percentage_of_nodes_to_score >= 100
        ):
            return num_all_nodes
        adaptive = self.percentage_of_nodes_to_score
        if adaptive <= 0:
            adaptive = 50 - num_all_nodes // 125
            if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
                adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
        num_nodes = num_all_nodes * adaptive // 100
        return max(num_nodes, MIN_FEASIBLE_NODES_TO_FIND)

    def find_nodes_that_fit_pod(
        self, state: CycleState, fwk: Framework, pod: Pod, snapshot: Snapshot, nominator
    ) -> Tuple[List[Node], Dict[str, Status]]:
        """generic_scheduler.go:201 findNodesThatFitPod."""
        filtered_statuses: Dict[str, Status] = {}
        status = fwk.run_pre_filter_plugins(state, pod)
        if status is not None and not status.is_success():
            if status.is_unschedulable():
                # all nodes share the prefilter rejection (:215)
                for ni in snapshot.list():
                    filtered_statuses[ni.node.metadata.name] = status
                raise FitError(pod, snapshot.num_nodes(), filtered_statuses)
            raise RuntimeError(f"prefilter error: {status.message()}")
        feasible = self._find_nodes_that_pass_filters(
            state, fwk, pod, snapshot, filtered_statuses, nominator
        )
        feasible = self._find_nodes_that_pass_extenders(pod, feasible, filtered_statuses)
        return feasible, filtered_statuses

    def _find_nodes_that_pass_filters(
        self, state, fwk, pod, snapshot, filtered_statuses, nominator
    ) -> List[Node]:
        """generic_scheduler.go:235: rotate start index; stop at numNodesToFind."""
        all_nodes = snapshot.list()
        num_all = len(all_nodes)
        num_to_find = self.num_feasible_nodes_to_find(num_all)
        feasible: List[Node] = []
        if not fwk.has_filter_plugins():
            start = self.next_start_node_index
            for i in range(num_to_find):
                feasible.append(all_nodes[(start + i) % num_all].node)
            self.next_start_node_index = (start + num_to_find) % num_all
            return feasible
        processed = 0
        for i in range(num_all):
            node_info = all_nodes[(self.next_start_node_index + i) % num_all]
            processed += 1
            status = fwk.run_filter_plugins_with_nominated_pods(
                state, pod, node_info, nominator
            )
            if status is None:
                feasible.append(node_info.node)
                if len(feasible) >= num_to_find:
                    break
            else:
                if not status.is_unschedulable():
                    raise RuntimeError(f"filter error: {status.message()}")
                filtered_statuses[node_info.node.metadata.name] = status
        self.next_start_node_index = (self.next_start_node_index + processed) % num_all
        return feasible

    def _find_nodes_that_pass_extenders(
        self, pod: Pod, feasible: List[Node], filtered_statuses: Dict[str, Status]
    ) -> List[Node]:
        """generic_scheduler.go:307 — HTTP extender Filter round-trips."""
        for extender in self.extenders:
            if not feasible:
                break
            if not extender.is_interested(pod):
                continue
            feasible, failed = extender.filter(pod, feasible)
            for name, reason in failed.items():
                filtered_statuses[name] = Status.unschedulable(
                    f"FailedExtenderFilter: {reason}"
                )
        return feasible

    # -- scoring -----------------------------------------------------------
    def prioritize_nodes(
        self, state: CycleState, fwk: Framework, pod: Pod, nodes: List[Node]
    ) -> List[NodeScore]:
        """generic_scheduler.go:342 prioritizeNodes."""
        if not self.extenders and not fwk.has_score_plugins():
            return [NodeScore(n.metadata.name, 1) for n in nodes]
        status = fwk.run_pre_score_plugins(state, pod, nodes)
        if status is not None and not status.is_success():
            raise RuntimeError(f"prescore error: {status.message()}")
        scores_map, status = fwk.run_score_plugins(state, pod, nodes)
        if status is not None and not status.is_success():
            raise RuntimeError(f"score error: {status.message()}")
        result = [NodeScore(n.metadata.name, 0) for n in nodes]
        for i in range(len(nodes)):
            for plugin_scores in scores_map.values():
                result[i].score += plugin_scores[i].score
        if self.extenders:
            combined: Dict[str, int] = {ns.name: 0 for ns in result}
            for extender in self.extenders:
                if not extender.is_interested(pod):
                    continue
                prioritized, weight = extender.prioritize(pod, nodes)
                for host_priority in prioritized:
                    combined[host_priority["host"]] += host_priority["score"] * weight
            for ns in result:
                ns.score += combined[ns.name]
        return result

    def select_host(self, node_score_list: List[NodeScore]) -> str:
        """generic_scheduler.go:152 selectHost — reservoir sampling over ties."""
        if not node_score_list:
            raise ValueError("empty priorityList")
        max_score = node_score_list[0].score
        selected = node_score_list[0].name
        cnt_of_max = 1
        for ns in node_score_list[1:]:
            if ns.score > max_score:
                max_score = ns.score
                selected = ns.name
                cnt_of_max = 1
            elif ns.score == max_score:
                cnt_of_max += 1
                if self.rng.randrange(cnt_of_max) == 0:
                    selected = ns.name
        return selected
