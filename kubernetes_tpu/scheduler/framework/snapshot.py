"""Scheduler cache snapshot: immutable view of cluster state for one cycle.

Reference: pkg/scheduler/internal/cache/snapshot.go:29 Snapshot — the node
list plus the two secondary lists (HavePodsWithAffinity,
HavePodsWithRequiredAntiAffinity) that let InterPodAffinity skip nodes, and
the cluster-wide image state index used by ImageLocality.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ...api import types as v1
from .types import ImageStateSummary, NodeInfo


class Snapshot:
    def __init__(self, node_infos: Optional[List[NodeInfo]] = None):
        self.node_info_list: List[NodeInfo] = node_infos or []
        self.node_info_map: Dict[str, NodeInfo] = {
            ni.node.metadata.name: ni for ni in self.node_info_list if ni.node
        }
        self.have_pods_with_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_affinity
        ]
        self.have_pods_with_required_anti_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_required_anti_affinity
        ]
        self.generation = 0

    @classmethod
    def from_objects(cls, pods: List[v1.Pod], nodes: List[v1.Node]) -> "Snapshot":
        """snapshot.go:48 NewSnapshot: build NodeInfos from raw objects and
        populate per-node ImageStates with cluster-wide spread counts."""
        by_node: Dict[str, NodeInfo] = {}
        for node in nodes:
            ni = NodeInfo()
            ni.set_node(node)
            by_node[node.metadata.name] = ni
        for pod in pods:
            name = pod.spec.node_name
            if name in by_node:
                by_node[name].add_pod(pod)
        # image spread index (snapshot.go createImageExistenceMap)
        image_nodes: Dict[str, set] = {}
        for node in nodes:
            for image in node.status.images or []:
                for n in image.names or []:
                    image_nodes.setdefault(n, set()).add(node.metadata.name)
        for node in nodes:
            ni = by_node[node.metadata.name]
            states: Dict[str, ImageStateSummary] = {}
            for image in node.status.images or []:
                for n in image.names or []:
                    states[n] = ImageStateSummary(image.size_bytes, len(image_nodes[n]))
            ni.image_states = states
        return cls([by_node[n.metadata.name] for n in nodes])

    # NodeInfos lister surface (snapshot.go:139-166)
    def list(self) -> List[NodeInfo]:
        return self.node_info_list

    def get(self, node_name: str) -> NodeInfo:
        ni = self.node_info_map.get(node_name)
        if ni is None:
            raise KeyError(f"nodeinfo not found for node name {node_name!r}")
        return ni

    def num_nodes(self) -> int:
        return len(self.node_info_list)
