"""Scheduler-internal representations of cluster state.

Reimplements framework types (reference: pkg/scheduler/framework/types.go):
Resource (int64 milli-units, :318), NodeInfo (:224) with the secondary
affinity lists and generation counter the incremental snapshot depends on,
PodInfo (:72) with pre-parsed affinity terms, QueuedPodInfo (:45), and
HostPortInfo (:608) conflict semantics.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional, Set, Tuple

from ...api import types as v1
from ...api.labels import Selector
from ...api.quantity import milli_value_of, value_of

# Non-zero request defaults (reference: pkg/scheduler/util/non_zero.go:33-38)
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

_generation = itertools.count(1)


def next_generation() -> int:
    """Monotonic generation for incremental snapshots (types.go:38)."""
    return next(_generation)


def is_scalar_resource_name(name: str) -> bool:
    """v1helper.IsScalarResourceName: extended, hugepages, attachable-volumes.

    Native resources are unprefixed or under *kubernetes.io/; everything else
    with a domain is an extended resource.
    """
    if name.startswith("hugepages-") or name.startswith("attachable-volumes-"):
        return True
    if "/" in name:
        domain = name.split("/", 1)[0]
        return not (domain == "kubernetes.io" or domain.endswith(".kubernetes.io"))
    return False


class Resource:
    """framework.Resource (types.go:318): int64 milli-CPU, bytes, scalars."""

    __slots__ = ("milli_cpu", "memory", "ephemeral_storage", "allowed_pod_number", "scalar_resources")

    def __init__(self):
        self.milli_cpu = 0
        self.memory = 0
        self.ephemeral_storage = 0
        self.allowed_pod_number = 0
        self.scalar_resources: Dict[str, int] = {}

    def add(self, resource_list: Optional[Dict[str, str]]) -> None:
        """Resource.Add (types.go:345)."""
        for name, q in (resource_list or {}).items():
            if name == v1.RESOURCE_CPU:
                self.milli_cpu += milli_value_of(q)
            elif name == v1.RESOURCE_MEMORY:
                self.memory += value_of(q)
            elif name == v1.RESOURCE_PODS:
                self.allowed_pod_number += value_of(q)
            elif name == v1.RESOURCE_EPHEMERAL_STORAGE:
                self.ephemeral_storage += value_of(q)
            elif is_scalar_resource_name(name):
                self.scalar_resources[name] = (
                    self.scalar_resources.get(name, 0) + value_of(q)
                )

    def set_max(self, resource_list: Optional[Dict[str, str]]) -> None:
        """Resource.SetMaxResource (types.go:393) — per-dimension max."""
        for name, q in (resource_list or {}).items():
            if name == v1.RESOURCE_CPU:
                self.milli_cpu = max(self.milli_cpu, milli_value_of(q))
            elif name == v1.RESOURCE_MEMORY:
                self.memory = max(self.memory, value_of(q))
            elif name == v1.RESOURCE_EPHEMERAL_STORAGE:
                self.ephemeral_storage = max(self.ephemeral_storage, value_of(q))
            elif is_scalar_resource_name(name):
                self.scalar_resources[name] = max(
                    self.scalar_resources.get(name, 0), value_of(q)
                )

    def clone(self) -> "Resource":
        r = Resource()
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.ephemeral_storage = self.ephemeral_storage
        r.allowed_pod_number = self.allowed_pod_number
        r.scalar_resources = dict(self.scalar_resources)
        return r

    def __repr__(self):
        return (
            f"Resource(cpu={self.milli_cpu}m, mem={self.memory}, "
            f"eph={self.ephemeral_storage}, pods={self.allowed_pod_number}, "
            f"scalar={self.scalar_resources})"
        )


def _nonzero_requests(requests: Optional[Dict[str, str]]) -> Tuple[int, int]:
    """GetNonzeroRequests (util/non_zero.go:42): defaults for unset cpu/mem."""
    requests = requests or {}
    if v1.RESOURCE_CPU in requests:
        cpu = milli_value_of(requests[v1.RESOURCE_CPU])
    else:
        cpu = DEFAULT_MILLI_CPU_REQUEST
    if v1.RESOURCE_MEMORY in requests:
        mem = value_of(requests[v1.RESOURCE_MEMORY])
    else:
        mem = DEFAULT_MEMORY_REQUEST
    return cpu, mem


def calculate_resource(pod: v1.Pod) -> Tuple[Resource, int, int]:
    """types.go:671 calculateResource: pod request = sum(containers) maxed
    with each initContainer, plus overhead; plus the NonZero cpu/mem pair."""
    res = Resource()
    non0_cpu = 0
    non0_mem = 0
    for c in pod.spec.containers:
        res.add(c.resources.requests)
        cpu, mem = _nonzero_requests(c.resources.requests)
        non0_cpu += cpu
        non0_mem += mem
    for ic in pod.spec.init_containers or []:
        res.set_max(ic.resources.requests)
        cpu, mem = _nonzero_requests(ic.resources.requests)
        non0_cpu = max(non0_cpu, cpu)
        non0_mem = max(non0_mem, mem)
    if pod.spec.overhead:
        res.add(pod.spec.overhead)
        if v1.RESOURCE_CPU in pod.spec.overhead:
            non0_cpu += milli_value_of(pod.spec.overhead[v1.RESOURCE_CPU])
        if v1.RESOURCE_MEMORY in pod.spec.overhead:
            non0_mem += value_of(pod.spec.overhead[v1.RESOURCE_MEMORY])
    return res, non0_cpu, non0_mem


# ---------------------------------------------------------------------------
# Affinity terms (types.go:60-70, :136-216)


class AffinityTerm:
    """Pre-parsed PodAffinityTerm: namespaces set + compiled selector."""

    __slots__ = ("namespaces", "selector", "topology_key")

    def __init__(self, namespaces: Set[str], selector: Selector, topology_key: str):
        self.namespaces = namespaces
        self.selector = selector
        self.topology_key = topology_key

    def matches(self, pod: v1.Pod) -> bool:
        """PodMatchesTermsNamespaceAndSelector (util/topologies.go:40)."""
        if pod.metadata.namespace not in self.namespaces:
            return False
        return self.selector.matches(pod.metadata.labels)


class WeightedAffinityTerm(AffinityTerm):
    __slots__ = ("weight",)

    def __init__(self, namespaces, selector, topology_key, weight: int):
        super().__init__(namespaces, selector, topology_key)
        self.weight = weight


def _term_namespaces(pod: v1.Pod, term: v1.PodAffinityTerm) -> Set[str]:
    """util/topologies.go:28 getNamespacesFromPodAffinityTerm: empty list
    means the pod's own namespace."""
    if term.namespaces:
        return set(term.namespaces)
    return {pod.metadata.namespace}


def _parse_terms(pod: v1.Pod, terms: Optional[List[v1.PodAffinityTerm]]) -> List[AffinityTerm]:
    out = []
    for t in terms or []:
        out.append(
            AffinityTerm(
                _term_namespaces(pod, t),
                Selector.from_label_selector(t.label_selector),
                t.topology_key,
            )
        )
    return out


def _parse_weighted_terms(
    pod: v1.Pod, terms: Optional[List[v1.WeightedPodAffinityTerm]]
) -> List[WeightedAffinityTerm]:
    out = []
    for wt in terms or []:
        t = wt.pod_affinity_term
        out.append(
            WeightedAffinityTerm(
                _term_namespaces(pod, t),
                Selector.from_label_selector(t.label_selector),
                t.topology_key,
                wt.weight,
            )
        )
    return out


class PodInfo:
    """Pod plus pre-parsed affinity terms (types.go:72 PodInfo)."""

    __slots__ = (
        "pod",
        "required_affinity_terms",
        "required_anti_affinity_terms",
        "preferred_affinity_terms",
        "preferred_anti_affinity_terms",
    )

    def __init__(self, pod: v1.Pod):
        self.pod = pod
        affinity = pod.spec.affinity
        pa = affinity.pod_affinity if affinity else None
        paa = affinity.pod_anti_affinity if affinity else None
        self.required_affinity_terms = _parse_terms(
            pod, pa.required_during_scheduling_ignored_during_execution if pa else None
        )
        self.required_anti_affinity_terms = _parse_terms(
            pod, paa.required_during_scheduling_ignored_during_execution if paa else None
        )
        self.preferred_affinity_terms = _parse_weighted_terms(
            pod, pa.preferred_during_scheduling_ignored_during_execution if pa else None
        )
        self.preferred_anti_affinity_terms = _parse_weighted_terms(
            pod, paa.preferred_during_scheduling_ignored_during_execution if paa else None
        )


class QueuedPodInfo:
    """PodInfo + queueing bookkeeping (types.go:45)."""

    __slots__ = (
        "pod_info",
        "timestamp",
        "attempts",
        "initial_attempt_timestamp",
        "last_failure_timestamp",
        "pop_timestamp",
        "nominated_node",
    )

    def __init__(self, pod: v1.Pod, timestamp: Optional[float] = None):
        self.pod_info = PodInfo(pod)
        self.timestamp = timestamp if timestamp is not None else time.monotonic()
        self.attempts = 0
        self.initial_attempt_timestamp = self.timestamp
        self.last_failure_timestamp = 0.0
        # stamped by the scheduler at queue pop; bind-sent minus this is
        # the per-attempt latency (pod_scheduling_duration measures from
        # initial_attempt_timestamp, i.e. includes queue wait)
        self.pop_timestamp = 0.0
        # set when this pod preempted victims on a node: the in-memory
        # mirror of status.nominatedNodeName (the API echo can lag the
        # victims' delete events; the queue's event-driven re-admission
        # and the scheduler's nominated-node short-circuit read this)
        self.nominated_node = ""

    @property
    def pod(self) -> v1.Pod:
        return self.pod_info.pod

    @pod.setter
    def pod(self, pod: v1.Pod) -> None:
        self.pod_info = PodInfo(pod)


# ---------------------------------------------------------------------------
# Host ports (types.go:608 HostPortInfo)

DEFAULT_BIND_ALL_HOST_IP = "0.0.0.0"


class HostPortInfo:
    """map[ip]set[(protocol, port)] with 0.0.0.0 wildcard conflicts."""

    __slots__ = ("ports",)

    def __init__(self):
        self.ports: Dict[str, Set[Tuple[str, int]]] = {}

    @staticmethod
    def _sanitize(ip: str, protocol: str) -> Tuple[str, str]:
        return ip or DEFAULT_BIND_ALL_HOST_IP, protocol or "TCP"

    def add(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        self.ports.setdefault(ip, set()).add((protocol, port))

    def remove(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        s = self.ports.get(ip)
        if s is not None:
            s.discard((protocol, port))
            if not s:
                del self.ports[ip]

    def check_conflict(self, ip: str, protocol: str, port: int) -> bool:
        if port <= 0:
            return False
        ip, protocol = self._sanitize(ip, protocol)
        key = (protocol, port)
        if ip == DEFAULT_BIND_ALL_HOST_IP:
            return any(key in s for s in self.ports.values())
        return key in self.ports.get(DEFAULT_BIND_ALL_HOST_IP, set()) or key in self.ports.get(ip, set())

    def clone(self) -> "HostPortInfo":
        h = HostPortInfo()
        h.ports = {ip: set(s) for ip, s in self.ports.items()}
        return h

    def __len__(self):
        return sum(len(s) for s in self.ports.values())


class ImageStateSummary:
    """types.go:205 ImageStateSummary: size + cluster spread."""

    __slots__ = ("size", "num_nodes")

    def __init__(self, size: int, num_nodes: int):
        self.size = size
        self.num_nodes = num_nodes


class NodeInfo:
    """Aggregated per-node scheduling state (types.go:224 NodeInfo)."""

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "used_ports",
        "requested",
        "non_zero_requested",
        "allocatable",
        "image_states",
        "generation",
    )

    def __init__(self, *pods: v1.Pod):
        self.node: Optional[v1.Node] = None
        self.pods: List[PodInfo] = []
        self.pods_with_affinity: List[PodInfo] = []
        self.pods_with_required_anti_affinity: List[PodInfo] = []
        self.used_ports = HostPortInfo()
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        self.image_states: Dict[str, ImageStateSummary] = {}
        self.generation = next_generation()
        for p in pods:
            self.add_pod(p)

    def set_node(self, node: v1.Node) -> None:
        """types.go:553 SetNode: ingest allocatable."""
        self.node = node
        alloc = Resource()
        alloc.add(node.status.allocatable or node.status.capacity)
        self.allocatable = alloc
        self.generation = next_generation()

    def add_pod(self, pod: v1.Pod) -> None:
        """types.go:489 AddPod."""
        self.add_pod_info(PodInfo(pod))

    def add_pod_info(self, pod_info: PodInfo, res3=None) -> None:
        """Shares an already-parsed PodInfo (the reference's AddPod path).
        `res3` optionally carries a precomputed calculate_resource(pod)
        triple so batch callers (SchedulerCache.assume_pods) parse each
        pod's Quantity strings exactly once."""
        pod = pod_info.pod
        res, non0_cpu, non0_mem = res3 if res3 is not None \
            else calculate_resource(pod)
        self.requested.milli_cpu += res.milli_cpu
        self.requested.memory += res.memory
        self.requested.ephemeral_storage += res.ephemeral_storage
        for name, val in res.scalar_resources.items():
            self.requested.scalar_resources[name] = (
                self.requested.scalar_resources.get(name, 0) + val
            )
        self.non_zero_requested.milli_cpu += non0_cpu
        self.non_zero_requested.memory += non0_mem
        self.pods.append(pod_info)
        if _pod_with_affinity(pod):
            self.pods_with_affinity.append(pod_info)
        if _pod_with_required_anti_affinity(pod):
            self.pods_with_required_anti_affinity.append(pod_info)
        self._update_used_ports(pod, add=True)
        self.generation = next_generation()

    def remove_pod(self, pod: v1.Pod, res3=None) -> None:
        """types.go:517 RemovePod. `res3` optionally carries a
        precomputed calculate_resource(pod) triple (see add_pod_info)."""
        key = v1.pod_key(pod)

        def _strip(lst: List[PodInfo]) -> None:
            for i, pi in enumerate(lst):
                if v1.pod_key(pi.pod) == key:
                    lst[i] = lst[-1]
                    lst.pop()
                    return

        _strip(self.pods_with_affinity)
        _strip(self.pods_with_required_anti_affinity)
        for i, pi in enumerate(self.pods):
            if v1.pod_key(pi.pod) == key:
                self.pods[i] = self.pods[-1]
                self.pods.pop()
                res, non0_cpu, non0_mem = res3 if res3 is not None \
                    else calculate_resource(pod)
                self.requested.milli_cpu -= res.milli_cpu
                self.requested.memory -= res.memory
                self.requested.ephemeral_storage -= res.ephemeral_storage
                for name, val in res.scalar_resources.items():
                    self.requested.scalar_resources[name] = (
                        self.requested.scalar_resources.get(name, 0) - val
                    )
                self.non_zero_requested.milli_cpu -= non0_cpu
                self.non_zero_requested.memory -= non0_mem
                self._update_used_ports(pod, add=False)
                self.generation = next_generation()
                return
        raise KeyError(f"no corresponding pod {key} in pods of node")

    def _update_used_ports(self, pod: v1.Pod, add: bool) -> None:
        for container in pod.spec.containers:
            for port in container.ports or []:
                if add:
                    self.used_ports.add(port.host_ip, port.protocol, port.host_port)
                else:
                    self.used_ports.remove(port.host_ip, port.protocol, port.host_port)

    def clone(self) -> "NodeInfo":
        """types.go:445 Clone — shares immutable PodInfos, copies aggregates."""
        c = NodeInfo()
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        c.used_ports = self.used_ports.clone()
        c.requested = self.requested.clone()
        c.non_zero_requested = self.non_zero_requested.clone()
        c.allocatable = self.allocatable.clone()
        c.image_states = dict(self.image_states)
        c.generation = self.generation
        return c


def _pod_with_affinity(pod: v1.Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)


def _pod_with_required_anti_affinity(pod: v1.Pod) -> bool:
    a = pod.spec.affinity
    return (
        a is not None
        and a.pod_anti_affinity is not None
        and bool(a.pod_anti_affinity.required_during_scheduling_ignored_during_execution)
    )
