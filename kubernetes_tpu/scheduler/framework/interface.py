"""Scheduling Framework plugin API.

Reimplements the extension-point contract of the reference's Scheduling
Framework (reference: pkg/scheduler/framework/interface.go):

  QueueSort, PreFilter (+ AddPod/RemovePod extensions), Filter, PostFilter,
  PreScore, Score (+ NormalizeScore), Reserve, Permit, PreBind, Bind, PostBind

Status codes preserve the Unschedulable vs UnschedulableAndUnresolvable
distinction (interface.go:74-93) that preemption relies on; scores are int64
in [MIN_NODE_SCORE, MAX_NODE_SCORE] (interface.go:95-103).
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence, Tuple

from ...api.types import Node, Pod

MAX_NODE_SCORE = 100  # interface.go:95 MaxNodeScore
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1  # interface.go:101 MaxTotalScore (math.MaxInt64)


class Code(enum.IntEnum):
    """Status codes (interface.go:36-70)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


class Status:
    """Result of running a plugin (interface.go:106). None == Success."""

    __slots__ = ("code", "reasons", "failed_plugin")

    def __init__(self, code: Code = Code.SUCCESS, reasons: Optional[List[str]] = None):
        self.code = code
        self.reasons = reasons or []
        self.failed_plugin = ""

    @classmethod
    def success(cls) -> Optional["Status"]:
        return None

    @classmethod
    def unschedulable(cls, *reasons: str) -> "Status":
        return cls(Code.UNSCHEDULABLE, list(reasons))

    @classmethod
    def unschedulable_and_unresolvable(cls, *reasons: str) -> "Status":
        return cls(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, list(reasons))

    @classmethod
    def error(cls, *reasons: str) -> "Status":
        return cls(Code.ERROR, list(reasons))

    @classmethod
    def wait(cls, *reasons: str) -> "Status":
        return cls(Code.WAIT, list(reasons))

    @classmethod
    def skip(cls) -> "Status":
        return cls(Code.SKIP)

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_unschedulable(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return ", ".join(self.reasons)

    def __repr__(self) -> str:
        return f"Status({self.code.name}, {self.reasons})"


def is_success(status: Optional[Status]) -> bool:
    return status is None or status.is_success()


class CycleState:
    """Per-scheduling-cycle key/value store plugins use to pass state between
    extension points (reference: pkg/scheduler/framework/cycle_state.go)."""

    __slots__ = ("data", "record_plugin_metrics", "skip_filter_plugins", "skip_score_plugins")

    def __init__(self):
        self.data: Dict[str, object] = {}
        self.record_plugin_metrics = False
        self.skip_filter_plugins: set = set()
        self.skip_score_plugins: set = set()

    def read(self, key: str):
        if key not in self.data:
            raise KeyError(f"{key} is not found in CycleState")
        return self.data[key]

    def write(self, key: str, value) -> None:
        self.data[key] = value

    def delete(self, key: str) -> None:
        self.data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        # StateData.Clone: our state objects are treated as immutable once
        # written except where a plugin's Clone() deep-copies (preemption).
        for k, v in self.data.items():
            clone = getattr(v, "clone", None)
            c.data[k] = clone() if callable(clone) else v
        return c


# ---------------------------------------------------------------------------
# Plugin interfaces. Python duck-typing replaces Go interface assertions: a
# plugin participates in an extension point iff it defines the method.


class Plugin:
    name: str = ""


class QueueSortPlugin(Plugin):
    def less(self, pod_info1, pod_info2) -> bool:  # QueuedPodInfo
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod: Pod) -> Optional[Status]:
        raise NotImplementedError

    # PreFilterExtensions (interface.go:243): return self to opt in.
    def pre_filter_extensions(self) -> Optional["PreFilterPlugin"]:
        return None

    def add_pod(self, state, pod_to_schedule, pod_info_to_add, node_info) -> Optional[Status]:
        return None

    def remove_pod(self, state, pod_to_schedule, pod_info_to_remove, node_info) -> Optional[Status]:
        return None


class FilterPlugin(Plugin):
    def filter(self, state: CycleState, pod: Pod, node_info) -> Optional[Status]:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod: Pod, filtered_node_status_map) -> Tuple[Optional[object], Optional[Status]]:
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pod: Pod, nodes: Sequence[Node]) -> Optional[Status]:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        raise NotImplementedError

    # ScoreExtensions: normalize_score presence opts in.
    def normalize_score(self, state: CycleState, pod: Pod, scores: List["NodeScore"]) -> Optional[Status]:
        return None

    has_normalize = False


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        pass


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Optional[Status], float]:
        """Returns (status, timeout_seconds). Wait status parks the pod."""
        raise NotImplementedError


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        raise NotImplementedError


class NodeScore:
    __slots__ = ("name", "score")

    def __init__(self, name: str, score: int):
        self.name = name
        self.score = score

    def __repr__(self):
        return f"NodeScore({self.name}={self.score})"


class FitError(Exception):
    """Scheduling failure with per-node statuses (framework/types.go:95)."""

    def __init__(self, pod: Pod, num_all_nodes: int, filtered_nodes_statuses: Dict[str, Status]):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.filtered_nodes_statuses = filtered_nodes_statuses
        super().__init__(
            f"0/{num_all_nodes} nodes are available for pod "
            f"{pod.metadata.namespace}/{pod.metadata.name}"
        )
