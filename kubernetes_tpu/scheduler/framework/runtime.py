"""Framework runtime: instantiates plugins from a profile and runs the
extension points.

Reference: pkg/scheduler/framework/runtime/framework.go — NewFramework
(:238), RunPreFilterPlugins (:426), RunFilterPlugins (:530),
RunFilterPluginsWithNominatedPods (:610), RunPreScorePlugins (:687),
RunScorePlugins (:723; score loop -> NormalizeScore -> x weight),
RunReservePlugins*, RunPermitPlugins (:962), RunPreBind/Bind/PostBind.

The per-node parallel loops (parallelize.Until with 16 workers) are run
serially here: the CPU oracle path exists for semantic parity testing and as
a fallback; the production path is the one-dispatch TPU kernel in
kubernetes_tpu.ops, which replaces RunFilterPlugins x nodes and
RunScorePlugins x nodes entirely.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ...api.types import Node, Pod, pod_key
from . import interface as fwk
from .interface import Code, CycleState, NodeScore, Status
from .types import NodeInfo, PodInfo


class WaitingPod:
    """A pod parked at Permit (runtime/waiting_pods_map.go waitingPod):
    each WAIT-returning plugin must Allow it, any may Reject; the binding
    goroutine blocks in Framework.wait_on_permit until resolution or the
    max plugin timeout."""

    def __init__(self, pod: Pod, plugin_timeouts: Dict[str, float]):
        self.pod = pod
        self._pending = set(plugin_timeouts)
        self._cv = threading.Condition()
        self._resolved = False
        self._status: Optional[Status] = None
        self._deadline = time.monotonic() + max(plugin_timeouts.values())
        self._listeners: List[Callable[[], None]] = []
        self._gate = None

    def set_gate(self, gate) -> None:
        """Attach a resolution arbiter (plugins/coscheduling.py GangGate).
        When the deadline and the gang's completion race, exactly one
        side wins: a timeout may resolve this pod ONLY after flipping
        the gate to failed; if the gate already completed, the allow()
        from the completing thread is in flight and timeout yields."""
        self._gate = gate

    def pending_plugins(self) -> List[str]:
        with self._cv:
            return sorted(self._pending)

    @property
    def deadline(self) -> float:
        return self._deadline

    def allow(self, plugin_name: str) -> None:
        with self._cv:
            self._pending.discard(plugin_name)
            if not self._pending and not self._resolved:
                self._resolved = True
                self._status = None  # success
            self._cv.notify_all()
            fire = self._take_listeners_locked()
        for fn in fire:
            fn()

    def reject(self, plugin_name: str, msg: str) -> None:
        with self._cv:
            if not self._resolved:
                self._resolved = True
                self._status = Status.unschedulable(
                    f"pod {self.pod.metadata.name!r} rejected while waiting at "
                    f"Permit: {msg}"
                )
                self._status.failed_plugin = plugin_name
            self._cv.notify_all()
            fire = self._take_listeners_locked()
        for fn in fire:
            fn()

    def add_listener(self, fn: Callable[[], None]) -> None:
        """Call fn() once, when this pod resolves (allow-all / reject /
        timeout); immediately if already resolved. Lets ONE drainer thread
        service every parked pod instead of one blocked thread per pod —
        a gang workload parks thousands at once."""
        with self._cv:
            if not self._resolved:
                self._listeners.append(fn)
                return
        fn()

    def timeout_if_due(self, now: float) -> bool:
        """Resolve with the timeout status if the deadline passed (the
        drainer's replacement for the per-thread wait loop's timeout).
        Returns False when a gang gate says completion won the race:
        the pod is NOT resolved here — the completing thread's allow()
        is about to resolve it success."""
        with self._cv:
            if self._resolved or now < self._deadline:
                return self._resolved
        return self._try_timeout()

    def _try_timeout(self) -> bool:
        """Arbitrate a due deadline against the gang gate (if any).
        True: this pod is resolved (timed out, or something else
        resolved it concurrently). False: the gate completed first —
        yield to the completing thread's allow()."""
        gate = self._gate
        if gate is not None and not gate.fail():
            with self._cv:
                return self._resolved
        with self._cv:
            if not self._resolved:
                self._resolved = True
                self._status = Status.unschedulable(
                    f"pod {self.pod.metadata.name!r} timed out waiting at "
                    f"Permit"
                )
                self._cv.notify_all()
            fire = self._take_listeners_locked()
        for fn in fire:
            fn()
        return True

    def _take_listeners_locked(self) -> List[Callable[[], None]]:
        if not self._resolved or not self._listeners:
            return []
        fire, self._listeners = self._listeners, []
        return fire

    def wait(self) -> Optional[Status]:
        while True:
            with self._cv:
                while not self._resolved:
                    remaining = self._deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=min(remaining, 0.5))
                if self._resolved:
                    status = self._status
                    fire = self._take_listeners_locked()
                    break
            if self._try_timeout():
                with self._cv:
                    status = self._status
                    fire = self._take_listeners_locked()
                break
            # the gang gate completed first: allow() is in flight on the
            # completing thread — wait for it to land, then re-read
            with self._cv:
                if not self._resolved:
                    self._cv.wait(timeout=0.05)
        for fn in fire:
            fn()
        return status

PluginFactory = Callable[[Optional[dict], "Framework"], fwk.Plugin]


class Registry(dict):
    """Plugin name -> factory (runtime/registry.go Registry)."""

    def register(self, name: str, factory: PluginFactory) -> None:
        if name in self:
            raise ValueError(f"a plugin named {name} already exists")
        self[name] = factory

    def merge(self, other: "Registry") -> None:
        for name, factory in other.items():
            self.register(name, factory)


class PluginSet:
    """Enabled plugins for one extension point with weights."""

    def __init__(self, enabled: Optional[List[Tuple[str, int]]] = None):
        self.enabled = enabled or []  # [(name, weight)]


class Framework:
    """One profile's configured plugin pipeline (framework.go:90 frameworkImpl)."""

    def __init__(
        self,
        registry: Registry,
        profile_name: str = "default-scheduler",
        plugins: Optional[Dict[str, List[Tuple[str, int]]]] = None,
        plugin_config: Optional[Dict[str, dict]] = None,
        snapshot_fn: Optional[Callable[[], object]] = None,
        parallelism: int = 16,
        handle_extras: Optional[Dict[str, object]] = None,
    ):
        self.profile_name = profile_name
        self.parallelism = parallelism
        self._snapshot_fn = snapshot_fn
        self._plugins_cfg = plugins or {}
        plugin_config = plugin_config or {}
        # Handle surface consumed by plugins at construction time
        # (interface.go:515 Handle: listers, clientset, volume binder).
        self.volume_binder = None
        self.volume_listers = None
        self.csi_node_lister = None
        self.client = None
        self.cache = None  # SchedulerCache (Coscheduling reservation counts)
        self.service_lister = None  # ServiceAffinity
        self.spread_listers = None  # SelectorSpread: () -> (svcs, rcs, rss, sss)
        for key, value in (handle_extras or {}).items():
            setattr(self, key, value)
        # Permit waiting-pods map (runtime/waiting_pods_map.go)
        self._waiting_pods: Dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()

        # Instantiate each referenced plugin exactly once (framework.go:276).
        needed: List[str] = []
        for names in self._plugins_cfg.values():
            for name, _ in names:
                if name not in needed:
                    needed.append(name)
        self.plugins: Dict[str, fwk.Plugin] = {}
        for name in needed:
            if name not in registry:
                raise ValueError(f"{name} does not exist in the plugin registry")
            self.plugins[name] = registry[name](plugin_config.get(name), self)

        def point(key: str) -> List[fwk.Plugin]:
            return [self.plugins[name] for name, _ in self._plugins_cfg.get(key, [])]

        self.queue_sort_plugins = point("queueSort")
        self.pre_filter_plugins = point("preFilter")
        self.filter_plugins = point("filter")
        self.post_filter_plugins = point("postFilter")
        self.pre_score_plugins = point("preScore")
        self.score_plugins = point("score")
        self.score_plugin_weight = {
            name: weight for name, weight in self._plugins_cfg.get("score", [])
        }
        self.reserve_plugins = point("reserve")
        self.permit_plugins = point("permit")
        self.pre_bind_plugins = point("preBind")
        self.bind_plugins = point("bind")
        self.post_bind_plugins = point("postBind")

    # -- Handle surface (interface.go:515) ---------------------------------
    def snapshot_shared_lister(self):
        return self._snapshot_fn() if self._snapshot_fn else None

    # -- QueueSort ---------------------------------------------------------
    def queue_sort_func(self):
        if not self.queue_sort_plugins:
            return None
        return self.queue_sort_plugins[0].less

    # -- PreFilter ---------------------------------------------------------
    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Optional[Status]:
        for pl in self.pre_filter_plugins:
            status = pl.pre_filter(state, pod)
            if not fwk.is_success(status):
                status.failed_plugin = pl.name
                return status
        return None

    def run_pre_filter_extension_add_pod(
        self, state: CycleState, pod: Pod, pod_info_to_add: PodInfo, node_info: NodeInfo
    ) -> Optional[Status]:
        for pl in self.pre_filter_plugins:
            if pl.pre_filter_extensions() is not None:
                status = pl.add_pod(state, pod, pod_info_to_add, node_info)
                if not fwk.is_success(status):
                    return status
        return None

    def run_pre_filter_extension_remove_pod(
        self, state: CycleState, pod: Pod, pod_info_to_remove: PodInfo, node_info: NodeInfo
    ) -> Optional[Status]:
        for pl in self.pre_filter_plugins:
            if pl.pre_filter_extensions() is not None:
                status = pl.remove_pod(state, pod, pod_info_to_remove, node_info)
                if not fwk.is_success(status):
                    return status
        return None

    # -- Filter ------------------------------------------------------------
    def run_filter_plugins(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Dict[str, Status]:
        """framework.go:530: runs all filter plugins, stops at first failure
        (unless recording all statuses); returns plugin->status map."""
        statuses: Dict[str, Status] = {}
        for pl in self.filter_plugins:
            status = pl.filter(state, pod, node_info)
            if not fwk.is_success(status):
                if not status.is_unschedulable():
                    status = Status(Code.ERROR, [f"running {pl.name!r} filter plugin: {status.message()}"])
                status.failed_plugin = pl.name
                statuses[pl.name] = status
                break
        return statuses

    def run_filter_plugins_with_nominated_pods(
        self, state: CycleState, pod: Pod, node_info: NodeInfo, nominator=None
    ) -> Optional[Status]:
        """framework.go:610: evaluate filters twice when the node has
        higher-priority nominated pods — once with them added, once without."""
        pod_priority = pod.spec.priority or 0
        nominated = []
        if nominator is not None and node_info.node is not None:
            nominated = [
                p
                for p in nominator.nominated_pods_for_node(node_info.node.metadata.name)
                if (p.spec.priority or 0) >= pod_priority
                and pod_key(p) != pod_key(pod)
            ]
        for run_with_nominated in ([True, False] if nominated else [False]):
            state_to_use = state
            node_info_to_use = node_info
            if run_with_nominated:
                state_to_use = state.clone()
                node_info_to_use = node_info.clone()
                for p in nominated:
                    pi = PodInfo(p)
                    node_info_to_use.add_pod_info(pi)
                    status = self.run_pre_filter_extension_add_pod(
                        state_to_use, pod, pi, node_info_to_use
                    )
                    if not fwk.is_success(status):
                        return status
            statuses = self.run_filter_plugins(state_to_use, pod, node_info_to_use)
            if statuses:
                return next(iter(statuses.values()))
        return None

    # -- PostFilter --------------------------------------------------------
    def run_post_filter_plugins(
        self, state: CycleState, pod: Pod, filtered_node_status_map: Dict[str, Status]
    ) -> Tuple[Optional[object], Optional[Status]]:
        statuses = []
        for pl in self.post_filter_plugins:
            result, status = pl.post_filter(state, pod, filtered_node_status_map)
            if status is not None and status.code == Code.SUCCESS:
                return result, status
            if status is not None and status.code != Code.UNSCHEDULABLE:
                return None, status
            statuses.append(status)
        reasons = [r for s in statuses if s for r in s.reasons]
        return None, Status(Code.UNSCHEDULABLE, reasons)

    # -- PreScore / Score --------------------------------------------------
    def run_pre_score_plugins(
        self, state: CycleState, pod: Pod, nodes: Sequence[Node]
    ) -> Optional[Status]:
        for pl in self.pre_score_plugins:
            status = pl.pre_score(state, pod, nodes)
            if not fwk.is_success(status):
                return Status(
                    Code.ERROR,
                    [f"running PreScore plugin {pl.name!r}: {status.message()}"],
                )
        return None

    def run_score_plugins(
        self, state: CycleState, pod: Pod, nodes: Sequence[Node]
    ) -> Tuple[Dict[str, List[NodeScore]], Optional[Status]]:
        """framework.go:723 RunScorePlugins.

        THE LOOP THE TPU KERNEL REPLACES. Order is load-bearing for parity:
        (1) per-node raw scores, (2) NormalizeScore per plugin, (3) x weight.
        """
        plugin_to_node_scores: Dict[str, List[NodeScore]] = {}
        for pl in self.score_plugins:
            scores = []
            for node in nodes:
                s, status = pl.score(state, pod, node.metadata.name)
                if not fwk.is_success(status):
                    return {}, Status(
                        Code.ERROR,
                        [f"plugin {pl.name!r} failed with: {status.message()}"],
                    )
                scores.append(NodeScore(node.metadata.name, s))
            plugin_to_node_scores[pl.name] = scores
        for pl in self.score_plugins:
            if pl.has_normalize:
                status = pl.normalize_score(state, pod, plugin_to_node_scores[pl.name])
                if not fwk.is_success(status):
                    return {}, Status(
                        Code.ERROR,
                        [f"plugin {pl.name!r} failed with: {status.message()}"],
                    )
        for pl in self.score_plugins:
            weight = self.score_plugin_weight.get(pl.name, 1)
            scores = plugin_to_node_scores[pl.name]
            for ns in scores:
                if ns.score > fwk.MAX_NODE_SCORE or ns.score < fwk.MIN_NODE_SCORE:
                    return {}, Status(
                        Code.ERROR,
                        [
                            f"plugin {pl.name!r} returns an invalid score {ns.score}, "
                            f"it should in the range of [{fwk.MIN_NODE_SCORE}, {fwk.MAX_NODE_SCORE}] after normalizing"
                        ],
                    )
                ns.score = ns.score * weight
        return plugin_to_node_scores, None

    # -- Reserve / Permit / Bind -------------------------------------------
    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        for pl in self.reserve_plugins:
            status = pl.reserve(state, pod, node_name)
            if not fwk.is_success(status):
                return status
        return None

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        # unreserve in reverse registration order (framework.go:932)
        for pl in reversed(self.reserve_plugins):
            pl.unreserve(state, pod, node_name)

    # Longest a Permit plugin may park a pod (framework.go maxTimeout 15min).
    MAX_PERMIT_TIMEOUT = 15 * 60.0

    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        """framework.go:962 RunPermitPlugins: WAIT-returning plugins park the
        pod in the waiting-pods map; the binding cycle then blocks in
        wait_on_permit (framework.go:1015)."""
        plugin_timeouts: Dict[str, float] = {}
        for pl in self.permit_plugins:
            status, timeout = pl.permit(state, pod, node_name)
            if not fwk.is_success(status):
                if status.code == Code.WAIT:
                    plugin_timeouts[pl.name] = min(
                        timeout or self.MAX_PERMIT_TIMEOUT, self.MAX_PERMIT_TIMEOUT
                    )
                    continue
                if status.is_unschedulable():
                    status.failed_plugin = pl.name
                    return status
                return Status(Code.ERROR, [f"running Permit plugin {pl.name!r}: {status.message()}"])
        if plugin_timeouts:
            wp = WaitingPod(pod, plugin_timeouts)
            with self._waiting_lock:
                self._waiting_pods[pod_key(pod)] = wp
            # notify the WAIT-returning plugins AFTER publishing the
            # map entry: a gang plugin attaches its gate and records
            # the park time here, and any later member completing the
            # gang must be able to find this pod via get_waiting_pod
            for pl in self.permit_plugins:
                if pl.name in plugin_timeouts:
                    on_waiting = getattr(pl, "on_waiting", None)
                    if on_waiting is not None:
                        on_waiting(wp)
            return Status(Code.WAIT)
        return None

    def wait_on_permit(self, pod: Pod) -> Optional[Status]:
        """framework.go:1015 WaitOnPermit: block the binding goroutine until
        every waiting Permit plugin allows (or one rejects / times out)."""
        with self._waiting_lock:
            wp = self._waiting_pods.get(pod_key(pod))
        if wp is None:
            return None
        try:
            return wp.wait()
        finally:
            with self._waiting_lock:
                self._waiting_pods.pop(pod_key(pod), None)

    def get_waiting_pod(self, key: str) -> Optional[WaitingPod]:
        with self._waiting_lock:
            return self._waiting_pods.get(key)

    def iterate_waiting_pods(self) -> List[WaitingPod]:
        with self._waiting_lock:
            return list(self._waiting_pods.values())

    def reject_waiting_pod(self, key: str, plugin_name: str, msg: str) -> bool:
        wp = self.get_waiting_pod(key)
        if wp is None:
            return False
        wp.reject(plugin_name, msg)
        return True

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        for pl in self.pre_bind_plugins:
            status = pl.pre_bind(state, pod, node_name)
            if not fwk.is_success(status):
                return Status(Code.ERROR, [f"running PreBind plugin {pl.name!r}: {status.message()}"])
        return None

    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        if not self.bind_plugins:
            return Status(Code.ERROR, ["no bind plugin configured"])
        for pl in self.bind_plugins:
            status = pl.bind(state, pod, node_name)
            if status is not None and status.code == Code.SKIP:
                continue
            if not fwk.is_success(status):
                return Status(Code.ERROR, [f"bind plugin {pl.name!r} failed to bind: {status.message()}"])
            return status
        return None

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for pl in self.post_bind_plugins:
            pl.post_bind(state, pod, node_name)

    def has_filter_plugins(self) -> bool:
        return bool(self.filter_plugins)

    def has_score_plugins(self) -> bool:
        return bool(self.score_plugins)
