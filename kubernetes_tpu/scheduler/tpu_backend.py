"""TPU scheduling backend: the kernel-driven replacement for the oracle
filter/score path.

Where the reference runs findNodesThatPassFilters + RunScorePlugins per
node on goroutines (reference: pkg/scheduler/core/generic_scheduler.go:235,
pkg/scheduler/framework/runtime/framework.go:723), this backend keeps the
whole cluster as device-resident dense arrays (models/encoding.py), mirrors
every scheduler-cache mutation into them via CacheListener hooks, and
evaluates ALL nodes in one fused dispatch (ops/kernel.py) — no adaptive
subsampling (generic_scheduler.go:177's 5-50% compromise removed).

Status reconstruction: each kernel mask corresponds to one plugin's Filter;
infeasible nodes get Unschedulable statuses naming the failing plugins so
FitError output matches the oracle's shape (plugin-name level, not
message-string level).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import types as v1
from ..models.encoding import ClusterEncoding
from ..models.pod_encoder import PodEncoder
from ..ops.batch import shape_signature
from ..ops.hoisted import (
    HoistedSession,
    ipa_term_match_np,
    match_matrices_np,
    template_fingerprint,
)
from ..utils import devtime, knobs, tracing
from .degradation import (
    RUNG_HOISTED,
    RUNG_ORACLE,
    RUNG_PALLAS,
    DegradationLadder,
    DeviceFault,
)

logger = logging.getLogger(__name__)

# sentinel "node" for a gate/encode volume-resolution race: the pod is
# not unschedulable — it must RE-GATE promptly (the scheduler re-adds it
# to the active queue instead of parking it for the leftover flusher)
RETRY_NODE = "\x00volume-retry"
from ..ops.kernel import DEFAULT_WEIGHTS, schedule_pod_jit
from .core import ScheduleResult
from .framework.interface import FitError, Status
from .internal.cache import CacheListener
from .volume_device import VolumeResolutionChanged

# kernel mask key -> plugin name (for FitError statuses)
MASK_PLUGINS = (
    ("mask_name", "NodeName"),
    ("mask_unsched", "NodeUnschedulable"),
    ("mask_taint", "TaintToleration"),
    ("mask_ports", "NodePorts"),
    ("mask_fit", "NodeResourcesFit"),
    ("mask_node_affinity", "NodeAffinity"),
    ("mask_pts", "PodTopologySpread"),
    ("mask_ipa", "InterPodAffinity"),
)


def _explain_topk(payload: Dict, node_names: List[str]) -> List[Tuple[str, int]]:
    """Level-2 provenance rendering of one pod's explain payload: the
    top-k candidates as (node, weighted total), best first. The full
    per-plugin masks/scores stay on the batch handle for the sentinel and
    the explain CLI — the flight-recorder record carries the ranking."""
    out: List[Tuple[str, int]] = []
    for idx, total in zip(payload["topk_idx"], payload["topk_total"]):
        idx, total = int(idx), int(total)
        if 0 <= idx < len(node_names) and total >= 0:
            out.append((node_names[idx], total))
    return out


class _BatchHandle:
    """One dispatched batch: device outputs + how to decode them. The
    decode fn is captured at dispatch time because the session may be
    invalidated (by foreign cluster events) before harvest — the computed
    ys stay valid either way."""

    __slots__ = ("group", "ys", "decide", "node_names", "results",
                 "deadline", "bucket", "timed_out", "speculative",
                 "conflicts", "prov", "explain", "basis_mutations", "dt")

    def __init__(self, group: List[v1.Pod]):
        self.group = group
        self.ys = None
        self.decide = None
        # speculative dispatch: this scan was enqueued while EARLIER
        # batches were still in flight — it chained on a carry whose
        # decisions had not been harvested/validated yet. A clean FIFO
        # harvest is a speculation hit; a re-drive because that carry
        # was invalidated (fault, validation failure, conflict suffix,
        # worker-crash abandon) is a miss.
        self.speculative = False
        # session-captured conflict decoder (like `decide`): maps ys to
        # (n_conflicts, replay_suffix_start) — None for sessions without
        # multipod support
        self.conflicts = None
        # decisions are node INDICES into the cluster as of dispatch; a
        # node remove/rebuild before harvest would shift enc.node_names,
        # so the dispatch-time table rides the handle
        self.node_names: Optional[List[str]] = None
        self.results: Optional[List[Tuple[v1.Pod, Optional[str]]]] = None
        # dispatch watchdog: the wall-clock deadline for this scan's
        # results; a wait past it is a device fault, not a longer wait
        self.deadline: Optional[float] = None
        self.bucket: Optional[int] = None  # pallas AOT-exec bucket (Bp)
        self.timed_out = False
        # flight-recorder provenance captured at dispatch time (rung,
        # session kind, build reason, ...). None unless KTPU_TRACE >= 2
        # — the disabled path must not allocate per batch beyond the
        # handle itself (pinned by the overhead test)
        self.prov: Optional[Dict] = None
        # KTPU_EXPLAIN: the decoded per-pod explain payloads (packed
        # filter-mask bits + top-k totals/score stacks), index-aligned
        # with `group`. None with explain off — same allocation contract
        # as prov — and None on sessions without explain support
        self.explain: Optional[List[Dict]] = None
        # (cache foreign-mutation generation, scheduler dropped-decision
        # count) latched just before dispatch: the shadow sentinel's
        # stale-basis gate — if either advanced by completion time, the
        # oracle replay would run against a cluster the device never
        # decided on, so the audit is skipped (counted) instead of
        # reporting false drift
        self.basis_mutations: Optional[Tuple[int, int]] = None
        # device-timeline launch token (utils/devtime.py): submit
        # stamped at dispatch enqueue, ready at harvest — None below
        # KTPU_DEVTIME=1 (the disabled path allocates nothing per
        # batch; pinned with prov/explain by the overhead test)
        self.dt = None


class TPUBackend(CacheListener):
    """Owns the dense encoding + kernel dispatch; registered as a cache
    listener so device state tracks the assume-cache at O(changed rows)."""

    def __init__(
        self,
        weights: Optional[Dict[str, int]] = None,
        rng: Optional[random.Random] = None,
        mesh=None,
    ):
        self.enc = ClusterEncoding()
        self.pe = PodEncoder(self.enc)
        self.weights = weights or DEFAULT_WEIGHTS
        self.rng = rng or random.Random()
        # multi-chip: a jax.sharding.Mesh shards the NODE axis of every
        # dispatch (parallel/sharded.py) — session statics and carry
        # inherit the sharding through GSPMD, reductions ride ICI
        # collectives. Decisions are bit-identical to single-device
        # (tests/test_sharded.py through the Scheduler loop).
        self.mesh = mesh
        if mesh is not None:
            # rebuild-time node capacity lands on a shard multiple, so
            # the mesh path never re-pads (shape-stable across rebuilds)
            # and incremental node adds stay inside the session's lanes
            from ..parallel.sharded import node_capacity_multiple

            self.enc.node_quantum = node_capacity_multiple(mesh)
        self._lock = threading.RLock()
        # cross-cycle hoisted session (ops/hoisted.py HoistedSession): the
        # device-resident carry survives between schedule_many calls as
        # long as the ONLY cluster mutations are the assumes the session
        # itself produced (tracked in _session_assumed — the cache.assume
        # confirmation arrives later through on_add_pod and must not
        # invalidate). Any other mutation tears the session down; the next
        # batch rebuilds it from the synced encoding.
        self._session = None  # HoistedSession or pallas PallasSession
        self._session_assumed: set = set()
        # incremental device-state deltas: cluster events the classifier
        # proved touch ONLY the session's carry (batchable pod add/remove
        # on a known node) or template-invariant statics (allocatable-only
        # node updates) queue here instead of tearing the session down,
        # and the next dispatch applies them in one fused launch
        # (_apply_session_deltas_locked). Teardown stays the path for
        # everything structural: node add/remove, pods with affinity
        # terms or host ports, vocab/capacity growth. The kill switch
        # exists for A/B parity runs (tests + probe_session_deltas.py).
        self._deltas: List[Dict] = []
        self.delta_patching = knobs.get_bool("KTPU_SESSION_DELTAS")
        # backstop for an idle scheduler accumulating events with no
        # dispatch to flush them: past this the rebuild is cheaper than
        # the queue is worth, and the teardown path absorbs everything
        self.max_queued_deltas = knobs.get_int("KTPU_MAX_QUEUED_DELTAS")
        self._node_fps: Dict[str, tuple] = {}  # heartbeat-change gate
        self._known_templates: Dict = {}  # fingerprint -> pod arrays
        # in-flight batches, oldest first. Depth 2 double-buffers the
        # device: batch k+1's scan is enqueued (chained on k's carry as a
        # pure data dependency) while k still runs, so the device never
        # drains between the host's harvest of k-1 and the dispatch of
        # k+1. Harvests are strictly FIFO — sequential assume semantics
        # ride the carry chain, and the host encoding applies each
        # batch's decisions in dispatch order (_harvest_locked).
        self._pending: deque = deque()  # of _BatchHandle
        self.max_pending = 2
        # back-pressure seam: when _pending is full, dispatch_many
        # either waits on this condition for the completion worker to
        # drain (async_harvest_drain=True — set by the Scheduler at
        # pipeline_depth >= 1, so the scheduler thread NEVER decodes a
        # harvest) or harvests inline (direct backend users: bench,
        # depth-0). Signalled whenever _pending shrinks.
        self._pending_cv = threading.Condition(self._lock)
        self.async_harvest_drain = False
        # speculative dispatch kill switch (KTPU_SPECULATION=0): with
        # speculation off, a new scan never chains on a not-yet-
        # harvested carry — dispatch_many flushes the pipeline first
        # (serializing; the A/B lever for the bench matrix)
        self.speculation = knobs.get_bool("KTPU_SPECULATION")
        self.MAX_SESSION_TEMPLATES = 8
        self.volume_resolver = None  # scheduler/volume_device.py
        # pallas rides only on real TPUs: on CPU (tests, dryruns) the
        # interpreter would be pathologically slow and compile-heavy.
        # A mesh also disables it: the Mosaic kernel is a single-device
        # program; multi-chip rides the GSPMD-sharded hoisted session.
        import jax

        self.use_pallas = (
            jax.devices()[0].platform == "tpu" and mesh is None
        )
        # device-side preemption planning (ops/whatif.py): the what-if
        # context is a SCRATCH view of the cluster (live-session carry
        # copy, or a non-donating encoding snapshot for pallas/sharded
        # sessions) — launches never chain onto or invalidate the live
        # session. Platform default mirrors kernel.multipod_k: ON where
        # the launch is a real device dispatch (TPU), OFF on CPU where
        # the jnp what-if pays XLA compiles the numpy fast rung + oracle
        # don't (the parity suites and probe enable it explicitly).
        # KTPU_WHATIF=0 is the kill switch / =1 the CPU opt-in.
        self.whatif = knobs.get_bool(
            "KTPU_WHATIF",
            default=jax.devices()[0].platform == "tpu",
        )
        # -- device fault tolerance ------------------------------------
        # Optional FaultInjector seam (testing/faults.py, duck-typed):
        # chaos drills arm dispatch raises / NaN harvests / wedged waits
        # through it. None in production.
        self.faults = None
        # watchdog: no device wait (harvest, flush, probe) may exceed
        # this — past it the dispatch is a fault, the in-flight chain is
        # abandoned, and the batch re-drives synchronously
        self.watchdog_timeout = knobs.get_float("KTPU_WATCHDOG_TIMEOUT")
        # bounded retry (capped exponential backoff + full jitter — the
        # Supervisor's restart policy at dispatch granularity)
        self.retry_cap = knobs.get_int("KTPU_DISPATCH_RETRIES")
        self.retry_base = knobs.get_float("KTPU_RETRY_BASE")
        self.retry_max = knobs.get_float("KTPU_RETRY_MAX")
        # degradation ladder: consecutive faults demote pallas -> hoisted
        # -> oracle; the probe loop below re-promotes when a canary
        # dispatch answers correctly again
        self.ladder = DegradationLadder(
            top=RUNG_PALLAS if self.use_pallas else RUNG_HOISTED,
            threshold=knobs.get_int("KTPU_DEMOTE_THRESHOLD"),
            probe_interval=knobs.get_float("KTPU_PROBE_INTERVAL"),
            rng=self.rng,
        )
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_lock = threading.Lock()
        self._probe_stop = threading.Event()
        # pallas batch buckets whose AOT executable produced a fault:
        # quarantined (jit-only) on every rebuilt session — the _exec
        # cache dies with its session, the suspicion must not — until
        # the bucket harvests cleanly again (_harvest_locked)
        self._suspect_buckets: set = set()
        self._whatif_cache: Dict = {}
        self._whatif_cache_version = -1
        # backend-health event hook: the Scheduler wires this to its
        # EventRecorder so ladder demote/promote, supervised-worker
        # restarts and speculation-miss re-drives surface as k8s Events
        # (cluster-level observers see device health without scraping
        # metrics). Signature: (event_type, reason, message). Must never
        # raise into the dispatch path — _notify_health guards it.
        self.health_cb = None
        # decision explainability (ISSUE 10): KTPU_EXPLAIN makes every
        # hoisted harvest carry per-plugin filter-mask verdicts and
        # weighted score splits for the top-k candidate nodes
        # (ops/hoisted.py explain mode; decisions stay bit-identical).
        # KTPU_SHADOW_SAMPLE arms the scheduler's shadow parity sentinel
        # — and needs the explain payload to attribute drift per plugin,
        # so any sample rate > 0 turns explain on. Explain rides the
        # hoisted session only: pallas/sharded sessions demote (loudly,
        # session_builds{reason="explain"}) while it is armed.
        self.shadow_sample = min(1.0, max(0.0,
            knobs.get_float("KTPU_SHADOW_SAMPLE")))
        self.explain = (
            knobs.get_bool("KTPU_EXPLAIN")
            or self.shadow_sample > 0
        )
        self.explain_topk = max(1, knobs.get_int("KTPU_EXPLAIN_TOPK"))
        # overload-shed lever (scheduler/degradation.OverloadMonitor):
        # False = the device still computes explain outputs (the session
        # shape is untouched — no teardown) but the host SKIPS the
        # attribution decode at harvest, shedding the decode cost while
        # overloaded. Decision columns are decoded either way.
        self.explain_harvest = True
        # flight-recorder provenance context: the last session build
        # ("kind/reason") and the last teardown reason — what the
        # per-pod provenance records (KTPU_TRACE=2) report as the
        # session half of "where did this pod's time go"
        self._last_build = ""
        self._last_invalidate = ""
        # device-timeline hand-off: _build_session_impl measures the
        # cluster upload (kind=transfer) before the session kind is
        # known; the _build_session wrapper reads this and feeds the
        # per-shard slug counter once the built session names the slug
        self._upload_seconds = 0.0
        # runtime-effective KTPU_* knob surface (utils/configz.py):
        # today the env vars are invisible at runtime; /configz shows
        # the values this backend actually resolved
        from ..models.vocab import node_headroom as _nh
        from ..ops.kernel import multipod_k as _mk
        from ..utils import configz
        from .metrics import mesh_shards

        mesh_shards.set(
            float(self.mesh.devices.size) if self.mesh is not None else 0.0)
        configz.install_knobs(
            "ktpu",
            multipod_k=_mk(platform=jax.devices()[0].platform),
            mesh_devices=(
                int(self.mesh.devices.size) if self.mesh is not None else 0),
            node_headroom=_nh(),
            speculation=self.speculation,
            whatif=self.whatif,
            session_deltas=self.delta_patching,
            max_queued_deltas=self.max_queued_deltas,
            use_pallas=self.use_pallas,
            watchdog_timeout=self.watchdog_timeout,
            dispatch_retries=self.retry_cap,
            demote_threshold=self.ladder.threshold,
            trace_level=tracing.level(),
            trace_capacity=tracing.RECORDER.capacity,
            devtime_level=devtime.level(),
            devtime_capacity=devtime.TIMELINE.capacity,
            explain=self.explain,
            explain_topk=self.explain_topk,
            shadow_sample=self.shadow_sample,
        )

    def _notify_health(self, event_type: str, reason: str,
                       message: str) -> None:
        """Best-effort backend-health event (ladder transitions, worker
        restarts, speculation-miss re-drives). Never raises: health
        reporting must not add a failure mode to the fault path."""
        cb = self.health_cb
        if cb is None:
            return
        try:
            cb(event_type, reason, message)
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.warning("backend health event failed", exc_info=True)

    def set_shadow_sample(self, rate: float) -> None:
        """Arm (or disarm) the shadow parity sentinel at runtime — the
        bench/harness knob (Workload.shadow_sample rides the row, not the
        process env). Arming forces explain mode on so drift can be
        attributed per plugin; a live non-explain session is torn down
        and the next dispatch rebuilds with explain outputs."""
        from ..utils import configz

        with self._lock:
            self.shadow_sample = min(1.0, max(0.0, float(rate)))
            explain = (
                knobs.get_bool("KTPU_EXPLAIN")
                or self.shadow_sample > 0
            )
            if explain != self.explain:
                self.explain = explain
                self._invalidate_session("explain-toggle")
            configz.install_knobs(
                "ktpu", explain=self.explain,
                shadow_sample=self.shadow_sample,
            )

    def set_shadow_rate_only(self, rate: float) -> None:
        """Overload-shed path for the sentinel: change the sample rate
        WITHOUT re-deriving explain mode. set_shadow_sample tears down a
        live session when the rate transition flips explain ("explain-
        toggle" rebuild) — exactly wrong under overload, where the point
        of shedding is to spend LESS. Leaving `explain` as resolved at
        arm time keeps the session shape (and therefore decisions)
        bit-identical; the completion worker just stops drawing samples
        while the rate is 0."""
        from ..utils import configz

        with self._lock:
            self.shadow_sample = min(1.0, max(0.0, float(rate)))
            configz.install_knobs("ktpu", shadow_sample=self.shadow_sample)

    def set_volume_resolver(self, resolver) -> None:
        """Enable the volume device path: bound-PVC pods encode their PV
        constraints + attach counts into kernel inputs (volume_device.py)
        instead of diverting to the oracle."""
        with self._lock:
            self.volume_resolver = resolver
            self.pe.volume_resolver = resolver
            self.enc.volume_hook = resolver
            resolver.on_new_driver = self._on_new_volume_driver

    def _on_new_volume_driver(self) -> None:
        """A driver just entered use: node rows built before it carry no
        limit column (reads 0 = limit 0) — rebuild before the next
        dispatch treats every node as attach-full."""
        with self._lock:
            self._invalidate_session("volume-driver")
            self.enc._rebuild_needed = True

    def volume_kernel_safe(self, pod: v1.Pod) -> bool:
        """True when this PVC-bearing pod's volume constraints resolve
        into the kernel envelope RIGHT NOW (gates the oracle diversion)."""
        if self.volume_resolver is None:
            return False
        return self.volume_resolver.resolve(pod) is not None

    def on_volume_change(self, kind: str = "", obj=None) -> None:
        """A PVC/PV/CSINode event: resolver.version bumps always (cached
        pod encodings key off it), but the EXPENSIVE part — session
        teardown + full encoding rebuild — only runs when the object can
        actually touch encoded state: a claim some encoded pod
        references, a PV bound to such a claim, or a CSINode for a
        driver in use. A steady provisioning drip for not-yet-scheduled
        pods must not cost a multi-second rebuild per event."""
        resolver = self.volume_resolver
        if resolver is None:
            return
        with self._lock:
            resolver.bump()
            if not self._volume_obj_encoded(kind, obj, resolver):
                return
            self._invalidate_session("volume-change")
            self.enc._rebuild_needed = True

    @staticmethod
    def _volume_obj_encoded(kind: str, obj, resolver) -> bool:
        if obj is None or not kind:
            return True  # unknown shape: stay conservative
        try:
            if kind == "pvc":
                key = (obj.metadata.namespace, obj.metadata.name)
                return resolver.claim_referenced(key)
            if kind == "pv":
                ns = obj.spec.claim_ref_namespace
                name = obj.spec.claim_ref_name
                if not name:
                    return False  # unbound PV: no encoded pod can see it
                return resolver.claim_referenced((ns or "default", name))
            if kind == "csinode":
                drivers = {d.name for d in obj.spec.drivers or []}
                return resolver.drivers_referenced(drivers)
        except Exception:  # noqa: BLE001 — malformed object: conservative
            return True
        return True

    def _shards_label(self) -> str:
        """`shards` metric label: mesh device count, '' off-mesh —
        appended LAST at every inc site (label order is declared)."""
        return str(int(self.mesh.devices.size)) if self.mesh is not None \
            else ""

    def _devtime_slug(self, session=None) -> str:
        """Per-shard device-time slug ('pallas@8', 'hoisted', '-' with
        no live session): the session_builds kind@shards convention, so
        scheduler_device_time_seconds_total reads per shard count."""
        s = session if session is not None else self._session
        if s is None:
            return "-"
        kind = "pallas" if "Pallas" in type(s).__name__ else "hoisted"
        sh = self._shards_label()
        return f"{kind}@{sh}" if sh else kind

    def _feed_device_time(self, kind: str, seconds: float,
                          session=None) -> None:
        """Accumulate one launch's device seconds into the per-shard
        slug counter (KTPU_DEVTIME >= 1 only — callers gate)."""
        from .metrics import device_time

        if seconds > 0:
            device_time.inc(
                seconds, slug=self._devtime_slug(session), kind=kind)

    def _invalidate_session(self, reason: str = "unspecified") -> None:
        # _session_assumed survives invalidation deliberately: an assume
        # echo (cache confirming a pod the torn-down session scheduled)
        # is host-bookkeeping either way and must not tear down the NEXT
        # session too. Queued deltas do NOT survive: they reconcile the
        # LIVE session with the encoding, and the fresh session builds
        # from the already-mutated encoding.
        import os as _os

        self._deltas.clear()
        if self._session is None:
            return
        from .metrics import session_rebuilds

        session_rebuilds.inc(reason=reason, shards=self._shards_label())
        self._last_invalidate = reason
        tracing.event("session-teardown", "session", reason=reason)
        if knobs.get_flag("KTPU_DEBUG_INVALIDATE"):
            import traceback as _tb

            print(f"SESSION INVALIDATED ({reason}) BY:",
                  file=__import__("sys").stderr)
            _tb.print_stack(limit=8)
        self._session = None

    # -- device fault tolerance --------------------------------------------
    # Every device-touching path runs under this discipline: the dispatch
    # is guarded (injector seam + real exceptions), the wait is bounded by
    # the watchdog, and the harvested payload passes a finite/in-range
    # check BEFORE its decisions reach assume(). A fault retires the
    # suspect AOT executable, tears the session down, counts toward the
    # ladder (demotion after `threshold` consecutive), and the batch
    # re-drives synchronously with capped backoff; an exhausted batch
    # resolves to RETRY_NODE so the scheduler returns its pods to the
    # queue exactly once.

    def _check_dispatch_fault(self, rung: Optional[int] = None) -> None:
        inj = self.faults
        if inj is not None:
            inj.on_dispatch(rung=self.ladder.rung() if rung is None else rung)

    def _wait_ready(self, ys, timeout: float) -> bool:
        """Watchdog-bounded device wait: True when every result leaf is
        ready, False when the deadline passes (wedged device). Polling
        is_ready() instead of block_until_ready keeps a hung XLA wait
        from pinning the calling thread forever — the one failure
        PR 3's pipeline could not survive."""
        import jax

        deadline = _time.monotonic() + max(0.0, timeout)
        leaves = [
            x for x in jax.tree_util.tree_leaves(ys) if hasattr(x, "is_ready")
        ]
        while True:
            inj = self.faults
            wedged = inj is not None and inj.wedge_active()
            if not wedged:
                try:
                    leaves = [x for x in leaves if not x.is_ready()]
                except Exception:  # noqa: BLE001 — let decode surface it
                    return True
                if not leaves:
                    return True
            if _time.monotonic() >= deadline:
                # an injected wedge shot is NOT consumed here: with
                # concurrent waiters (completion worker + a locked
                # flush) the first watchdog would otherwise absorb the
                # shot and the second thread would harvest "cleanly" —
                # the shot ends when the timeout FAULT is recorded
                # (_device_fault_locked), i.e. when recovery begins
                return False
            _time.sleep(0.002)

    def _validate_decisions(self, decisions: List[int], n_names: int,
                            ys=None) -> None:
        """Cheap guard between harvest and assume: every decision must be
        a node index (or -1) against the dispatch-time node table, and
        any float payload must be finite. Garbage from a sick device is
        a fault to recover from, not state to propagate."""
        for d in decisions:
            if not (-1 <= int(d) < n_names):
                raise DeviceFault(
                    f"decision {d} outside [-1, {n_names})", kind="invalid")
        if isinstance(ys, dict):
            for k, val in ys.items():
                if not hasattr(val, "dtype"):
                    continue
                a = np.asarray(val)
                if a.dtype.kind == "f" and not np.isfinite(a).all():
                    raise DeviceFault(
                        f"non-finite device payload in {k!r}", kind="invalid")

    def _device_fault_locked(self, kind: str, buckets=(),
                             attrs: Optional[Dict] = None) -> None:
        """Record one device fault: count it, quarantine the suspect AOT
        buckets (pallas — the quarantine outlives the session teardown
        one line down, _build_session re-applies it to every rebuild),
        tear the session down, and demote the ladder when this fault
        crossed the consecutive threshold. The flight recorder dumps its
        ring BEFORE recovery proceeds: a watchdog timeout or validation
        fault leaves the faulted dispatch's span trail (bucket, rung,
        speculation state) in the log, not just a counter bump."""
        from .metrics import device_faults, dump_seam

        device_faults.inc(kind=kind)
        if kind == "timeout" and self.faults is not None:
            # injected-wedge shot accounting: the watchdog fired and the
            # fault is now recorded — recovery's retry path must see a
            # responsive device again
            self.faults.consume_wedge()
        self._suspect_buckets.update(b for b in buckets if b is not None)
        fault_attrs = dict(attrs or ())
        fault_attrs.update(
            kind=kind, rung=self.ladder.mode(),
            buckets=sorted(b for b in buckets if b is not None),
        )
        tracing.event("device-fault", "fault", **fault_attrs)
        dump_seam(f"device-fault-{kind}", **fault_attrs)
        self._invalidate_session("device-fault")
        if self.ladder.record_fault(kind):
            logger.warning(
                "TPU backend demoted to %s after %d consecutive device "
                "faults (last: %s); background probe will re-promote",
                self.ladder.mode(), self.ladder.threshold, kind,
            )
            dump_seam("ladder-demoted", **fault_attrs)
            self._notify_health(
                "Warning", "BackendDemoted",
                f"scoring backend demoted to {self.ladder.mode()} after "
                f"consecutive device faults (last: {kind})",
            )
            self._ensure_probe_thread()

    def _dispatch_with_retry(self, attempt):
        """THE bounded-retry policy, shared by every synchronous dispatch
        path: capped exponential backoff + full jitter (the Supervisor's
        restart policy at dispatch granularity), one recorded fault per
        failed attempt (so persistent faults walk the ladder down), and
        an immediate stop once the ladder hits oracle (a sick device
        must not be hammered with retry storms the scheduler is already
        routing around). Returns `attempt()`'s value; raises DeviceFault
        when retries exhaust or the backend is fully demoted."""
        from .metrics import dispatch_retries

        delay = self.retry_base
        for n in range(self.retry_cap + 1):
            if self.ladder.rung() <= RUNG_ORACLE:
                break
            if n:
                dispatch_retries.inc()
                tracing.event("dispatch-retry", "fault", attempt=n,
                              rung=self.ladder.mode())
                _time.sleep(
                    min(delay, self.retry_max) * (1 + self.rng.random()))
                delay *= 2
            try:
                out = attempt()
                self.ladder.record_success()
                return out
            except DeviceFault as e:
                logger.warning("device dispatch fault (%s, attempt %d/%d)",
                               e.kind, n + 1, self.retry_cap + 1)
                self._device_fault_locked(e.kind)
            except Exception:  # noqa: BLE001 — any device-path error
                logger.warning("device dispatch fault (attempt %d/%d)",
                               n + 1, self.retry_cap + 1, exc_info=True)
                self._device_fault_locked("raise")
        raise DeviceFault(
            "dispatch retries exhausted (or backend demoted)", kind="raise")

    def _session_schedule_guarded(self, arrays: List[Dict]) -> Optional[List[int]]:
        """_session_schedule under the retry policy. Returns None when
        retries exhaust or the ladder hit oracle — callers turn the
        group into RETRY_NODE results (back to the scheduling queue
        exactly once; the scheduler routes the re-pop through the
        oracle while demoted)."""

        def attempt():
            self._check_dispatch_fault()
            decisions = self._session_schedule(arrays)
            self._validate_decisions(decisions, self.enc.n_lanes)
            return decisions

        try:
            return self._dispatch_with_retry(attempt)
        except DeviceFault:
            return None

    def _recover_dispatches_locked(self, kind: str, first: "_BatchHandle") -> None:
        """Harvest-side fault: `first`'s payload is bad, and every later
        pending batch chained its scan on the same carry — all of it is
        suspect. Abandon the chain, record the fault, then re-decide
        each batch synchronously IN DISPATCH ORDER (schedule_many runs
        the guarded/retrying session path), so sequential-assume
        semantics — and decision parity when the fault was transient —
        survive the recovery. Nothing from the abandoned scans ever
        touched the host encoding: pre-harvest handles carry no state."""
        from .metrics import dispatch_retries

        dropped = [first] + list(self._pending)
        self._pending.clear()
        self._pending_cv.notify_all()
        # every later batch was a speculative dispatch chained on the
        # carry this fault just invalidated — count the misses (the
        # faulting batch itself is the fault, not a miss)
        self._miss_speculative(dropped[1:])
        buckets = {h.bucket for h in dropped if h.bucket is not None}
        self._device_fault_locked(
            kind, buckets=buckets,
            attrs={
                "n_batches": len(dropped), "n_pods": len(first.group),
                "bucket": first.bucket, "speculative": first.speculative,
            },
        )
        for h in dropped:
            h.ys = None
            dispatch_retries.inc()
            with tracing.span("re-drive", "replay", n=len(h.group),
                              speculative=h.speculative, kind=kind):
                h.results = self.schedule_many(h.group)

    def abandon_pending(self) -> int:
        """Drop every not-yet-harvested in-flight dispatch WITHOUT
        re-deciding it (completion-worker crash recovery: the restarted
        worker requeues the pods instead). Abandoned handles resolve to
        RETRY_NODE results, so a completion that still holds one sends
        its pods back to the queue exactly once; the session is torn
        down because its device carry includes the abandoned assumes."""
        with self._lock:
            n = len(self._pending)
            self._miss_speculative(self._pending)
            for h in self._pending:
                h.ys = None
                h.results = [(p, RETRY_NODE) for p in h.group]
            self._pending.clear()
            self._pending_cv.notify_all()
            if n:
                self._invalidate_session("abandon-pending")
            return n

    # -- device-side preemption: what-if context ---------------------------

    def whatif_enabled(self) -> bool:
        """True when the planner's device rung may run: kill switch on
        and the degradation ladder above oracle."""
        return self.whatif and self.ladder.rung() > RUNG_ORACLE

    def whatif_context(self, pod_arrays: Dict):
        """A WhatifContext for this preemptor template against CURRENT
        cluster state. Preference order: the live HoistedSession when it
        knows the template (queued deltas reconciled first, carry
        snapshotted on-device — zero uploads); otherwise a throwaway
        hoisted view over a non-donating encoding snapshot (the pallas /
        sharded sessions keep their carry in kernel-private scaled
        layouts, and the host encoding is their exact mirror after
        harvest). Neither path invalidates the live session or counts a
        session build. Cached per encoding version."""
        from ..ops.whatif import WhatifContext, WhatifUnavailable

        with self._lock:
            if not self.whatif:
                raise WhatifUnavailable("KTPU_WHATIF=0", reason="disabled")
            if self.ladder.rung() <= RUNG_ORACLE:
                raise WhatifUnavailable("backend demoted to oracle",
                                        reason="demoted")
            if self.enc.n_nodes == 0:
                raise WhatifUnavailable("empty cluster", reason="context")
            # settle the array epoch BEFORE keying the cache: volume
            # events flag _rebuild_needed without an object-level
            # version bump, and rebuild() bumps the version itself
            if self.enc._rebuild_needed or self.enc._caps_grew():
                self.enc.rebuild()
            if self._whatif_cache_version != self.enc.version:
                self._whatif_cache.clear()
                self._whatif_cache_version = self.enc.version
            fp = template_fingerprint(pod_arrays)
            sess = self._session
            if isinstance(sess, HoistedSession) and fp in sess._fps:
                ctx = self._whatif_cache.get(("sess",))
                if ctx is not None and ctx._sess is sess:
                    return ctx
                # reconcile queued cluster-event deltas into the live
                # carry first (the normal pre-dispatch apply — the
                # scratch copy must see them); an apply failure falls
                # through to the encoding path
                self._apply_session_deltas_locked()
                sess = self._session
                if isinstance(sess, HoistedSession) and fp in sess._fps:
                    ctx = WhatifContext.from_session(
                        sess, self.enc.node_names)
                    self._whatif_cache[("sess",)] = ctx
                    return ctx
            ctx = self._whatif_cache.get(("enc", fp))
            if ctx is not None:
                return ctx
            # the throwaway hoisted view costs a device upload + a
            # prologue build — carry a consistent host copy out and do
            # the expensive part WITHOUT the lock (dispatch/harvest
            # contend on it); double-checked insert below
            host = self.enc.host_snapshot()
            node_names = list(self.enc.node_names)
            version = self.enc.version
        ctx = WhatifContext.from_host_snapshot(host, node_names, pod_arrays,
                                               mesh=self.mesh)
        with self._lock:
            if (self._whatif_cache_version == version
                    and self.enc.version == version):
                self._whatif_cache[("enc", fp)] = ctx
        return ctx

    def gang_feasible(self, pod: v1.Pod, k: int) -> Optional[bool]:
        """Joint co-placement probe for the gang deadlock breaker: can
        k pods of this pod's template co-place on the current cluster?
        One positive-delta what-if launch on a scratch carry
        (ops/whatif._gang_fits_run) — False is definitive capacity-wise
        ("cannot place even ignoring inter-member constraints"), True
        is optimistic on inter-member couplings. None when the what-if
        path cannot serve (disabled, demoted, template outside the
        envelope, encode failure): the probe is advisory, and the
        caller treats unknown as 'maybe feasible'."""
        try:
            enc_pa = self.pe.encode(pod)
            pa = {n: a for n, a in enc_pa.items() if not n.startswith("_")}
            ctx = self.whatif_context(pa)
            tj = ctx.template_index(pa)
            return ctx.gang_fits(tj, int(k))
        except Exception:  # noqa: BLE001 — advisory probe, never fatal
            return None

    def check_whatif_fault(self) -> None:
        """Injector seam for the what-if launch path (testing/faults.py
        raise-whatif)."""
        inj = self.faults
        if inj is not None:
            inj.on_whatif()

    def record_whatif_fault(self, kind: str) -> None:
        """A what-if launch faulted: count it and walk the PR 4 ladder
        (consecutive faults demote and wake the probe), but DO NOT
        invalidate the live session — the what-if ran on a scratch
        snapshot, so there is nothing to quarantine or rebuild, and
        tearing the session down would charge planning with a rebuild
        storm (the acceptance contract pins session_rebuilds_total
        unchanged by planning)."""
        from .metrics import device_faults

        device_faults.inc(kind=kind)
        tracing.event("whatif-fault", "fault", kind=kind,
                      rung=self.ladder.mode())
        from .metrics import dump_seam

        dump_seam("whatif-fault", kind=kind)
        with self._lock:
            self._whatif_cache.clear()
            self._whatif_cache_version = -1
        if self.ladder.record_fault(kind):
            logger.warning(
                "TPU backend demoted to %s after %d consecutive device "
                "faults (last: what-if %s); background probe will "
                "re-promote", self.ladder.mode(), self.ladder.threshold,
                kind,
            )
            self._notify_health(
                "Warning", "BackendDemoted",
                f"scoring backend demoted to {self.ladder.mode()} after "
                f"consecutive device faults (last: {kind})",
            )
            self._ensure_probe_thread()

    # -- ladder probe: background re-promotion -----------------------------

    def _ensure_probe_thread(self) -> None:
        with self._probe_lock:
            t = self._probe_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(
                target=self._probe_loop, name="tpu-ladder-probe", daemon=True)
            self._probe_thread = t
            t.start()

    def _probe_loop(self) -> None:
        """While demoted, periodically run a canary dispatch vouching for
        the NEXT rung up; a correct answer promotes one rung (cadence
        resets), a wrong/absent one doubles the cadence (capped) so a
        flapping device cannot whipsaw the session cache. Exits once
        fully re-promoted; a later demotion starts a fresh thread."""
        while not self._probe_stop.is_set():
            if self.ladder.rung() >= self.ladder.top:
                return
            if self._probe_stop.wait(self.ladder.probe_delay()):
                return
            ok = self._probe_device()
            if self.ladder.on_probe(ok):
                logger.warning(
                    "TPU backend re-promoted to %s after a clean probe",
                    self.ladder.mode(),
                )
                self._notify_health(
                    "Normal", "BackendPromoted",
                    f"scoring backend re-promoted to {self.ladder.mode()} "
                    f"after a clean probe",
                )
                with self._lock:
                    # the next batch must rebuild at the restored rung
                    self._invalidate_session("probe-promoted")

    def _probe_device(self) -> bool:
        """One canary with a known answer through the same fault seam as
        real dispatches (rung = the rung being vouched for)."""
        try:
            target = min(self.ladder.rung() + 1, self.ladder.top)
            inj = self.faults
            if inj is not None:
                inj.on_dispatch(rung=target, probe=True)
            import jax.numpy as jnp

            y = (jnp.arange(64, dtype=jnp.int32) * 2).sum()
            if not self._wait_ready(y, self.watchdog_timeout):
                # the probe's wait IS a device wait that hit the
                # watchdog: consume an armed wedge shot here too — at
                # the oracle rung no dispatch traffic exists to consume
                # it, and an unconsumed shot would wedge every future
                # probe (permanently demoted backend)
                if inj is not None:
                    inj.consume_wedge()
                return False
            # ktpu: allow-sync(ladder probe: the 1-element sentinel readback IS the probe)
            return int(np.asarray(y)) == 64 * 63
        except Exception:  # noqa: BLE001 — a raising probe is a failed probe
            return False

    def close(self) -> None:
        """Stop the background probe (Scheduler.shutdown)."""
        self._probe_stop.set()
        t = self._probe_thread
        if t is not None:
            t.join(timeout=2)

    # -- CacheListener (called under the cache lock) -----------------------
    # Classification contract (the session-delta design): every event is
    # one of
    #   carry-delta     — a batchable pod (no affinity terms, no host
    #                     ports) added to / removed from a KNOWN node,
    #                     whose row fits the encoding incrementally and
    #                     whose labels match no session template's IPA
    #                     term: exactly (a) a utilization row and (b) PTS
    #                     pair counts move — both ARE the session carry
    #                     (the PERF_NOTES exactness invariant), so the
    #                     event queues as a device-side patch;
    #   prologue-patch  — a node update whose fingerprint moved ONLY in
    #                     allocatable/capacity: alloc is read in-step,
    #                     never by the prologue, so the static column
    #                     patches in place;
    #   structural      — everything else (node add/remove, term/port
    #                     pods, vocab or capacity growth, volume-world
    #                     changes): the old path — session teardown, full
    #                     rebuild at the next dispatch.

    def on_add_pod(self, pod: v1.Pod, node_name: str) -> None:
        with self._lock:
            key = (pod.metadata.namespace, pod.metadata.name, node_name)
            if key in self._session_assumed:
                # the cache confirming an assume the session already
                # applied on-device: host bookkeeping only
                self._session_assumed.discard(key)
                self.enc.add_pod(pod, node_name)
                return
            if v1.pod_key(pod) in self.enc._pods:
                # duplicate add (re-add of a key the encoding already
                # holds nets a remove+add inside enc.add_pod — the old
                # row's counts are not reconstructible here)
                self._invalidate_session("foreign-pod-add")
                self.enc.add_pod(pod, node_name)
                return
            if not self._queue_pod_delta(
                pod, node_name, +1,
                lambda: self.enc.add_pod(pod, node_name),
            ):
                self._invalidate_session("foreign-pod-add")

    def on_assume_pods(self, items) -> None:
        """Batched assume-echo from the cache's columnar assume_pods: one
        listener call per harvest instead of N on_add_pod events. For
        placements this backend itself applied on-device
        (_apply_decisions_locked recorded them in _session_assumed), the
        echo's remove+re-add through enc.add_pod would be array-identical
        — the only object difference vs the decision-time pod is
        spec.node_name, which is not encoded — so the echo collapses to a
        pure stored-object swap (enc.swap_pod_object): no row encode, no
        volume refcount round-trip, no Quantity re-parse. Anything else
        (nominated placements, swap misses) falls through to the per-pod
        on_add_pod path, preserving object-path semantics exactly."""
        leftovers = None
        with self._lock:
            assumed = self._session_assumed
            enc = self.enc
            swap = enc.swap_pod_object
            for pod, node_name in items:
                key = (pod.metadata.namespace, pod.metadata.name, node_name)
                if key in assumed and swap(v1.pod_key(pod), pod, node_name):
                    assumed.discard(key)
                    continue
                if leftovers is None:
                    leftovers = []
                leftovers.append((pod, node_name))
            if leftovers:
                for pod, node_name in leftovers:
                    self.on_add_pod(pod, node_name)  # RLock: nested is fine

    def on_forget_pods(self, items) -> None:
        """Batched forget-echo (gang rollback): every member's removal
        lands under ONE backend lock acquisition, so the whole gang's
        release queues as one contiguous carry-delta batch the session
        absorbs together — the retraction dual of on_assume_pods."""
        with self._lock:
            for pod, node_name in items:
                self.on_remove_pod(pod, node_name)  # RLock: nested is fine

    def on_remove_pod(self, pod: v1.Pod, node_name: str) -> None:
        with self._lock:
            # mirror of the add path's assume-echo gate: removing a pod
            # the encoding never contained (never encoded, or bound to
            # no node) is a no-op, not a session teardown
            if not node_name or v1.pod_key(pod) not in self.enc._pods:
                return
            self._session_assumed.discard(
                (pod.metadata.namespace, pod.metadata.name, node_name)
            )
            if not self._queue_pod_delta(
                pod, node_name, -1, lambda: self.enc.remove_pod(pod),
            ):
                self._invalidate_session("pod-remove")

    def on_add_node(self, node: v1.Node) -> None:
        with self._lock:
            self._node_fps[node.metadata.name] = ClusterEncoding.node_fingerprint(node)
            lane = self.enc.add_node(node)
            if not self._queue_node_delta(lane, "node-join"):
                self._invalidate_session("node-add")

    def on_update_node(self, node: v1.Node) -> None:
        with self._lock:
            # heartbeat gate: kubelets PATCH node status every ~10s
            # (conditions + heartbeat timestamps), none of which the
            # encoding consumes — tearing down the session (and forcing
            # a full encoding rebuild) per heartbeat would make the
            # cross-batch session useless in a live cluster. Only
            # scheduling-relevant changes (labels, annotations, taints,
            # unschedulable, allocatable/capacity, images) invalidate.
            name = node.metadata.name
            fp = ClusterEncoding.node_fingerprint(node)
            old = self._node_fps.get(name)
            if old == fp:
                return
            self._node_fps[name] = fp
            if self._queue_alloc_patch(node, old, fp):
                return
            self._invalidate_session("node-update")
            self.enc.update_node(node)

    def _queue_alloc_patch(self, node: v1.Node, old, fp) -> bool:
        """Prologue-patch classification for a node update: when ONLY the
        allocatable/capacity slot of the fingerprint moved, the encoding
        updates the row in place and the live session patches its static
        alloc column — no other prologue product reads alloc (fit and
        the utilization scores consume it in-step), so nothing else
        needs recomputing. False -> caller takes the structural path."""
        sess = self._session
        if (
            not self.delta_patching
            or sess is None
            or old is None
            or len(self._deltas) >= self.max_queued_deltas
            or self.enc._rebuild_needed
            # fingerprint slots: labels, avoid-annotation, taints,
            # unschedulable, alloc, images — everything but alloc equal
            or old[:4] != fp[:4]
            or old[5] != fp[5]
        ):
            return False
        got = self.enc.update_node_alloc(node)
        if got is None:
            return False
        dalloc, dallowed = got
        if not sess.delta_compatible(dalloc, np.zeros(2, np.int64)):
            # the row is already patched in the host encoding (dirty-row
            # sync covers the next build); only the session must go
            self._invalidate_session("node-update")
            return True
        nidx = self.enc.node_index[node.metadata.name]
        self._deltas.append({
            "kind": "node-alloc", "node": nidx,
            "dalloc": dalloc, "dallowed": dallowed,
        })
        return True

    def on_remove_node(self, node_name: str) -> None:
        with self._lock:
            self._node_fps.pop(node_name, None)
            lane = self.enc.remove_node(node_name)
            if not self._queue_node_delta(lane, "node-leave"):
                self._invalidate_session("node-remove")

    def _queue_node_delta(self, lane: Optional[int], kind: str) -> bool:
        """Absorb a node add/remove into the LIVE session as a lane-column
        delta. The encoding has already decided the host half: `lane` is
        None when the event was structural there (vocab bucket growth,
        lane space exhausted, node still carrying pods). The session half
        gates itself (node_join_delta / node_leave_delta return None
        outside their exactness envelope — shared topology pairs, term
        templates, image-locality mass, conflict mode). True -> the event
        is fully reconciled; False -> the caller tears the session down
        (rebuild from the already-mutated encoding is always correct)."""
        if lane is None or not self.delta_patching:
            return False
        sess = self._session
        if sess is None:
            return True  # nothing device-resident; next build sees it
        if (
            not hasattr(sess, "node_join_delta")
            or len(self._deltas) >= self.max_queued_deltas
        ):
            return False
        try:
            if kind == "node-join":
                d = sess.node_join_delta(
                    self.enc.node_slice_cluster(lane), lane)
            else:
                d = sess.node_leave_delta(lane)
        except Exception:  # noqa: BLE001 — rebuild is always correct
            logger.warning("node delta classification failed; rebuilding",
                           exc_info=True)
            return False
        if d is None:
            return False
        self._deltas.append(d)
        return True

    # -- session-delta classification + apply ------------------------------

    def _pod_self_rows(self, pod: v1.Pod) -> Dict:
        """The pod's label/namespace bit rows at current vocab widths —
        what match_matrices_np and the term-match classifier evaluate.
        Built with get() (never intern): a label pair the vocab has
        never seen cannot appear in any compiled selector, so the zero
        sentinel is exact."""
        enc = self.enc
        pp = np.zeros(enc.pod_pair_vocab.capacity, bool)
        pk = np.zeros(enc.pod_key_vocab.capacity, bool)
        for k, val in (pod.metadata.labels or {}).items():
            kid = enc.pod_key_vocab.get(k)
            pid = enc.pod_pair_vocab.get((k, val))
            if kid:
                pk[kid] = True
            if pid:
                pp[pid] = True
        return {
            "self_ppair": pp, "self_pkey": pk,
            "self_ns": np.int32(enc.ns_vocab.get(pod.metadata.namespace)),
        }

    @staticmethod
    def _pod_structural(pod: v1.Pod) -> bool:
        """Pods whose assume/remove touches term/port tables (the exact
        complement of ops/batch.py pod_batchable, from the spec)."""
        from .framework.types import PodInfo

        pi = PodInfo(pod)
        if (
            pi.required_affinity_terms
            or pi.required_anti_affinity_terms
            or pi.preferred_affinity_terms
            or pi.preferred_anti_affinity_terms
        ):
            return True
        return any(
            port.host_port > 0
            for c in pod.spec.containers
            for port in c.ports or []
        )

    def _queue_pod_delta(self, pod: v1.Pod, node_name: str, sign: int,
                         mutate) -> bool:
        """Run `mutate` (the host-encoding update) and try to absorb the
        event into the live session as a carry delta. True -> the event
        is fully reconciled (delta queued, or no live session to
        reconcile); False -> structural, the caller tears the session
        down. The utilization delta is captured as the host ROW diff
        around the mutation, so volume attach-scalar extras and every
        other row-math subtlety transfer exactly."""
        sess = self._session
        enc = self.enc
        nidx = None
        snap = None
        if (
            self.delta_patching
            and sess is not None
            and len(self._deltas) < self.max_queued_deltas
            and not enc._rebuild_needed
            # a remove must hit the row the encoding actually holds: a
            # relocated pod (informer-wins path) removes from its STORED
            # node, which is the node_name the cache passes — verify
            and (sign > 0
                 or enc._pods.get(v1.pod_key(pod), (None, node_name))[1]
                 == node_name)
        ):
            nidx = enc.node_index.get(node_name)
            if nidx is not None:
                A = enc._arrays
                snap = (
                    A["requested"][nidx].copy(),
                    A["nz_requested"][nidx].copy(),
                    int(A["pod_count"][nidx]),
                )
        mutate()
        if sess is None:
            # nothing device-resident to reconcile; the next session
            # builds from the mutated encoding
            return True
        if snap is None or enc._rebuild_needed:
            return False  # structural: unknown node or capacity growth
        if self._pod_structural(pod):
            return False
        rows = self._pod_self_rows(pod)
        if getattr(sess, "dyn_ipa", False) and ipa_term_match_np(
                sess._term_np, rows):
            # the pod counts toward a template's own-term statics
            # (anti/aff counts, D5 score rows) — not carry-only
            return False
        A = enc._arrays
        dres = A["requested"][nidx] - snap[0]
        dnz = A["nz_requested"][nidx] - snap[1]
        dcount = int(A["pod_count"][nidx]) - snap[2]
        if not sess.delta_compatible(dres, dnz):
            return False  # pallas int32/GCD envelope
        t_n = sess._tp_np["self_ns"].shape[0]
        c_n = sess._tp_np["ptsf_op"].shape[1]
        if pod.metadata.deletion_timestamp is not None:
            # terminating pods never enter the prologue's PTS counts
            # (the ~pterm gate); only utilization moves
            mf = np.zeros((t_n, c_n), np.int32)
            ms = np.zeros((t_n, c_n), np.int32)
        else:
            mfa, msa = match_matrices_np(sess._tp_np, [rows])
            mf = mfa[:, 0, :].astype(np.int32) * sign
            ms = msa[:, 0, :].astype(np.int32) * sign
        self._deltas.append({
            "kind": "pod-add" if sign > 0 else "pod-remove",
            "node": nidx, "dres": dres, "dnz": dnz, "dcount": dcount,
            "mf": mf, "ms": ms,
        })
        return True

    def _apply_session_deltas_locked(self) -> None:
        """Flush the queued deltas into the live session in one fused
        launch — called right before a dispatch rides the session, so
        patches chain onto any in-flight scans as pure data
        dependencies. An apply failure downgrades to the structural
        path (teardown + rebuild from the already-mutated encoding) —
        never to wrong state."""
        if not self._deltas:
            return
        if self._session is None:
            self._deltas.clear()
            return
        deltas, self._deltas = self._deltas, []
        from .metrics import session_delta_applies

        try:
            with tracing.span("queued-delta-apply", "delta-apply",
                              n=len(deltas)):
                if devtime.enabled():
                    # measured delta apply: the fused patch launch gets
                    # its own submit->ready interval via an explicit
                    # block (decision-inert; the block is the
                    # documented KTPU_DEVTIME=1 measurement cost — the
                    # next dispatch would synchronize on the carry
                    # anyway)
                    import jax

                    lt = devtime.launch("kernel", "delta-apply",
                                        n=len(deltas))
                    self._session.apply_deltas(deltas)
                    # ktpu: allow-sync(devtime fence: delta-apply is timed in-window; the fence is the measurement)
                    jax.block_until_ready(
                        getattr(self._session, "_carry", None))
                    lt.done()
                    self._feed_device_time(
                        "kernel", _time.perf_counter() - lt.submit)
                else:
                    self._session.apply_deltas(deltas)
        except Exception:  # noqa: BLE001 — rebuild is always correct
            logger.warning(
                "session delta apply failed; falling back to a rebuild",
                exc_info=True,
            )
            self._invalidate_session("delta-apply-failed")
            return
        for d in deltas:
            session_delta_applies.inc(kind=d["kind"])

    # -- scheduling --------------------------------------------------------

    def schedule(self, pod: v1.Pod) -> ScheduleResult:
        """One pod against every node; raises FitError when none fit
        (generic_scheduler.go:95 Schedule semantics)."""
        with self._lock:
            # an outstanding pipelined batch must land in the encoding
            # first (its decisions are part of the ground truth this
            # dispatch evaluates against)
            self._flush_pending()
            # device_state() with dirty rows DONATES the previous device
            # buffers (encoding.py fused scatter) — exactly the statics a
            # live session still references. Tear the session down first;
            # this also covers schedule_many's bound-pod path and the
            # scheduler core's unschedulable re-dispatch (scheduler.py
            # _schedule_batch_tpu), whose enc.add_pod()s would otherwise
            # leave a surviving session's carry missing those pods.
            self._invalidate_session("single-pod-dispatch")
            try:
                p = {k: v for k, v in self.pe.encode(pod).items()
                     if not k.startswith("_")}
            except VolumeResolutionChanged:
                # gate/encode race: fail this attempt; the retry re-gates
                raise FitError(pod, self.enc.n_nodes, {})
            def attempt(p=p):
                self._check_dispatch_fault()
                c = self.enc.device_state()
                if self.mesh is not None:
                    from ..parallel import sharded

                    c = sharded.shard_cluster(c, self.mesh)
                    p = sharded.replicate_pod(p, self.mesh)
                out = schedule_pod_jit(c, p, self.weights)
                if not self._wait_ready(out, self.watchdog_timeout):
                    raise DeviceFault(
                        "single-pod dispatch exceeded the watchdog",
                        kind="timeout")
                total = np.asarray(out["total"])
                feasible = np.asarray(out["feasible"])
                if total.dtype.kind == "f" and not np.isfinite(total).all():
                    raise DeviceFault("non-finite scores", kind="invalid")
                return out, total, feasible

            # raises DeviceFault when retries exhaust or the ladder sits
            # at oracle (callers requeue; the scheduler routes the
            # re-pop through the oracle path)
            out, total, feasible = self._dispatch_with_retry(attempt)
            n_nodes = self.enc.n_nodes
            n_feasible = int(feasible.sum())
            if n_feasible == 0:
                # statuses walk the LANE space (kernel outputs are
                # lane-indexed); the FitError count stays the live count
                raise FitError(
                    pod, n_nodes, self._statuses(out, self.enc.n_lanes))
            best = self._select_host(total, feasible)
            return ScheduleResult(self.enc.node_names[best], n_nodes, n_feasible)

    def reevaluate(self, pods: List[v1.Pod]) -> List[Tuple[Optional[str], Dict]]:
        """Batched re-evaluation of FAILED pods against current state:
        per pod, (best node | None, per-node failure statuses). One
        vmapped kernel dispatch per shape group instead of a per-pod
        schedule() (each of which was a session teardown + a full
        launch over the tunnel — the r2 preemption-workload crawl).
        Statuses feed the DefaultPreemption dry-run
        (default_preemption.go:320); a pod that now fits (state moved
        since its batch was dispatched) gets its node directly."""
        from ..ops.kernel import schedule_pods_jit

        results: List[Tuple[Optional[str], Dict]] = []
        with self._lock:
            self._flush_pending()
            if self.ladder.rung() <= RUNG_ORACLE:
                # fully demoted: no device dispatch at all — the pods
                # re-gate via the queue and ride the oracle there
                return [(RETRY_NODE, {}) for _ in pods]
            # device_state() with dirty rows donates buffers a live
            # session still references — same discipline as schedule()
            self._invalidate_session("reevaluate")
            c = self.enc.device_state()
            if self.mesh is not None:
                from ..parallel import sharded

                c = sharded.shard_cluster(c, self.mesh)
            n_nodes = self.enc.n_lanes  # kernel outputs are lane-indexed
            encoded = []
            skipped = set()
            for idx, p in enumerate(pods):
                try:
                    encoded.append({
                        k: v for k, v in self.pe.encode(p).items()
                        if not k.startswith("_")
                    })
                except VolumeResolutionChanged:
                    encoded.append(None)
                    skipped.add(idx)
            # group by shape signature so each group stacks; chunk to a
            # FIXED width — the kernel's per-pod PTS/IPA sweeps are
            # [P]-sized, so an unbounded vmap width makes XLA chew on a
            # [B, P, ...] program (a 500-wide vmap at 500 nodes compiled
            # for minutes); 32-wide chunks bound the program and reuse
            # one compile across waves (rows are padded by repeating row
            # 0 — outputs for pads are discarded)
            CHUNK = 32
            out_rows: List[Tuple[Dict, int]] = [None] * len(pods)
            # group by shape via a sort (results are written back by
            # original index, so order is free): interleaved shapes must
            # not produce one padded chunk per 1-2 pods
            by_shape: Dict[Tuple, List[int]] = {}
            for idx, e in enumerate(encoded):
                if idx in skipped:
                    continue
                by_shape.setdefault(shape_signature(e), []).append(idx)
            for group in by_shape.values():
                for lo in range(0, len(group), CHUNK):
                    chunk = group[lo:lo + CHUNK]
                    pad = CHUNK - len(chunk)
                    stacked = {
                        k: np.stack(
                            [np.asarray(encoded[g][k]) for g in chunk]
                            + [np.asarray(encoded[chunk[0]][k])] * pad
                        )
                        for k in encoded[chunk[0]]
                    }
                    if self.mesh is not None:
                        from ..parallel import sharded

                        stacked = sharded.replicate_pod(stacked, self.mesh)
                    try:
                        if self.ladder.rung() <= RUNG_ORACLE:
                            continue  # demoted mid-loop: rest re-gates
                        self._check_dispatch_fault()
                        outs = schedule_pods_jit(c, stacked, self.weights)
                        if not self._wait_ready(outs, self.watchdog_timeout):
                            raise DeviceFault(
                                "re-evaluation dispatch exceeded the "
                                "watchdog", kind="timeout")
                        outs = {k: np.asarray(v) for k, v in outs.items()}
                    except DeviceFault as e:
                        # chunk pods re-gate via the queue; the retry
                        # lands after the session-rebuild/demotion the
                        # fault just triggered
                        self._device_fault_locked(e.kind)
                        continue
                    except Exception:  # noqa: BLE001 — device-path error
                        self._device_fault_locked("raise")
                        continue
                    for row, g in enumerate(chunk):
                        out_rows[g] = (outs, row)
            for g, pod in enumerate(pods):
                if g in skipped:
                    results.append((RETRY_NODE, {}))  # prompt re-gate
                    continue
                if out_rows[g] is None:
                    results.append((RETRY_NODE, {}))  # faulted chunk
                    continue
                outs, row = out_rows[g]
                feasible = outs["feasible"][row][:n_nodes]
                if feasible.any():
                    total = outs["total"][row][:n_nodes]
                    best = self._select_host(total, feasible)
                    results.append((self.enc.node_names[best], {}))
                else:
                    results.append(
                        (None, self._statuses(outs, n_nodes, row=row))
                    )
        return results

    # -- pipelined batch API -----------------------------------------------
    # The session dispatch is ASYNC (HoistedSession.schedule returns device
    # arrays without blocking; batch k+1's scan chains on k's carry as a
    # pure data dependency). dispatch_many/harvest expose that to the
    # scheduler loop's three-stage pipeline (scheduler.py): the scheduler
    # thread encodes + dispatches batch k+1, the device scans batch k
    # (double-buffered — up to max_pending enqueued scans), and the
    # completion worker harvests + assumes + binds batch k-1. Exactness
    # rides the PERF_NOTES invariant: batchable assumes touch only the
    # carry (utilization + PTS pair counts), so the prologue stays valid
    # and no host pod-table sync is needed between pipelined batches.

    def dispatch_many(self, pods: List[v1.Pod]) -> "_BatchHandle":
        """Dispatch a batch; returns a handle for harvest(). Up to
        `max_pending` batches may be outstanding (the device double
        buffer) — a dispatch beyond that harvests the OLDEST first.
        Falls back to the synchronous path (ready handle) when the batch
        can't ride the live session (bound pods, mixed shapes, unknown
        templates or no session yet — the session builds on the
        synchronous path and subsequent batches pipeline)."""
        h = _BatchHandle(list(pods))
        with self._lock:
            while len(self._pending) >= max(1, self.max_pending):
                if self.async_harvest_drain:
                    # back-pressure WITHOUT charging harvest+assume+
                    # decode to the dispatch critical path: the
                    # completion worker drains the FIFO and signals;
                    # the timeout re-checks liveness (a crashed worker
                    # is restarted by the Scheduler's supervision, and
                    # abandon_pending also signals)
                    self._pending_cv.wait(0.2)
                    continue
                self._harvest_locked()
            if pods and not self.speculation:
                # KTPU_SPECULATION=0: never chain a scan on a carry
                # whose decisions have not been harvested + validated —
                # land everything first (serializes the device)
                self._flush_pending()
            if pods and self._session is not None \
                    and self.ladder.rung() > RUNG_ORACLE and all(
                not p.spec.node_name for p in pods
            ):
                try:
                    with tracing.span("encode", "encode", n=len(pods)):
                        clean = [
                            {k: v for k, v in self.pe.encode(p).items()
                             if not k.startswith("_")}
                            for p in pods
                        ]
                except VolumeResolutionChanged:
                    clean = None  # schedule_many handles it per pod
                if clean is None:
                    h.results = self.schedule_many(pods)
                    return h
                sig0 = shape_signature(clean[0])
                if (
                    all(shape_signature(a) == sig0 for a in clean[1:])
                    and all(
                        template_fingerprint(a) in self._session._fps
                        for a in clean
                    )
                ):
                    try:
                        # queued cluster-event deltas land first (one
                        # fused launch chained on the carry) so this
                        # scan evaluates the reconciled state
                        self._apply_session_deltas_locked()
                        if self._session is None:
                            # delta apply failed: structural fallback
                            h.results = self.schedule_many(pods)
                            return h
                        self._check_dispatch_fault()
                        # span attrs (incl. the ladder-lock rung read)
                        # are only evaluated when tracing is on: the
                        # disabled dispatch path stays one predicate
                        # check per instrumentation point
                        sp = tracing.span(
                            "dispatch", "dispatch", n=len(pods),
                            rung=self.ladder.rung(),
                            speculative=bool(self._pending),
                            pipelined=True,
                            group_pos=len(self._pending),
                        ) if tracing.enabled() else tracing.NOOP_SPAN
                        with sp, devtime.TIMELINE.maybe_profile(
                                "dispatch"):
                            ys = self._session.schedule(clean)  # async
                        if devtime.enabled():
                            # submit stamps at the enqueue; harvest
                            # stamps ready after the pipeline's own
                            # wait — no extra synchronization on the
                            # dispatch path
                            h.dt = devtime.launch(
                                "kernel", "dispatch",
                                h2d_bytes=devtime.payload_bytes(clean),
                                n=len(pods),
                            )
                    except Exception:  # noqa: BLE001 — dispatch-time fault:
                        # the enqueue failed BEFORE the scan chained onto
                        # the carry, so earlier pending batches stay
                        # valid; this batch re-drives synchronously
                        # through the guarded (retrying) path
                        self._device_fault_locked("raise")
                        h.results = self.schedule_many(pods)
                        return h
                    h.ys = ys
                    if isinstance(ys, dict):
                        h.bucket = ys.get("bucket")
                    h.decide = type(self._session).decisions
                    h.conflicts = getattr(
                        type(self._session), "conflict_stats", None)
                    h.node_names = list(self.enc.node_names)
                    h.deadline = _time.monotonic() + self.watchdog_timeout
                    # chained on a not-yet-harvested carry: speculative
                    h.speculative = bool(self._pending)
                    if tracing.RECORDER.pod_level():
                        h.prov = {
                            "rung": self.ladder.mode(),
                            "session": type(self._session).__name__,
                            "build_reason": self._last_build,
                            "bucket": h.bucket,
                            "speculative": h.speculative,
                        }
                    self._pending.append(h)
                    return h
            h.results = self.schedule_many(pods)  # re-entrant: RLock
        return h

    def harvest(self, handle: "_BatchHandle") -> List[Tuple[v1.Pod, Optional[str]]]:
        ys = handle.ys
        if ys is not None and handle.results is None:
            # wait for the device OUTSIDE the backend lock: the
            # completion worker parking here must not block the
            # scheduler thread's next dispatch (the whole point of the
            # pipeline). The ys arrays are plain outputs — only the
            # carry is donated — so waiting on them unlocked is safe.
            # The wait is watchdog-bounded: a wedged device marks the
            # handle timed out and the locked harvest runs recovery.
            with tracing.span("wait", "wait", n=len(handle.group),
                              bucket=handle.bucket,
                              speculative=handle.speculative) as sp:
                if not self._wait_ready(ys, self.watchdog_timeout):
                    handle.timed_out = True
                    sp.set(timed_out=True)
        with self._lock:
            # strictly FIFO: older batches' decisions are ground truth
            # for this one — land them first
            while handle.results is None and self._pending:
                self._harvest_locked()
        assert handle.results is not None, "harvest of an abandoned handle"
        return handle.results

    def _flush_pending(self) -> None:
        """Apply every outstanding batch's assumes to the host encoding.
        MUST run (under the lock) before anything treats the encoding as
        ground truth — session rebuilds and the one-pod schedule() path —
        or the rebuilt carry would miss those pods."""
        while self._pending:
            self._harvest_locked()

    def _apply_decisions_locked(
        self, pods: List[v1.Pod], decisions: List[int],
        node_names: List[str], prov: Optional[Dict] = None,
        explain: Optional[List[Dict]] = None,
    ) -> List[Tuple[v1.Pod, Optional[str]]]:
        """Land a batch's harvested decisions in the host encoding (the
        host half of the assume; the device carry already holds them).
        `prov` carries the dispatch-time provenance for KTPU_TRACE=2
        per-pod records (rung, session kind, build reason, bucket,
        speculation) — None below level 2 keeps this loop allocation-free.
        `explain` (index-aligned with pods) adds the top-k candidate
        attribution to each pod's provenance record."""
        results: List[Tuple[v1.Pod, Optional[str]]] = []
        rec = tracing.RECORDER
        pod_level = rec.pod_level()
        live = self._session is not None
        record_assume = self._session_assumed.add
        enc_add = self.enc.add_pod
        append = results.append
        for i, (g, best) in enumerate(zip(pods, decisions)):
            if best < 0:
                append((g, None))
                node = None
            else:
                node = node_names[best]
                if live:
                    record_assume(
                        (g.metadata.namespace, g.metadata.name, node)
                    )
                enc_add(g, node)
                append((g, node))
            if pod_level:
                if explain is not None and i < len(explain):
                    rec.provenance(
                        v1.pod_key(g), node=node,
                        explain_topk=_explain_topk(explain[i], node_names),
                        **(prov or {}),
                    )
                else:
                    rec.provenance(
                        v1.pod_key(g), node=node, **(prov or {}),
                    )
        return results

    def _miss_speculative(self, handles) -> None:
        """Speculation-miss accounting for handles whose chained-on
        carry was invalidated before they could harvest."""
        from .metrics import speculative_dispatches

        n = sum(1 for h in handles if h.speculative)
        if n:
            speculative_dispatches.inc(n, outcome="miss")
            tracing.event("speculation-miss", "fault", n=n)
            for _ in range(n):
                # constant message: repeats AGGREGATE on the recorder
                # side (count bumps), so a miss storm is one event with
                # a large count, not an event flood
                self._notify_health(
                    "Warning", "SpeculationMissRedrive",
                    "speculative dispatch re-driven: the carry it "
                    "chained on was invalidated",
                )

    def _close_launch_devtime(self, h, ys) -> None:
        """Commit a dispatched batch's device-timeline record: ready is
        stamped when the pipeline's own watchdog-bounded wait returned
        (no extra synchronization — the pipeline already paid it), D2H
        bytes are the harvest outputs' array sizes (readable without
        forcing a transfer). Faulted batches never commit: their launch
        never became ready, and the fault seam dumps the timeline
        instead."""
        lt = h.dt
        if lt is None:
            return
        h.dt = None
        if not devtime.enabled():
            return  # shed mid-flight: drop, don't record a torn window
        ready = _time.perf_counter()
        lt.done(
            d2h_bytes=devtime.payload_bytes(ys) if isinstance(ys, dict)
            else 0,
            bucket=h.bucket, speculative=h.speculative,
        )
        self._feed_device_time("kernel", ready - lt.submit)

    def _harvest_locked(self) -> None:
        h = self._pending.popleft()
        self._pending_cv.notify_all()  # back-pressured dispatchers
        hsp = tracing.span("harvest", "harvest", n=len(h.group),
                           bucket=h.bucket, speculative=h.speculative)
        try:
            with hsp:
                if h.timed_out or not self._wait_ready(
                    h.ys, self.watchdog_timeout
                    if h.deadline is None
                    else h.deadline - _time.monotonic()
                ):
                    raise DeviceFault(
                        "device wait exceeded the dispatch watchdog",
                        kind="timeout")
                ys = h.ys
                if self.faults is not None:
                    ys = self.faults.corrupt_harvest(
                        ys, rung=self.ladder.rung())
                decisions = h.decide(ys)
                self._validate_decisions(decisions, len(h.node_names), ys)
        except DeviceFault as e:
            self._recover_dispatches_locked(e.kind, h)
            return
        except Exception:  # noqa: BLE001 — decode blew up on garbage
            logger.warning("harvest decode failed", exc_info=True)
            self._recover_dispatches_locked("invalid", h)
            return
        self._close_launch_devtime(h, ys)
        self.ladder.record_success()
        if h.bucket is not None:
            # the bucket proved itself (through jit while quarantined):
            # future session rebuilds may AOT it again
            self._suspect_buckets.discard(h.bucket)
        if (self.explain and self.explain_harvest
                and isinstance(ys, dict) and "expl_bits" in ys):
            try:
                h.explain = HoistedSession.explain_payload(ys)
            except Exception:  # noqa: BLE001 — attribution must never
                # fail a harvest that already produced valid decisions
                logger.warning("explain decode failed", exc_info=True)
            else:
                from .metrics import explain_harvests

                explain_harvests.inc()
        from .metrics import (
            conflict_replays,
            multipod_conflicts,
            speculative_dispatches,
        )

        if h.speculative:
            speculative_dispatches.inc(outcome="hit")
        n_conf, suffix = (
            h.conflicts(ys) if h.conflicts is not None else (0, None)
        )
        if n_conf:
            multipod_conflicts.inc(n_conf)
        if h.prov is not None:
            h.prov["spec_outcome"] = "hit" if h.speculative else None
            h.prov["conflicts"] = n_conf
        if suffix is None:
            if n_conf:
                # hoisted multipod: conflicts were replayed IN-DEVICE
                # (exact); decisions below are final
                conflict_replays.inc(n_conf)
            h.results = self._apply_decisions_locked(
                h.group, decisions, h.node_names, prov=h.prov,
                explain=h.explain)
            return
        # conflict SUFFIX (pallas/sharded multipod): pods [suffix:] were
        # left UNCOMMITTED by the kernel — the carry holds exactly the
        # committed prefix. Land the prefix, then replay the suffix
        # sequentially through the session. Any LATER pending batches
        # chained their scans on a carry missing the suffix commits AND
        # polluted it with their own — speculation misses: abandon the
        # chain, tear the session down, and re-decide them in dispatch
        # order (the PR-4 re-drive discipline, minus the fault: the
        # ladder is untouched and nothing is quarantined).
        results = self._apply_decisions_locked(
            h.group[:suffix], decisions[:suffix], h.node_names,
            prov=h.prov)
        conflict_replays.inc(len(h.group) - suffix)
        dropped = list(self._pending)
        self._pending.clear()
        self._pending_cv.notify_all()
        if dropped:
            self._miss_speculative(dropped)
            for hd in dropped:
                hd.ys = None
            self._invalidate_session("conflict-replay")
        # with no dropped batches the live session replays the suffix
        # chained on its committed-prefix carry (exact); after a drop it
        # rebuilds from the encoding, which now holds the prefix assumes
        with tracing.span("conflict-suffix-replay", "replay",
                          n=len(h.group) - suffix,
                          n_dropped=len(dropped), bucket=h.bucket):
            results.extend(self.schedule_many(h.group[suffix:]))
        h.results = results
        for hd in dropped:
            hd.results = self.schedule_many(hd.group)

    def schedule_many(self, pods: List[v1.Pod]) -> List[Tuple[v1.Pod, Optional[str]]]:
        """Batched sequential scheduling: groups batchable same-shape pods
        into single scan dispatches (ops/batch.py); falls back to per-pod
        dispatch for pods whose assume mutates term/port tables. Decisions
        are applied to the encoding as if each pod was assumed; callers
        MUST follow up with cache.assume_pod for each bound pod (which
        re-syncs the same rows idempotently via the listener hooks)."""
        results: List[Tuple[v1.Pod, Optional[str]]] = []
        with self._lock:
            self._flush_pending()
            i = 0
            while i < len(pods):
                pod = pods[i]
                try:
                    p = self.pe.encode(pod)
                except VolumeResolutionChanged:
                    results.append((pod, RETRY_NODE))  # prompt re-gate
                    i += 1
                    continue
                # bound pods (spec.nodeName already set) go one-at-a-time;
                # everything else — including affinity/host-port pods,
                # whose assume effects the session carries dynamically
                # (ops/hoisted.py term machinery) — rides the batch path
                if pod.spec.node_name:
                    try:
                        # schedule() invalidates the session at entry, so the
                        # term/port-table writes of this add_pod cannot leak
                        # into a stale device carry.
                        r = self.schedule(pod)
                        node = r.suggested_host
                        # NOTE: never mutate the caller's pod (it aliases the
                        # informer cache); the node rides the result tuple and
                        # enc.add_pod takes the node explicitly
                        self.enc.add_pod(pod, node)
                        results.append((pod, node))
                    except FitError:
                        results.append((pod, None))
                    except DeviceFault:
                        # single-pod retries exhausted: back to the
                        # queue exactly once (prompt re-gate); the
                        # ladder already recorded the faults
                        results.append((pod, RETRY_NODE))
                    i += 1
                    continue
                # group a maximal run of pending, shape-identical pods
                group = [pod]
                arrays = [p]
                sig = shape_signature({k: v for k, v in p.items() if not k.startswith("_")})
                j = i + 1
                while j < len(pods):
                    if pods[j].spec.node_name:
                        break
                    try:
                        q = self.pe.encode(pods[j])
                    except VolumeResolutionChanged:
                        break  # handled when the outer loop reaches j
                    qa = {k: v for k, v in q.items() if not k.startswith("_")}
                    if shape_signature(qa) != sig:
                        break
                    group.append(pods[j])
                    arrays.append(q)
                    j += 1

                # pending pods: the template-hoisted SESSION — carry
                # stays on-device across batches and scheduler cycles;
                # prologue is paid only when the session is torn down
                # by a foreign cluster mutation or a new template.
                # NOTE: no device_state() here — with dirty rows the
                # fused scatter DONATES the old device arrays, which
                # are exactly the live session's statics (the session
                # is self-consistent without the sync; its exactness
                # argument is in ops/hoisted.py)
                sp = tracing.span(
                    "dispatch-sync", "dispatch", n=len(group),
                    rung=self.ladder.rung(), pipelined=False,
                ) if tracing.enabled() else tracing.NOOP_SPAN
                with sp:
                    decisions = self._session_schedule_guarded([
                        {k: v for k, v in a.items()
                         if not k.startswith("_")}
                        for a in arrays
                    ])
                if decisions is None:
                    # retries exhausted (or fully demoted): the whole
                    # group re-gates via the queue exactly once; while
                    # the ladder sits at oracle the scheduler routes the
                    # re-pop through _schedule_one_oracle
                    results.extend((g, RETRY_NODE) for g in group)
                    i = j
                    continue
                prov = None
                if tracing.RECORDER.pod_level():
                    prov = {
                        "rung": self.ladder.mode(),
                        "session": type(self._session).__name__
                        if self._session is not None else "",
                        "build_reason": self._last_build,
                        "speculative": False,
                    }
                results.extend(self._apply_decisions_locked(
                    group, decisions, self.enc.node_names, prov=prov))
                i = j
        return results

    def _session_schedule(self, arrays: List[Dict]) -> List[int]:
        """Schedule a batchable pending group through the cross-cycle
        session, (re)building it when torn down or when a new template
        fingerprint appears."""
        fps = [template_fingerprint(a) for a in arrays]
        uniq: Dict = {}
        for fp, a in zip(fps, arrays):
            uniq.setdefault(fp, a)
        if len(uniq) > self.MAX_SESSION_TEMPLATES:
            # one batch alone exceeds the session template budget: a
            # one-shot hoisted dispatch. The device_state() sync may
            # donate buffers a live session still references, so tear
            # the session down first
            from ..ops.hoisted import schedule_batch_hoisted

            self._invalidate_session("template-overflow")
            cluster = self.enc.device_state()
            if self.mesh is not None:
                from ..parallel import sharded

                cluster = sharded.shard_cluster(cluster, self.mesh)
            decisions, _ = schedule_batch_hoisted(
                cluster, arrays, self.weights
            )
            return decisions
        # an encoding rebuild (vocab/table growth) changes array shapes;
        # cached templates from before the rebuild can no longer stack
        # with the incoming batch — evict them
        sig = shape_signature(arrays[0])
        stale = [
            fp for fp, a in self._known_templates.items()
            if shape_signature(a) != sig
        ]
        if stale:
            for fp in stale:
                del self._known_templates[fp]
            self._invalidate_session("shape-change")
        new = [fp for fp in uniq if fp not in self._known_templates]
        if new:
            for fp in new:
                self._known_templates[fp] = uniq[fp]
            # evict oldest templates NOT used by this batch (keeps the
            # hot set; clearing everything would thrash a workload that
            # alternates template sets)
            while len(self._known_templates) > self.MAX_SESSION_TEMPLATES:
                for old in list(self._known_templates):
                    if old not in uniq:
                        del self._known_templates[old]
                        break
                else:
                    break
            self._invalidate_session("new-template")
        if self._session is None:
            self._session = self._build_session()
        else:
            # a surviving session may carry queued cluster-event deltas:
            # reconcile before this scan chains on the carry (a FRESH
            # build needs none — the encoding it built from already
            # holds every mutation, and _invalidate_session cleared the
            # queue)
            self._apply_session_deltas_locked()
            if self._session is None:  # apply failed -> rebuild now
                self._session = self._build_session()
        from .metrics import conflict_replays, multipod_conflicts

        decisions: List[int] = []
        while arrays:
            ys = self._session.schedule(arrays)
            # decisions() decodes through np.asarray, an UNBOUNDED device
            # wait — bound it with the watchdog first or the synchronous
            # re-decide path (fault recovery!) could hang on the very
            # device wedge it is recovering from, with the backend lock
            # held
            if not self._wait_ready(ys, self.watchdog_timeout):
                raise DeviceFault(
                    "synchronous dispatch exceeded the watchdog",
                    kind="timeout")
            got = type(self._session).decisions(ys)
            stats = getattr(type(self._session), "conflict_stats", None)
            n_conf, suffix = stats(ys) if stats is not None else (0, None)
            if n_conf:
                multipod_conflicts.inc(n_conf)
            if suffix is None:
                if n_conf:
                    # hoisted multipod: conflicts replayed IN-DEVICE
                    conflict_replays.inc(n_conf)
                decisions.extend(got)
                break
            # conflict-SUFFIX contract (pallas/sharded multipod): pods
            # [suffix:] were left UNCOMMITTED by the kernel — keep the
            # prefix and replay exactly the suffix through the live
            # session, whose carry holds the committed prefix. The step
            # algebra guarantees a batch's FIRST pod never conflicts
            # (its eval ran against the very carry it commits to), so
            # every round lands at least one pod and the loop
            # terminates; a suffix of 0 would mean that invariant broke
            # — fail loudly as a device fault rather than loop.
            if suffix <= 0:
                raise DeviceFault(
                    "conflict suffix at batch head (kernel invariant "
                    "violation)", kind="invalid")
            conflict_replays.inc(len(arrays) - suffix)
            decisions.extend(got[:suffix])
            arrays = arrays[suffix:]
        return decisions

    def _build_session(self):
        """Span-wrapped _build_session_impl: records the build as a
        "session" span (builds are the seconds-scale cost rebuild storms
        are made of) and pins the session-kind/rebuild-reason pair the
        per-pod provenance records report."""
        with tracing.span("session-build", "session",
                          reason=self._last_invalidate) as sp:
            s = self._build_session_impl()
            self._last_build = (
                f"{type(s).__name__}/{self._last_invalidate or 'initial'}"
            )
            sp.set(kind=type(s).__name__)
            up, self._upload_seconds = self._upload_seconds, 0.0
            if up:
                # the impl measured the cluster upload before the
                # session kind existed; the slug comes from the session
                # it became
                self._feed_device_time("transfer", up, session=s)
            return s

    def _build_session_impl(self):
        """Pallas single-launch session when the cluster shape supports it
        (ops/pallas_scan.py), else the jnp lax.scan session — identical
        decisions either way (tests/test_pallas_scan.py). Downgrades are
        LOUD: a pallas->hoisted fallback costs ~2.4x throughput, so every
        build is counted in scheduler_tpu_session_builds_total{kind,reason}
        and downgrades are logged."""
        from .metrics import session_builds

        sh = self._shards_label()
        templates = list(self._known_templates.values())
        if devtime.enabled():
            # the cluster upload is the H2D transfer the mesh rows care
            # about: measured with an explicit block (decision-inert —
            # the session constructor would synchronize on these arrays
            # anyway), byte count from the uploaded leaves
            import jax

            lt = devtime.launch("transfer", "session-upload")
            cluster = self.enc.device_state()
            # ktpu: allow-sync(devtime fence: session upload timed at build, not on the dispatch path)
            jax.block_until_ready(cluster)
            lt.h2d_bytes = devtime.payload_bytes(cluster)
            lt.done()
            self._upload_seconds = _time.perf_counter() - lt.submit
        else:
            cluster = self.enc.device_state()
        # KTPU_EXPLAIN (or an armed shadow sentinel): per-plugin
        # attribution exists only on the hoisted session's scan outputs
        # — pallas/sharded builds demote, loudly, for as long as the
        # knob is on (the decisions themselves stay bit-identical; the
        # throughput cost is the explain mode's price)
        explain_k = self.explain_topk if self.explain else 0
        if explain_k:
            if self.mesh is not None:
                from ..parallel import sharded

                session_builds.inc(kind="hoisted", reason="explain", shards=sh)
                return HoistedSession(
                    sharded.shard_cluster(cluster, self.mesh),
                    templates, self.weights, explain_k=explain_k,
                )
            if self.use_pallas:
                logger.warning(
                    "explain mode: hoisted session instead of pallas")
            session_builds.inc(kind="hoisted", reason="explain", shards=sh)
            return HoistedSession(
                cluster, templates, self.weights, explain_k=explain_k)
        # degradation ladder: a DEMOTED backend (rung below the
        # platform's top — NOT merely a platform whose top is hoisted)
        # builds the hoisted session even on a TPU; the probe loop
        # re-promotes and invalidates, so the NEXT build climbs back
        demoted = self.ladder.rung() < self.ladder.top
        if self.mesh is not None and demoted:
            session_builds.inc(kind="hoisted", reason="mesh-ladder-demoted",
                               shards=sh)
            from ..parallel import sharded

            return HoistedSession(
                sharded.shard_cluster(cluster, self.mesh),
                templates, self.weights,
            )
        if self.mesh is not None:
            # two-phase sharded session (ops/sharded_scan.py): the pallas
            # session's exact math with node-sharded carries and ICI
            # scalar collectives — the mesh path no longer pays the
            # hoisted tax (term templates included; VERDICT r4 #2)
            from ..ops.pallas_scan import PallasUnsupported
            from ..ops.sharded_scan import ShardedPallasSession

            try:
                s = ShardedPallasSession(
                    cluster, templates, self.weights, mesh=self.mesh)
                session_builds.inc(kind="pallas", reason="mesh-sharded", shards=sh)
                return s
            except PallasUnsupported as e:
                logger.warning(
                    "sharded two-phase session unsupported for this "
                    "workload shape (%s); mesh rides the GSPMD hoisted "
                    "session", e,
                )
                # mesh- prefix: a mesh downgrade is a different (bigger)
                # throughput cliff than a single-chip one — alerting must
                # tell them apart; slugs stay bounded
                session_builds.inc(kind="hoisted",
                                   reason=f"mesh-{e.reason}", shards=sh)
            from ..parallel import sharded

            return HoistedSession(
                sharded.shard_cluster(cluster, self.mesh),
                templates, self.weights,
            )
        if self.use_pallas and demoted:
            logger.warning(
                "ladder-demoted session build: %s instead of pallas",
                self.ladder.mode(),
            )
            session_builds.inc(kind="hoisted", reason="ladder-demoted", shards=sh)
        elif self.use_pallas:
            from ..ops.pallas_scan import PallasSession, PallasUnsupported

            try:
                s = PallasSession(cluster, templates, self.weights)
                # re-apply the fault quarantine: suspect buckets stay
                # jit-only on the rebuilt session until they harvest
                # cleanly again
                for b in self._suspect_buckets:
                    s.retire_exec(bucket=b)
                session_builds.inc(kind="pallas", reason="", shards=sh)
                # AOT-warm the ragged-tail batch buckets OFF the serving
                # path: a daemon thread populates the (persistent)
                # compile caches so a mid-window first-tail batch never
                # pays a fresh Mosaic compile
                threading.Thread(
                    target=s.warm_buckets, name="pallas-bucket-warm",
                    daemon=True,
                ).start()
                return s
            except PallasUnsupported as e:
                logger.warning(
                    "pallas scan unsupported for this workload shape (%s); "
                    "downgrading to the jnp hoisted session (~2.4x slower)", e,
                )
                session_builds.inc(kind="hoisted", reason=e.reason, shards=sh)
        else:
            session_builds.inc(kind="hoisted", reason="platform is not tpu",
                               shards=sh)
        return HoistedSession(cluster, templates, self.weights)

    # -- helpers -----------------------------------------------------------

    def _select_host(self, total: np.ndarray, feasible: np.ndarray) -> int:
        """selectHost, FIRST-MAX tie-break — the TPU build's convention on
        every kernel path (single-pod here; batch scan via jnp.argmax,
        ops/batch.py; pallas via explicit min-index-among-maxima,
        ops/pallas_scan.py:727; sharded via the same argmax under GSPMD).

        The reference reservoir-samples ties (generic_scheduler.go:152) —
        any tie member is a correct decision, but a randomized pick can
        never be bit-reproducible across differently-batched paths, so
        the deterministic lowest-index maximum is the A/B convention and
        the oracle is pinned to it in the parity harnesses
        (tests/test_kernel_parity.py first-max oracle,
        tests/test_hoisted_terms.py _sequential_reference). The oracle
        BACKEND (scheduler backend="oracle") keeps reference reservoir
        semantics."""
        masked = np.where(feasible, total, np.iinfo(np.int64).min)
        return int(np.argmax(masked))

    def _statuses(
        self, out: Dict, n_nodes: int, row: Optional[int] = None
    ) -> Dict[str, Status]:
        """row selects one pod of a batched (vmapped) output."""
        statuses: Dict[str, Status] = {}

        def arr(key):
            a = np.asarray(out[key])
            return a[row] if row is not None else a

        masks = {k: arr(k) for k, _ in MASK_PLUGINS}
        pts_unres = arr("pts_unresolvable")
        ipa_unres = arr("ipa_unresolvable")
        names = self.enc.node_names
        for i in range(n_nodes):
            if i >= len(names) or names[i] is None:
                continue  # tombstoned lane: no node to report on
            failed = [name for key, name in MASK_PLUGINS if not masks[key][i]]
            if not failed:
                continue
            unresolvable = (
                ("PodTopologySpread" in failed and pts_unres[i])
                or ("InterPodAffinity" in failed and ipa_unres[i])
                or "NodeName" in failed
                or "NodeAffinity" in failed
            )
            reasons = [f"{name}" for name in failed]
            statuses[names[i]] = (
                Status.unschedulable_and_unresolvable(*reasons)
                if unresolvable
                else Status.unschedulable(*reasons)
            )
        return statuses
