"""Backend degradation ladder: pallas -> hoisted -> oracle under faults.

The TPU scoring backend assumes the device answers; production hardware
does not always oblige (preempted chips, XLA runtime errors, hung
collectives). The ladder is the containment policy for PERSISTENT device
faults: after `threshold` consecutive faults the backend demotes one
rung — pallas (single-launch Mosaic scan) -> hoisted (jnp lax.scan) ->
oracle (host Go-semantics path, no device at all) — and keeps scheduling
at the lower rung instead of crash-looping the pipeline. A background
probe (tpu_backend.TPUBackend._probe_loop) re-runs a canary dispatch with
a known answer; when the device answers correctly again the ladder
promotes one rung back, with the probe cadence backing off (capped, full
jitter) while the device stays sick so a flapping chip cannot whipsaw the
session cache.

The active rung is exported as the `scheduler_backend_mode` gauge
(2=pallas, 1=hoisted, 0=oracle); demotions/promotions also count on the
ladder object itself for drills (scripts/fault_drill.py).

One transient fault never demotes: the dispatch retry path (bounded
attempts, capped exponential backoff + jitter — mirroring the
controllers/manager.Supervisor restart policy) absorbs it, and a clean
harvest resets the consecutive-fault count.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Tuple

from ..utils import tracing
from .metrics import (
    backend_mode,
    overload_level,
    overload_restores,
    overload_sheds,
)

# ladder rungs, ordered: demotion decrements, promotion increments
RUNG_ORACLE = 0  # host Go-semantics path; no device dispatch at all
RUNG_HOISTED = 1  # jnp lax.scan session (the ~2.4x-slower fallback)
RUNG_PALLAS = 2  # single-launch Mosaic scan (real-TPU fast path)

RUNG_NAMES = {RUNG_ORACLE: "oracle", RUNG_HOISTED: "hoisted",
              RUNG_PALLAS: "pallas"}


class DeviceFault(Exception):
    """A device dispatch failed: the launch raised, the wait exceeded the
    watchdog, or the harvested payload failed the finite/in-range guard.
    `kind` feeds the scheduler_device_faults_total counter."""

    def __init__(self, message: str = "", kind: str = "raise"):
        super().__init__(message)
        self.kind = kind


class DegradationLadder:
    """Fault accounting + rung state machine; thread-safe (dispatches,
    the completion worker, and the probe thread all touch it)."""

    def __init__(
        self,
        top: int = RUNG_PALLAS,
        threshold: int = 3,
        probe_interval: float = 1.0,
        probe_max: float = 30.0,
        rng: Optional[random.Random] = None,
    ):
        self.top = top
        self.threshold = max(1, threshold)
        self._rung = top
        self._consecutive = 0
        self._probe_interval = probe_interval
        self._probe_max = probe_max
        self._probe_delay = probe_interval
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self.demotions = 0
        self.promotions = 0
        # transition history for drills + flight-recorder dumps:
        # (monotonic time, "demote" | "promote", new rung). Bounded.
        self.transitions: List[Tuple[float, str, int]] = []
        backend_mode.set(self._rung)

    # -- state -------------------------------------------------------------

    def rung(self) -> int:
        with self._lock:
            return self._rung

    def mode(self) -> str:
        return RUNG_NAMES[self.rung()]

    def healthy(self) -> bool:
        with self._lock:
            return self._rung >= self.top and self._consecutive == 0

    # -- fault accounting --------------------------------------------------

    def record_fault(self, kind: str = "raise") -> bool:
        """One device fault; returns True when THIS fault crossed the
        demotion threshold (the caller logs + starts the probe). The
        counter is consecutive: any clean harvest resets it."""
        with self._lock:
            self._consecutive += 1
            if self._consecutive >= self.threshold and self._rung > RUNG_ORACLE:
                self._demote_locked()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._rung >= self.top:
                # genuinely healthy at the top rung: restore the probe
                # cadence (promotion alone does NOT — see on_probe)
                self._probe_delay = self._probe_interval

    def demote(self) -> bool:
        """Unconditional demotion (pipeline-stall escape hatch: a drain
        that exceeds even the watchdog-bounded budget)."""
        with self._lock:
            if self._rung <= RUNG_ORACLE:
                return False
            self._demote_locked()
            return True

    def _demote_locked(self) -> None:
        self._rung -= 1
        self.demotions += 1
        self._consecutive = 0
        self._record_transition_locked("demote")
        # flap hysteresis: each demotion doubles the probe cadence
        # (capped). The probe canary vouches for the DEVICE, not for the
        # kernel at the target rung — a kernel-level fault (garbage from
        # one workload shape) passes the probe, re-promotes, and faults
        # again; without this the demote/promote cycle would whipsaw at
        # probe_interval forever. With it the flap rate decays to once
        # per probe_max.
        self._probe_delay = min(self._probe_delay * 2, self._probe_max)
        backend_mode.set(self._rung)

    # -- probe / re-promotion ----------------------------------------------

    def probe_delay(self) -> float:
        """Next probe wait: current backoff with full jitter."""
        with self._lock:
            return self._probe_delay * (1 + self._rng.random())

    def on_probe(self, ok: bool) -> bool:
        """Probe verdict. A clean canary promotes ONE rung (stepwise —
        pallas confidence is rebuilt through hoisted, not assumed); a
        failed one doubles the cadence (capped). Promotion does NOT
        restore the cadence — only a clean harvest at the top rung does
        (record_success) — so a workload that faults right after every
        re-promotion keeps the backed-off cadence and the flapping stays
        bounded."""
        with self._lock:
            if ok:
                if self._rung >= self.top:
                    return False
                self._rung += 1
                self.promotions += 1
                self._consecutive = 0
                self._record_transition_locked("promote")
                backend_mode.set(self._rung)
                return True
            self._probe_delay = min(self._probe_delay * 2, self._probe_max)
            return False

    def _record_transition_locked(self, kind: str) -> None:
        """Ledger + flight-recorder marker for a rung change (the event
        the dump timeline anchors a demotion's surrounding spans to)."""
        self.transitions.append((time.monotonic(), kind, self._rung))
        del self.transitions[:-64]  # bounded
        tracing.event(f"ladder-{kind}", "fault",
                      rung=RUNG_NAMES[self._rung])


class OverloadMonitor:
    """Host-side overload detection + adaptive shedding — the HOST dual
    of the device-fault ladder above.

    The ladder handles a sick DEVICE; this handles a drowning HOST: the
    PR-8 stage attribution showed completion (assume/bind, plus the
    optional audit work riding it) is the largest stage, so when the
    host falls behind the completion FIFO ages and queue depth climbs
    with no device fault in sight. The monitor watches those signals
    once per completed batch and, under SUSTAINED pressure, sheds
    strictly OPTIONAL work in a fixed order:

        explain-harvest -> shadow-sample -> trace -> speculation

    Decision correctness is never shed — every lever changes how much
    observability/overlap the host pays for, never which node a pod
    lands on. Restore is hysteretic and LIFO (last shed, first
    restored): shedding needs `shed_dwell` consecutive hot ticks,
    restoring needs `restore_dwell` consecutive calm ticks, a tick in
    the dead band between the high and low water marks resets both
    streaks, and `cooldown` seconds must separate any two transitions —
    so a load level that hovers at the threshold cannot flap a lever.

    Levers are (name, shed_fn, restore_fn) closures supplied by the
    scheduler; the monitor owns only the policy. Thread-safety: `observe`
    is called from the completion worker, but everything is locked so
    drills/tests can poke it from other threads.
    """

    def __init__(
        self,
        levers,
        *,
        high_fifo_age: float = 0.5,
        low_fifo_age: float = 0.1,
        high_queue_depth: int = 512,
        low_queue_depth: int = 128,
        high_stage_p99: float = 0.0,
        low_stage_p99: float = 0.0,
        shed_dwell: int = 3,
        restore_dwell: int = 8,
        cooldown: float = 1.0,
        now=time.monotonic,
        on_shed=None,
        on_restore=None,
    ):
        self.levers = list(levers)
        self.high_fifo_age = high_fifo_age
        self.low_fifo_age = low_fifo_age
        self.high_queue_depth = high_queue_depth
        self.low_queue_depth = low_queue_depth
        # stage-p99 signal is opt-in (0 = disabled): per-stage latency is
        # workload-shaped, so the deployment picks the water marks
        self.high_stage_p99 = high_stage_p99
        self.low_stage_p99 = (
            low_stage_p99 if low_stage_p99 > 0 else high_stage_p99 / 2
        )
        self.shed_dwell = max(1, shed_dwell)
        self.restore_dwell = max(1, restore_dwell)
        self.cooldown = cooldown
        self._now = now
        self._on_shed = on_shed
        self._on_restore = on_restore
        self._lock = threading.Lock()
        self._hot_streak = 0
        self._calm_streak = 0
        self._level = 0  # levers currently shed (prefix of self.levers)
        self._last_transition = -float("inf")
        self.triggered = False  # any shed ever fired this run
        self.cycles = 0  # completed shed->...->fully-restored cycles
        # bounded ledger: (monotonic time, "shed"|"restore", lever name,
        # {signal: value}) — the soak report prints it
        self.history: List[Tuple[float, str, str, dict]] = []
        overload_level.set(0)

    # -- state -------------------------------------------------------------

    def level(self) -> int:
        with self._lock:
            return self._level

    def shed_names(self) -> List[str]:
        with self._lock:
            return [name for name, _, _ in self.levers[: self._level]]

    # -- the per-completion tick -------------------------------------------

    def observe(
        self,
        fifo_depth: int = 0,
        fifo_age: float = 0.0,
        queue_depth: int = 0,
        stage_p99: float = 0.0,
    ) -> Optional[str]:
        """One sample of the host-pressure signals; returns the lever
        name if THIS tick shed or restored one (else None)."""
        hot = (
            fifo_age >= self.high_fifo_age
            or queue_depth >= self.high_queue_depth
            or (self.high_stage_p99 > 0 and stage_p99 >= self.high_stage_p99)
        )
        calm = (
            fifo_age <= self.low_fifo_age
            and queue_depth <= self.low_queue_depth
            and (self.high_stage_p99 <= 0 or stage_p99 <= self.low_stage_p99)
        )
        signals = {
            "fifo_depth": fifo_depth,
            "fifo_age": round(fifo_age, 4),
            "queue_depth": queue_depth,
            "stage_p99": round(stage_p99, 4),
        }
        with self._lock:
            now = self._now()
            if hot:
                self._hot_streak += 1
                self._calm_streak = 0
                if (
                    self._hot_streak >= self.shed_dwell
                    and self._level < len(self.levers)
                    and now - self._last_transition >= self.cooldown
                ):
                    return self._shed_locked(now, signals)
            elif calm:
                self._calm_streak += 1
                self._hot_streak = 0
                if (
                    self._calm_streak >= self.restore_dwell
                    and self._level > 0
                    and now - self._last_transition >= self.cooldown
                ):
                    return self._restore_locked(now, signals)
            else:
                # dead band between the water marks: hysteresis — neither
                # streak accumulates, so hovering load cannot flap
                self._hot_streak = 0
                self._calm_streak = 0
            return None

    def _shed_locked(self, now: float, signals: dict) -> str:
        name, shed_fn, _ = self.levers[self._level]
        self._level += 1
        self._hot_streak = 0
        self._calm_streak = 0
        self._last_transition = now
        self.triggered = True
        self.history.append((now, "shed", name, signals))
        del self.history[:-128]  # bounded
        overload_sheds.inc(what=name)
        overload_level.set(self._level)
        tracing.event("overload-shed", "fault", what=name, **signals)
        shed_fn()
        if self._on_shed is not None:
            self._on_shed(name, signals)
        return name

    def _restore_locked(self, now: float, signals: dict) -> str:
        self._level -= 1
        name, _, restore_fn = self.levers[self._level]
        self._hot_streak = 0
        self._calm_streak = 0
        self._last_transition = now
        self.history.append((now, "restore", name, signals))
        del self.history[:-128]  # bounded
        overload_restores.inc(what=name)
        overload_level.set(self._level)
        tracing.event("overload-restore", "fault", what=name, **signals)
        if self._level == 0:
            self.cycles += 1
        restore_fn()
        if self._on_restore is not None:
            self._on_restore(name, signals)
        return name
