"""Backend degradation ladder: pallas -> hoisted -> oracle under faults.

The TPU scoring backend assumes the device answers; production hardware
does not always oblige (preempted chips, XLA runtime errors, hung
collectives). The ladder is the containment policy for PERSISTENT device
faults: after `threshold` consecutive faults the backend demotes one
rung — pallas (single-launch Mosaic scan) -> hoisted (jnp lax.scan) ->
oracle (host Go-semantics path, no device at all) — and keeps scheduling
at the lower rung instead of crash-looping the pipeline. A background
probe (tpu_backend.TPUBackend._probe_loop) re-runs a canary dispatch with
a known answer; when the device answers correctly again the ladder
promotes one rung back, with the probe cadence backing off (capped, full
jitter) while the device stays sick so a flapping chip cannot whipsaw the
session cache.

The active rung is exported as the `scheduler_backend_mode` gauge
(2=pallas, 1=hoisted, 0=oracle); demotions/promotions also count on the
ladder object itself for drills (scripts/fault_drill.py).

One transient fault never demotes: the dispatch retry path (bounded
attempts, capped exponential backoff + jitter — mirroring the
controllers/manager.Supervisor restart policy) absorbs it, and a clean
harvest resets the consecutive-fault count.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Tuple

from ..utils import tracing
from .metrics import backend_mode

# ladder rungs, ordered: demotion decrements, promotion increments
RUNG_ORACLE = 0  # host Go-semantics path; no device dispatch at all
RUNG_HOISTED = 1  # jnp lax.scan session (the ~2.4x-slower fallback)
RUNG_PALLAS = 2  # single-launch Mosaic scan (real-TPU fast path)

RUNG_NAMES = {RUNG_ORACLE: "oracle", RUNG_HOISTED: "hoisted",
              RUNG_PALLAS: "pallas"}


class DeviceFault(Exception):
    """A device dispatch failed: the launch raised, the wait exceeded the
    watchdog, or the harvested payload failed the finite/in-range guard.
    `kind` feeds the scheduler_device_faults_total counter."""

    def __init__(self, message: str = "", kind: str = "raise"):
        super().__init__(message)
        self.kind = kind


class DegradationLadder:
    """Fault accounting + rung state machine; thread-safe (dispatches,
    the completion worker, and the probe thread all touch it)."""

    def __init__(
        self,
        top: int = RUNG_PALLAS,
        threshold: int = 3,
        probe_interval: float = 1.0,
        probe_max: float = 30.0,
        rng: Optional[random.Random] = None,
    ):
        self.top = top
        self.threshold = max(1, threshold)
        self._rung = top
        self._consecutive = 0
        self._probe_interval = probe_interval
        self._probe_max = probe_max
        self._probe_delay = probe_interval
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self.demotions = 0
        self.promotions = 0
        # transition history for drills + flight-recorder dumps:
        # (monotonic time, "demote" | "promote", new rung). Bounded.
        self.transitions: List[Tuple[float, str, int]] = []
        backend_mode.set(self._rung)

    # -- state -------------------------------------------------------------

    def rung(self) -> int:
        with self._lock:
            return self._rung

    def mode(self) -> str:
        return RUNG_NAMES[self.rung()]

    def healthy(self) -> bool:
        with self._lock:
            return self._rung >= self.top and self._consecutive == 0

    # -- fault accounting --------------------------------------------------

    def record_fault(self, kind: str = "raise") -> bool:
        """One device fault; returns True when THIS fault crossed the
        demotion threshold (the caller logs + starts the probe). The
        counter is consecutive: any clean harvest resets it."""
        with self._lock:
            self._consecutive += 1
            if self._consecutive >= self.threshold and self._rung > RUNG_ORACLE:
                self._demote_locked()
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._rung >= self.top:
                # genuinely healthy at the top rung: restore the probe
                # cadence (promotion alone does NOT — see on_probe)
                self._probe_delay = self._probe_interval

    def demote(self) -> bool:
        """Unconditional demotion (pipeline-stall escape hatch: a drain
        that exceeds even the watchdog-bounded budget)."""
        with self._lock:
            if self._rung <= RUNG_ORACLE:
                return False
            self._demote_locked()
            return True

    def _demote_locked(self) -> None:
        self._rung -= 1
        self.demotions += 1
        self._consecutive = 0
        self._record_transition_locked("demote")
        # flap hysteresis: each demotion doubles the probe cadence
        # (capped). The probe canary vouches for the DEVICE, not for the
        # kernel at the target rung — a kernel-level fault (garbage from
        # one workload shape) passes the probe, re-promotes, and faults
        # again; without this the demote/promote cycle would whipsaw at
        # probe_interval forever. With it the flap rate decays to once
        # per probe_max.
        self._probe_delay = min(self._probe_delay * 2, self._probe_max)
        backend_mode.set(self._rung)

    # -- probe / re-promotion ----------------------------------------------

    def probe_delay(self) -> float:
        """Next probe wait: current backoff with full jitter."""
        with self._lock:
            return self._probe_delay * (1 + self._rng.random())

    def on_probe(self, ok: bool) -> bool:
        """Probe verdict. A clean canary promotes ONE rung (stepwise —
        pallas confidence is rebuilt through hoisted, not assumed); a
        failed one doubles the cadence (capped). Promotion does NOT
        restore the cadence — only a clean harvest at the top rung does
        (record_success) — so a workload that faults right after every
        re-promotion keeps the backed-off cadence and the flapping stays
        bounded."""
        with self._lock:
            if ok:
                if self._rung >= self.top:
                    return False
                self._rung += 1
                self.promotions += 1
                self._consecutive = 0
                self._record_transition_locked("promote")
                backend_mode.set(self._rung)
                return True
            self._probe_delay = min(self._probe_delay * 2, self._probe_max)
            return False

    def _record_transition_locked(self, kind: str) -> None:
        """Ledger + flight-recorder marker for a rung change (the event
        the dump timeline anchors a demotion's surrounding spans to)."""
        self.transitions.append((time.monotonic(), kind, self._rung))
        del self.transitions[:-64]  # bounded
        tracing.event(f"ladder-{kind}", "fault",
                      rung=RUNG_NAMES[self._rung])
