"""HTTP scheduler extender: out-of-tree Filter/Prioritize/Bind webhooks.

Reference: pkg/scheduler/core/extender.go:42 HTTPExtender (:273 Filter,
:343 Prioritize, :380 Bind, :412 send — POST JSON per verb) and the wire
types staging/src/k8s.io/kube-scheduler/extender/v1/types.go:71
ExtenderArgs {pod, nodes|nodenames}, :86 ExtenderFilterResult
{nodes|nodenames, failedNodes, error}, :118 HostPriority {host, score},
ExtenderBindingArgs {podName, podNamespace, podUID, node}.

nodeCacheCapable extenders receive/return node NAMES only; otherwise full
node objects travel (exactly the reference's two modes).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..api import types as v1
from ..utils import serde
from .apis.config import Extender as ExtenderConfig


class ExtenderError(Exception):
    pass


class HTTPExtender:
    def __init__(self, cfg: ExtenderConfig, opener=None):
        self.cfg = cfg
        self._opener = opener or urllib.request.urlopen

    @property
    def name(self) -> str:
        return self.cfg.url_prefix

    @property
    def ignorable(self) -> bool:
        return self.cfg.ignorable

    # -- interest (extender.go:441 IsInterested) ---------------------------

    def is_interested(self, pod: v1.Pod) -> bool:
        if not self.cfg.managed_resources:
            return True
        managed = set(self.cfg.managed_resources)
        for c in list(pod.spec.containers) + list(pod.spec.init_containers or []):
            for res in (c.resources.requests or {}, c.resources.limits or {}):
                if managed.intersection(res):
                    return True
        return False

    # -- verbs -------------------------------------------------------------

    def filter(
        self, pod: v1.Pod, nodes: List[v1.Node]
    ) -> Tuple[List[v1.Node], Dict[str, str]]:
        """(feasible nodes, failed {node: reason}); extender.go:273."""
        if not self.cfg.filter_verb:
            return nodes, {}
        args = self._args(pod, nodes)
        result = self._send(self.cfg.filter_verb, args)
        if result.get("error"):
            raise ExtenderError(result["error"])
        failed = result.get("failedNodes") or {}
        if self.cfg.node_cache_capable:
            names = result.get("nodenames")
            if names is None:
                kept = [n for n in nodes if n.metadata.name not in failed]
            else:
                keep = set(names)
                kept = [n for n in nodes if n.metadata.name in keep]
        else:
            items = (result.get("nodes") or {}).get("items", None)
            if items is None:
                kept = [n for n in nodes if n.metadata.name not in failed]
            else:
                kept = [serde.from_dict(v1.Node, item) for item in items]
        return kept, dict(failed)

    def prioritize(
        self, pod: v1.Pod, nodes: List[v1.Node]
    ) -> Tuple[List[Dict], int]:
        """(HostPriorityList, weight); extender.go:343."""
        if not self.cfg.prioritize_verb:
            return [{"host": n.metadata.name, "score": 0} for n in nodes], 0
        args = self._args(pod, nodes)
        result = self._send(self.cfg.prioritize_verb, args)
        return list(result or []), self.cfg.weight

    def bind(self, pod: v1.Pod, node_name: str) -> None:
        """extender.go:380 Bind."""
        if not self.cfg.bind_verb:
            raise ExtenderError("extender has no bind verb")
        args = {
            "podName": pod.metadata.name,
            "podNamespace": pod.metadata.namespace,
            "podUID": pod.metadata.uid,
            "node": node_name,
        }
        result = self._send(self.cfg.bind_verb, args)
        if result and result.get("error"):
            raise ExtenderError(result["error"])

    def supports_bind(self) -> bool:
        return bool(self.cfg.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.cfg.preempt_verb)

    # -- wire --------------------------------------------------------------

    def _args(self, pod: v1.Pod, nodes: List[v1.Node]) -> Dict:
        args: Dict = {"pod": serde.to_dict(pod)}
        if self.cfg.node_cache_capable:
            args["nodenames"] = [n.metadata.name for n in nodes]
        else:
            args["nodes"] = {"items": [serde.to_dict(n) for n in nodes]}
        return args

    def _send(self, verb: str, args: Dict):
        url = f"{self.cfg.url_prefix.rstrip('/')}/{verb}"
        req = urllib.request.Request(
            url,
            data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with self._opener(req, timeout=self.cfg.http_timeout_seconds) as resp:
            return json.loads(resp.read().decode())
