"""Scheduler metric set (reference: pkg/scheduler/metrics/metrics.go:45-163).

Same metric names as the reference so dashboards/harnesses carry over:
schedule_attempts_total{result,profile}, e2e/algorithm duration histograms,
framework_extension_point_duration_seconds, pending_pods{queue},
scheduler_cache_size, preemption_victims/attempts.
"""

from __future__ import annotations

from ..utils.metrics import Counter, Gauge, Histogram, legacy_registry

SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"

schedule_attempts = legacy_registry.register(
    Counter(
        "scheduler_schedule_attempts_total",
        "Number of attempts to schedule pods, by result.",
        ("result", "profile"),
    )
)
e2e_scheduling_duration = legacy_registry.register(
    Histogram(
        "scheduler_e2e_scheduling_duration_seconds",
        "E2e scheduling latency (scheduling algorithm + binding).",
        ("result", "profile"),
    )
)
scheduling_algorithm_duration = legacy_registry.register(
    Histogram(
        "scheduler_scheduling_algorithm_duration_seconds",
        "Scheduling algorithm latency.",
        (),
    )
)
framework_extension_point_duration = legacy_registry.register(
    Histogram(
        "scheduler_framework_extension_point_duration_seconds",
        "Latency per scheduling framework extension point.",
        ("extension_point", "status", "profile"),
    )
)
pending_pods = legacy_registry.register(
    Gauge(
        "scheduler_pending_pods",
        "Pending pods by queue: active, backoff, unschedulable.",
        ("queue",),
    )
)
cache_size = legacy_registry.register(
    Gauge(
        "scheduler_scheduler_cache_size",
        "Scheduler cache contents by type.",
        ("type",),
    )
)
preemption_attempts = legacy_registry.register(
    Counter(
        "scheduler_preemption_attempts_total",
        "Total preemption attempts in the cluster.",
        (),
    )
)
preemption_victims = legacy_registry.register(
    Histogram(
        "scheduler_preemption_victims",
        "Number of selected preemption victims.",
        (),
        buckets=(1, 2, 4, 8, 16, 32, 64),
    )
)
batch_size = legacy_registry.register(
    Histogram(
        "scheduler_tpu_batch_size",
        "Pods per fused TPU scheduling dispatch (TPU-build metric).",
        (),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    )
)
pod_scheduling_duration = legacy_registry.register(
    Histogram(
        "scheduler_pod_scheduling_duration_seconds",
        "E2e latency for a pod being scheduled, from first attempt "
        "(queue admission) to bind sent — the metric scheduler_perf "
        "extracts Perc50/90/99 from (reference: metrics.go "
        "PodSchedulingDuration; test/integration/scheduler_perf/"
        "util.go:177-218).",
        ("attempts",),
        # metrics.go PodSchedulingDuration: ExponentialBuckets(0.001, 2, 20)
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
scheduling_attempt_duration = legacy_registry.register(
    Histogram(
        "scheduler_pod_scheduling_attempt_duration_seconds",
        "Latency of ONE scheduling attempt: queue pop to bind sent "
        "(excludes queue wait; the per-attempt half of the north-star "
        "latency metric).",
        (),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
e2e_duration = legacy_registry.register(
    Histogram(
        "scheduler_e2e_duration_seconds",
        "Kube-style e2e scheduling SLO histogram: queue admission "
        "(first attempt) to bind sent, per pod — the distribution "
        "behind the harness's pod_scheduling_p50/90/99 extracts, "
        "exposed on /metricsz so an SLO reader needs no harness. Fed "
        "from the same bind timestamps the latency sample ring uses.",
        (),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
attempt_duration = legacy_registry.register(
    Histogram(
        "scheduler_attempt_duration_seconds",
        "Per-stage scheduling SLO histogram (kube's "
        "scheduling_attempt_duration sliced by pipeline stage): "
        "stage=attempt is one attempt queue-pop->bind-sent (per pod); "
        "stage=bind is the batched bind POST (per batch); "
        "stage=complete is the completion worker's harvest+assume+bind "
        "pass (per batch); stage=fifo-wait is dispatch-enqueue->"
        "completion-finish age (per batch; the overload monitor's "
        "primary signal, as a distribution instead of a last-value "
        "gauge).",
        ("stage",),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
queue_wait = legacy_registry.register(
    Histogram(
        "scheduler_queue_wait_seconds",
        "Queue wait per scheduled pod: queue admission (first attempt "
        "timestamp) to the pop that led to its bind — e2e minus the "
        "attempt, as its own SLO distribution (kube's "
        "pod_scheduling_sli_duration decomposition).",
        (),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
device_time = legacy_registry.register(
    Counter(
        "scheduler_device_time_seconds_total",
        "Accumulated device time by kind and session slug (TPU-build "
        "metric; KTPU_DEVTIME >= 1, zero-cost and absent at 0): "
        "kind=kernel is scheduling-scan submit->ready time, "
        "kind=transfer is session-build cluster upload, kind=compile "
        "is AOT executable-cache misses. slug carries the session kind "
        "and mesh shard count ('pallas@8', 'hoisted') in the "
        "session_builds slug convention, so the mesh bench rows read "
        "collective/transfer cost PER SHARD COUNT. Rate(kernel) vs "
        "wall-clock is the device-utilization half of the overlap "
        "accounting in utils/devtime.py.",
        ("slug", "kind"),
    )
)
backend_mode = legacy_registry.register(
    Gauge(
        "scheduler_backend_mode",
        "Active scoring-backend rung of the degradation ladder "
        "(TPU-build metric): 2=pallas single-launch, 1=hoisted jnp scan, "
        "0=oracle (host Go-semantics path). Anything below the platform's "
        "top rung means the backend demoted itself after consecutive "
        "device faults and a background probe is working on re-promotion "
        "— alert on a sustained drop.",
        (),
    )
)
device_faults = legacy_registry.register(
    Counter(
        "scheduler_device_faults_total",
        "Device dispatch faults seen by the TPU backend, by kind: "
        "kind=raise (launch/dispatch raised), kind=timeout (a pending "
        "scan exceeded the dispatch watchdog — wedged device wait), "
        "kind=invalid (harvested masks/scores failed the finite/in-range "
        "guard before assume). Enough consecutive faults demote the "
        "backend one ladder rung (scheduler_backend_mode).",
        ("kind",),
    )
)
dispatch_retries = legacy_registry.register(
    Counter(
        "scheduler_dispatch_retries_total",
        "Device dispatches re-driven after a fault: session rebuild + "
        "capped exponential backoff with jitter (the Supervisor's restart "
        "policy at dispatch granularity). A retry storm without matching "
        "binds means the retry budget is being burned on a sick device.",
        (),
    )
)
worker_restarts = legacy_registry.register(
    Counter(
        "scheduler_worker_restarts_total",
        "Scheduling-pipeline worker threads (worker=scheduler | "
        "completion) restarted by the in-process supervision wrapper "
        "after a crash; the in-flight dispatch FIFO is drained back to "
        "the scheduling queue before the restart.",
        ("worker",),
    )
)
session_rebuilds = legacy_registry.register(
    Counter(
        "scheduler_session_rebuilds_total",
        "Live device sessions torn down, by WHY (TPU-build metric). "
        "Every teardown costs the next batch a full rebuild (prologue "
        "sweeps + cluster upload, ~seconds on a tunneled chip), so this "
        "counter is the rebuild-storm detector: cluster-churn reasons "
        "(foreign-pod-add, pod-remove) should be near zero now that "
        "batchable pod events apply as carry deltas "
        "(scheduler_session_delta_applies_total) — a sustained rate "
        "there means events are falling off the delta fast path. "
        "shards = mesh shard count at teardown time ('' off-mesh): at "
        "100k nodes a rebuild storm is a per-HOST cost, so alerts key "
        "on the sharded series.",
        ("reason", "shards"),
    )
)
session_delta_applies = legacy_registry.register(
    Counter(
        "scheduler_session_delta_applies_total",
        "Cluster events absorbed into the LIVE device session as "
        "incremental state deltas instead of session teardowns "
        "(TPU-build metric): kind=pod-add / pod-remove are batchable-pod "
        "carry deltas (utilization row + PTS pair-count patch), "
        "kind=node-alloc is an allocatable-only prologue patch. Each "
        "apply replaces a full rebuild on the old path.",
        ("kind",),
    )
)
session_builds = legacy_registry.register(
    Counter(
        "scheduler_tpu_session_builds_total",
        "Device session (re)builds by kernel kind (TPU-build metric): "
        "kind=pallas is the single-launch fast path; kind=hoisted is the "
        "jnp lax.scan fallback. A pallas->hoisted downgrade on a workload "
        "that previously rode pallas is a ~2.4x throughput cliff — alert "
        "on it; the build also logs the downgrade reason. shards = mesh "
        "shard count the session spans ('' off-mesh), so per-shard build "
        "rates separate mesh rebuild storms from single-chip ones.",
        ("kind", "reason", "shards"),
    )
)
mesh_shards = legacy_registry.register(
    Gauge(
        "scheduler_mesh_shards",
        "Devices in the node-axis scoring mesh (TPU-build metric): 0 = "
        "single-device dispatch (no mesh), N = every per-node array is "
        "split N ways and each host holds 1/N of the cluster encoding. "
        "Changes only at backend construction — a drop to 0 in a fleet "
        "that should be meshed means the mesh env (KTPU_MESH_DEVICES / "
        "megascale topology) regressed.",
        (),
    )
)
multipod_conflicts = legacy_registry.register(
    Counter(
        "scheduler_multipod_conflicts_total",
        "Multi-pod-step conflict DETECTIONS: a speculative decision was "
        "invalidated by an earlier pod of the same step (same-node "
        "pick, PTS/IPA count interference, or a fit/balanced/least "
        "recheck failure — the exact conflict algebra). The hoisted "
        "scan counts every conflicted pod; the pallas/sharded kernels "
        "count one per conflict SUFFIX (later flags are collateral, "
        "and genuine later conflicts are re-detected when the replayed "
        "suffix runs). Decisions stay bit-identical to "
        "one-pod-per-step either way. A detection rate near 1/k means "
        "the workload class wants a smaller KTPU_MULTIPOD_K "
        "(scripts/probe_multipod.py picks defaults).",
        (),
    )
)
conflict_replays = legacy_registry.register(
    Counter(
        "scheduler_conflict_replays_total",
        "Conflicted multi-pod-step pods re-decided sequentially: "
        "in-device lax.cond replays on the hoisted scan, host-side "
        "suffix replays through the live session on the pallas/sharded "
        "kernels (their conflicted suffix is left uncommitted and "
        "flagged). Replays are the exactness cost of multipod steps — "
        "this counter vs the step count is the effective speedup.",
        (),
    )
)
preemption_planner = legacy_registry.register(
    Counter(
        "scheduler_preemption_planner_total",
        "Preemptors planned, by planner-ladder rung (TPU-build metric): "
        "path=device is the batched what-if scan (one fused launch per "
        "preemptor over every candidate node — covers affinity/spread "
        "preemptors); path=fast is the numpy vectorized planner "
        "(resource-fit envelope); path=oracle is the per-pod "
        "DefaultPreemption dry-run via redispatch. A preemption-heavy "
        "workload sitting on path=oracle is the crawl this ladder "
        "exists to prevent — check "
        "scheduler_whatif_fallbacks_total{reason} for why.",
        ("path",),
    )
)
whatif_launches = legacy_registry.register(
    Counter(
        "scheduler_whatif_launches_total",
        "Fused what-if device launches (one per device-planned "
        "preemptor: base feasibility + the full reprieve walk across "
        "all candidate nodes). Launches never touch the live session "
        "carry — scheduler_session_rebuilds_total must not move with "
        "this counter.",
        (),
    )
)
whatif_fallbacks = legacy_registry.register(
    Counter(
        "scheduler_whatif_fallbacks_total",
        "Device-rung preemptors that fell a rung, by reason: "
        "reason=fault (device fault mid-what-if — counted in "
        "scheduler_device_faults_total and ladder-recorded, live "
        "session untouched), reason=disabled (KTPU_WHATIF=0 kill "
        "switch), reason=demoted (degradation ladder at oracle), "
        "reason=template/context/encode/node-skew (preemptor outside "
        "the what-if view), reason=error (host-side prep failure).",
        ("reason",),
    )
)
trace_dumps = legacy_registry.register(
    Counter(
        "scheduler_trace_dumps_total",
        "Flight-recorder dumps emitted at pipeline fault seams, by seam: "
        "seam=device-fault-<kind> (watchdog timeout / harvest validation "
        "/ dispatch raise), seam=pipeline-stalled (_drain_pipeline budget "
        "exceeded), seam=ladder-demoted, seam=whatif-fault, "
        "seam=worker-restart-<worker>, seam=shadow-drift (the parity "
        "sentinel caught a device decision the oracle replay disagrees "
        "with — scheduler_parity_drift_total names the plugin). Each "
        "dump snapshots the last N "
        "span events (utils/tracing.py) to the log/file before recovery "
        "proceeds — nonzero here means a fault seam fired with a "
        "triageable record attached.",
        ("seam",),
    )
)
fencing_rejections = legacy_registry.register(
    Counter(
        "scheduler_fencing_rejections_total",
        "State-changing writes the apiserver rejected because their "
        "lease fencing token was stale (different holder or an older "
        "leaseTransitions epoch than the stored leader lease), by "
        "op=bind|update_status|delete. Nonzero means a deposed leader "
        "tried to write after failover and the fence held — the "
        "split-brain double-bind that write would have been never "
        "reached the store. The healthy-path count is ZERO: the "
        "elector self-fences KTPU_LEASE_FENCE_MARGIN seconds before "
        "its lease expires, so only clock skew, a GC pause outliving "
        "the margin, or a drill's deliberate stale replay lands here.",
        ("op",),
    )
)
restart_reconcile = legacy_registry.register(
    Counter(
        "scheduler_restart_reconcile_total",
        "Pods processed by the cold-restart/promotion reconcile "
        "(authoritative store relist), by outcome: outcome=adopted "
        "(already bound — folded into the SchedulerCache as its node's "
        "tenant), outcome=requeued (unbound in-flight pod re-entered "
        "the active queue, exactly once — dedup against the queue and "
        "the drained-FIFO set), outcome=cleared (stale "
        "nominated_node_name from a preemption that never completed "
        "wiped so the slot isn't double-reserved).",
        ("outcome",),
    )
)
leader_transitions = legacy_registry.register(
    Counter(
        "scheduler_leader_transitions_total",
        "Times THIS scheduler instance was promoted to leader "
        "(lease acquired or adopted). Summed across instances it "
        "counts failovers + initial elections; a climb with no chaos "
        "running means the lease is flapping (fence margin too tight "
        "for the renew cadence, or the store is slow).",
        (),
    )
)
gang_admitted = legacy_registry.register(
    Counter(
        "scheduler_gang_admitted_total",
        "Gangs whose Permit transaction committed: every member was "
        "reserved, the gang gate flipped waiting->completed exactly "
        "once, and all members were released to bind as one batch. "
        "The all-or-nothing success count; pairs with "
        "scheduler_gang_rollbacks_total as the failure count.",
        (),
    )
)
gang_rejected = legacy_registry.register(
    Counter(
        "scheduler_gang_rejected_total",
        "Gang members bounced at Permit before reserving completed, by "
        "reason: reason=invalid (min-available < 1), reason=late (a "
        "member arrived after its gang already failed this wave — it "
        "requeues rather than camp on a dead transaction). Counted per "
        "member, not per gang; these never held a reservation.",
        ("reason",),
    )
)
gang_rollbacks = legacy_registry.register(
    Counter(
        "scheduler_gang_rollbacks_total",
        "Whole-gang rollbacks (every reserved/waiting member released "
        "and requeued as one wave), by reason: reason=timeout "
        "(KTPU_GANG_PERMIT_TIMEOUT elapsed before completion), "
        "reason=member-deleted (a waiting member was deleted "
        "mid-permit), reason=member-rejected (a Permit plugin rejected "
        "a member), reason=deadlock (the deadlock breaker backed off "
        "the youngest of mutually-blocking gangs), reason=reconcile "
        "(promotion reconcile found an orphaned gang reservation from "
        "a dead leader), reason=device-fault (a member's dispatch "
        "abandoned — the whole gang re-drives through recovery), "
        "reason=demotion (leader demoted with the gang mid-permit), "
        "reason=preempted (the gang's bound members were chosen as "
        "preemption victims — its waiting wave unwinds too). "
        "Counted once per gang per rollback.",
        ("reason",),
    )
)
gang_preempted = legacy_registry.register(
    Counter(
        "scheduler_gang_preempted_total",
        "Gangs evicted whole by gang-aware preemption: the victim scan "
        "groups same-node members into one eviction unit, and "
        "_apply_preemptions closes over the gang's off-node siblings "
        "so no partial gang survives a preemption. Counted once per "
        "gang per preemption (however many members it had).",
        (),
    )
)
gang_admission_duration = legacy_registry.register(
    Histogram(
        "scheduler_gang_admission_duration_seconds",
        "Gang admission latency: first member parked at Permit to the "
        "gang gate committing (waiting->completed). The gang-level "
        "SLO the Gang-{8,64,256} bench rows report as "
        "gang_admission_p99; one observation per admitted gang.",
        (),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)


def dump_seam(seam: str, **attrs) -> None:
    """Flight-recorder dump + scheduler_trace_dumps_total bump, PAIRED.
    Every fault seam goes through here so the counter and the dump can
    never drift apart — fault_drill's --dump-trace integrity check
    counts faults against dumps, and a seam that bumps without dumping
    (or vice versa) would silently break that accounting. The device
    timeline dumps HERE too (utils/devtime.py): a device fault leaves
    both the host span trail and the launch timeline, each gated on its
    own level. No-op with both recorders off (the rings are empty there
    and the fault path stays cheap)."""
    from ..utils import devtime, tracing

    if tracing.enabled():
        trace_dumps.inc(seam=seam)
        tracing.dump(seam, **attrs)
    if devtime.enabled():
        devtime.dump(seam, **attrs)


shadow_samples = legacy_registry.register(
    Counter(
        "scheduler_shadow_samples_total",
        "Decided pods replayed through the oracle filter/score chain by "
        "the shadow parity sentinel (KTPU_SHADOW_SAMPLE > 0): each "
        "sample re-derives the decision read-only against the "
        "decision-time cache state the completion worker already holds "
        "for assume ordering. The denominator for "
        "scheduler_parity_drift_total.",
        (),
    )
)
shadow_skips = legacy_registry.register(
    Counter(
        "scheduler_shadow_skips_total",
        "Shadow audits voided by the stale-basis gate: the cache's "
        "foreign-mutation generation advanced between dispatch and "
        "completion (informer add/update/remove, node event, TTL "
        "expiry, forget), so the oracle replay would adjudicate against "
        "state the device never decided on. A skip is lost sentinel "
        "COVERAGE, never a drift signal — sustained high skip:sample "
        "ratios mean completions lag events (see the overload monitor).",
        ("reason",),
    )
)
parity_drift = legacy_registry.register(
    Counter(
        "scheduler_parity_drift_total",
        "Shadow-sentinel mismatches between a device decision and the "
        "oracle replay, by the plugin whose filter verdict or weighted "
        "score diverged (plugin=decision when the totals disagree "
        "without a per-plugin culprit, e.g. explain attribution was "
        "unavailable). Every drift dumps the flight-recorder ring "
        "(seam=shadow-drift) and writes a repro bundle that "
        "scripts/replay_drift.py re-adjudicates offline — on chips this "
        "counter IS the continuously-measured form of the CI parity "
        "gate, so any sustained nonzero rate is a page. Informer events "
        "landing between dispatch and completion can produce isolated "
        "false positives; the bundle replay tells them apart.",
        ("plugin",),
    )
)
explain_harvests = legacy_registry.register(
    Counter(
        "scheduler_explain_harvests_total",
        "Batches harvested WITH per-pod decision attribution attached "
        "(KTPU_EXPLAIN / shadow sampling): the sessions returned "
        "per-plugin filter verdicts and weighted score splits alongside "
        "decisions. Explain mode pins the hoisted one-pod-per-step "
        "kernel (scheduler_tpu_session_builds_total reason=explain), so "
        "this counter moving on a pallas-class platform names the "
        "audit-mode throughput cost.",
        (),
    )
)
speculative_dispatches = legacy_registry.register(
    Counter(
        "scheduler_speculative_dispatches_total",
        "Batches dispatched chained on a NOT-YET-HARVESTED carry "
        "(pipelined scans enqueued while earlier batches were still in "
        "flight), by outcome: outcome=hit harvested cleanly; "
        "outcome=miss was re-driven synchronously because the carry it "
        "chained on was invalidated (device fault, harvest validation "
        "failure, a multipod conflict suffix, or a completion-worker "
        "crash abandon). KTPU_SPECULATION=0 serializes dispatch on "
        "harvest and zeroes this counter.",
        ("outcome",),
    )
)
overload_sheds = legacy_registry.register(
    Counter(
        "scheduler_overload_sheds_total",
        "Optional work SHED by the host overload monitor under sustained "
        "pressure (completion-FIFO age / queue depth / stage latency past "
        "their high-water marks for the dwell window), by lever: "
        "what=explain-harvest (host skips attribution decode), "
        "what=shadow-sample (parity-sentinel rate to 0), what=devtime "
        "(device timeline off), what=trace (flight recorder off), "
        "what=speculation (dispatch serializes on "
        "harvest). Levers shed in that fixed order and restore LIFO after "
        "a sustained-calm window — decision correctness is never shed, so "
        "this counter moving changes observability coverage, not "
        "placements. Sustained nonzero rate = the host is the "
        "bottleneck; see the paired OverloadShed k8s Events for the "
        "triggering signal values.",
        ("what",),
    )
)
overload_restores = legacy_registry.register(
    Counter(
        "scheduler_overload_restores_total",
        "Shed levers restored by the overload monitor after the calm "
        "dwell window (LIFO: last lever shed is first restored). "
        "sheds_total - restores_total = levers currently shed (also on "
        "scheduler_overload_level).",
        ("what",),
    )
)
overload_level = legacy_registry.register(
    Gauge(
        "scheduler_overload_level",
        "Number of overload-shed levers currently engaged (0 = full "
        "observability, 5 = maximally shed: explain+shadow+devtime+"
        "trace+speculation). Alert on this sitting above 0 — the host "
        "cannot "
        "keep up with the configured audit load.",
        (),
    )
)
expired_assumes = legacy_registry.register(
    Counter(
        "scheduler_cache_expired_assumes_total",
        "Assumed pods expired by the cache TTL sweep because no bind "
        "confirmation (informer add) arrived within the assume TTL. "
        "Expiry routes through the cache listeners like any other "
        "remove (live device sessions absorb it as a carry delta), but "
        "each expiry means a bind was sent and never observed — lost "
        "bind, apiserver lag, or informer stall. Production rate should "
        "be ~0; the endurance soak asserts it.",
        (),
    )
)
assumed_pods = legacy_registry.register(
    Gauge(
        "scheduler_cache_assumed_pods",
        "Pods currently in the assumed (optimistically bound, awaiting "
        "informer confirmation) state in the scheduler cache.",
        (),
    )
)
oldest_assume_age = legacy_registry.register(
    Gauge(
        "scheduler_cache_oldest_assume_seconds",
        "Age past bind-finish of the OLDEST still-assumed pod at the "
        "last TTL sweep (0 when none are overdue-tracked). The sweep "
        "runs every ~1 s, so this exceeding assume TTL + a couple of "
        "sweep periods means the expiry sweep itself is stalled — the "
        "soak's no-pod-outlives-its-TTL invariant reads this gauge.",
        (),
    )
)
completion_fifo_depth = legacy_registry.register(
    Gauge(
        "scheduler_completion_fifo_depth",
        "In-flight dispatched batches awaiting completion (the pipeline "
        "FIFO between the scheduler thread and the completion worker). "
        "Bounded by pipeline_depth; pinned at the bound = dispatch is "
        "waiting on host completion.",
        (),
    )
)
completion_fifo_age = legacy_registry.register(
    Gauge(
        "scheduler_completion_fifo_age_seconds",
        "Queue-to-completion age of the batch most recently completed: "
        "time from dispatch enqueue to completion finish. The overload "
        "monitor's primary hot signal — sustained age above the "
        "high-water mark sheds optional work "
        "(scheduler_overload_sheds_total).",
        (),
    )
)
