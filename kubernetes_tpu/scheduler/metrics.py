"""Scheduler metric set (reference: pkg/scheduler/metrics/metrics.go:45-163).

Same metric names as the reference so dashboards/harnesses carry over:
schedule_attempts_total{result,profile}, e2e/algorithm duration histograms,
framework_extension_point_duration_seconds, pending_pods{queue},
scheduler_cache_size, preemption_victims/attempts.
"""

from __future__ import annotations

from ..utils.metrics import Counter, Gauge, Histogram, legacy_registry

SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"

schedule_attempts = legacy_registry.register(
    Counter(
        "scheduler_schedule_attempts_total",
        "Number of attempts to schedule pods, by result.",
        ("result", "profile"),
    )
)
e2e_scheduling_duration = legacy_registry.register(
    Histogram(
        "scheduler_e2e_scheduling_duration_seconds",
        "E2e scheduling latency (scheduling algorithm + binding).",
        ("result", "profile"),
    )
)
scheduling_algorithm_duration = legacy_registry.register(
    Histogram(
        "scheduler_scheduling_algorithm_duration_seconds",
        "Scheduling algorithm latency.",
        (),
    )
)
framework_extension_point_duration = legacy_registry.register(
    Histogram(
        "scheduler_framework_extension_point_duration_seconds",
        "Latency per scheduling framework extension point.",
        ("extension_point", "status", "profile"),
    )
)
pending_pods = legacy_registry.register(
    Gauge(
        "scheduler_pending_pods",
        "Pending pods by queue: active, backoff, unschedulable.",
        ("queue",),
    )
)
cache_size = legacy_registry.register(
    Gauge(
        "scheduler_scheduler_cache_size",
        "Scheduler cache contents by type.",
        ("type",),
    )
)
preemption_attempts = legacy_registry.register(
    Counter(
        "scheduler_preemption_attempts_total",
        "Total preemption attempts in the cluster.",
        (),
    )
)
preemption_victims = legacy_registry.register(
    Histogram(
        "scheduler_preemption_victims",
        "Number of selected preemption victims.",
        (),
        buckets=(1, 2, 4, 8, 16, 32, 64),
    )
)
batch_size = legacy_registry.register(
    Histogram(
        "scheduler_tpu_batch_size",
        "Pods per fused TPU scheduling dispatch (TPU-build metric).",
        (),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
    )
)
pod_scheduling_duration = legacy_registry.register(
    Histogram(
        "scheduler_pod_scheduling_duration_seconds",
        "E2e latency for a pod being scheduled, from first attempt "
        "(queue admission) to bind sent — the metric scheduler_perf "
        "extracts Perc50/90/99 from (reference: metrics.go "
        "PodSchedulingDuration; test/integration/scheduler_perf/"
        "util.go:177-218).",
        ("attempts",),
        # metrics.go PodSchedulingDuration: ExponentialBuckets(0.001, 2, 20)
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
scheduling_attempt_duration = legacy_registry.register(
    Histogram(
        "scheduler_pod_scheduling_attempt_duration_seconds",
        "Latency of ONE scheduling attempt: queue pop to bind sent "
        "(excludes queue wait; the per-attempt half of the north-star "
        "latency metric).",
        (),
        buckets=tuple(0.001 * 2**i for i in range(20)),
    )
)
session_builds = legacy_registry.register(
    Counter(
        "scheduler_tpu_session_builds_total",
        "Device session (re)builds by kernel kind (TPU-build metric): "
        "kind=pallas is the single-launch fast path; kind=hoisted is the "
        "jnp lax.scan fallback. A pallas->hoisted downgrade on a workload "
        "that previously rode pallas is a ~2.4x throughput cliff — alert "
        "on it; the build also logs the downgrade reason.",
        ("kind", "reason"),
    )
)
