"""Device-side preemption planner: the top rung of the planner ladder.

Three rungs, per failed pod:

  device  — victim search as a batched what-if scan (ops/whatif.py): one
            fused launch per preemptor evaluates base feasibility and
            the exact reprieve walk for EVERY candidate node against a
            scratch copy of the session carry. Covers preemptors with
            pod (anti-)affinity terms and topology-spread constraints —
            the classes the numpy envelope must reject — because the
            session kernels already compute the IPA/PTS count
            interference the dry run needs.
  fast    — the numpy FastPreemptionPlanner (preemption.py): resource
            fit + static gates + vectorized PDB reprieve, host-side.
  oracle  — the DefaultPreemption plugin dry-run via the scheduler's
            redispatch path (per-pod filter chain).

This planner subclasses FastPreemptionPlanner so the WAVE BOOKS are one
set of state across rungs: PDB allowance tensors, the MoreImportantPod
sort, claimed-victim exclusion, and nominated-load accounting are shared
verbatim — two rungs can never double-claim a victim or disagree on the
pick-one ladder, because both read and write the same books. Node
choice, victim sets and PDB handling stay bit-identical to the Go-oracle
semantics pinned in tests/test_preemption_fast.py.

A device fault mid-what-if (launch raise, watchdog timeout) falls the
pod one rung — device -> fast (or oracle when the numpy envelope rejects
it) — through the PR 4 degradation machinery: the fault is counted and
ladder-recorded, but the LIVE session is never invalidated (the what-if
ran on a scratch snapshot; `scheduler_session_rebuilds_total` must not
move from planning).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import types as v1
from ..utils import tracing
from . import metrics
from .degradation import DeviceFault
from .plugins.defaultpreemption import Candidate
from .preemption import (
    FastPreemptionPlanner,
    WaveAntiTerms,
    _prio,
    eviction_invariant_gates,
)

logger = logging.getLogger(__name__)

# sentinel candidate: this pod must fall to the ORACLE rung (the
# scheduler routes it through the batched redispatch + DefaultPreemption)
ORACLE_FALLBACK = object()

_I64_MIN = np.iinfo(np.int64).min


def device_eligible(pod: v1.Pod, extenders: Sequence,
                    anti_terms: WaveAntiTerms) -> bool:
    """The device rung's envelope: fast_eligible WITHOUT the affinity /
    topology-spread gates (the what-if kernel evaluates those filters
    under eviction), keeping the gates eviction cannot express:
    extenders, Never-policy, a pinned spec.nodeName, host ports, PVCs,
    and existing pods whose required anti-affinity terms match the
    preemptor (a victim eviction can only DECREMENT term counts;
    un-ORing another pod's repulsion is outside the count algebra)."""
    if extenders:
        return False
    if anti_terms.matches(pod):
        return False
    return eviction_invariant_gates(pod)


class DevicePreemptionPlanner(FastPreemptionPlanner):
    """FastPreemptionPlanner books + a device what-if rung.

    `eligibility` maps pod_key -> (device_ok, fast_ok) as computed by
    the scheduler's wave partition (one WaveAntiTerms pass); pods
    missing from the map ride the fast rung (base-class behavior)."""

    def __init__(self, snapshot, nominator, backend, framework=None,
                 args: Optional[dict] = None,
                 claimed_victims: Optional[Set[str]] = None,
                 pdbs: Optional[Sequence[v1.PodDisruptionBudget]] = None,
                 eligibility: Optional[Dict[str, Tuple[bool, bool]]] = None):
        super().__init__(snapshot, nominator, framework=framework,
                         args=args, claimed_victims=claimed_victims,
                         pdbs=pdbs)
        self.backend = backend
        self.eligibility = eligibility or {}
        self.planner_paths: List[str] = []

    # -- wave books: device-side extensions --------------------------------

    def _build(self, wave: List[v1.Pod]) -> None:
        super()._build(wave)
        self.planner_paths = []
        enc = self.backend.enc
        with self.backend._lock:
            # node_index / row arrays materialize at rebuild time; a
            # fresh backend that never dispatched has neither (host-only
            # rebuild — the cached device dict is untouched)
            if enc._rebuild_needed or not enc._arrays:
                enc.rebuild()
            # pin the encoding epoch the wave books were built against:
            # concurrent churn (informer threads mutate enc under the
            # backend lock) bumps enc.version, the what-if context
            # rebuilds over the REORDERED encoding, and the lane map
            # below would attribute verdicts to the wrong nodes — the
            # per-pod launch re-checks this pin and falls a rung instead
            self._books_version = enc.version
        # memoized per-row-object match tensors: claim lists only grow
        # across a wave, and re-matching EVERY accumulated entry per
        # preemptor is the O(wave^2) trap the base class's running
        # totals exist to avoid (preemption.py _nom_sum comment)
        self._match_memo: Dict[Tuple[int, int], Tuple] = {}
        # planner (snapshot) node order -> encoding lane
        self._enc_idx = np.array(
            [enc.node_index.get(ni.node.metadata.name, -1)
             for ni in self.nodes],
            dtype=np.int64,
        )
        # victim device rows, dense by (planner node, victim slot): a
        # slot is an eviction UNIT (singleton or whole co-located gang)
        # — its request row is the members' SUM, while label rows and
        # terminating flags stay per member (match tensors and the
        # prologue's ~pterm PTS gate are per-pod facts the slot
        # aggregates at tensor-prep time)
        R = enc._arrays["requested"].shape[1] if enc._arrays else 0
        self._enc_r = R
        vm = max(self._vmax, 1)
        self._v_enc_req = np.zeros((self.n, vm, R), np.int64)
        self._v_rows: List[List[List[Dict]]] = [
            [[] for _ in range(vm)] for _ in range(self.n)
        ]
        self._v_term: List[List[List[bool]]] = [
            [[] for _ in range(vm)] for _ in range(self.n)
        ]
        for i in range(self.n):
            for j, slot_pods in enumerate(self._vpods[i]):
                for vpod in slot_pods:
                    vec, _nz = enc.pod_row_delta(vpod)
                    if vec.shape[0] == R:
                        self._v_enc_req[i, j] += vec
                    self._v_rows[i][j].append(
                        self.backend._pod_self_rows(vpod)
                    )
                    self._v_term[i][j].append(
                        vpod.metadata.deletion_timestamp is not None
                    )
        # claimed victims (earlier in-flight waves): resident in the
        # encoding but already spoken for — every what-if state drains
        # them, at topology-pair granularity (their groups span nodes)
        self._pre: List[Tuple[int, Dict, np.ndarray, bool]] = []
        for i, ni in enumerate(self.nodes):
            lane = int(self._enc_idx[i])
            if lane < 0:
                continue
            for pi in ni.pods:
                if v1.pod_key(pi.pod) not in self.claimed_victims:
                    continue
                vec, _nz = enc.pod_row_delta(pi.pod)
                self._pre.append((
                    lane, self.backend._pod_self_rows(pi.pod),
                    vec if vec.shape[0] == R else np.zeros(R, np.int64),
                    pi.pod.metadata.deletion_timestamp is not None,
                ))
        # nominated entries with pod rows (the base class keeps only
        # request vectors in planner dims); claims append here too
        self._nom_entries: List[Tuple[int, int, Dict, np.ndarray]] = []
        if self.nominator is not None:
            wave_keys = {v1.pod_key(p) for p in wave}
            for i, ni in enumerate(self.nodes):
                for np_pod in self.nominator.nominated_pods_for_node(
                    ni.node.metadata.name
                ):
                    if v1.pod_key(np_pod) in wave_keys:
                        continue
                    vec, _nz = enc.pod_row_delta(np_pod)
                    self._nom_entries.append((
                        i, _prio(np_pod),
                        self.backend._pod_self_rows(np_pod),
                        vec if vec.shape[0] == R else np.zeros(R, np.int64),
                    ))

    def _claim(self, cand: Candidate, pod: v1.Pod, prio: int,
               req: np.ndarray) -> None:
        i = self._name_to_idx[cand.node_name]
        lane = int(self._enc_idx[i]) if hasattr(self, "_enc_idx") else -1
        keys = {v1.pod_key(vp) for vp in cand.victims}
        claimed_rows = []
        if lane >= 0:
            enc = self.backend.enc
            for j, slot_pods in enumerate(self._vpods[i]):
                for m, vp in enumerate(slot_pods):
                    if v1.pod_key(vp) not in keys:
                        continue
                    # per-MEMBER request rows (the slot's _v_enc_req is
                    # the unit sum; claimed drains stay per pod)
                    vec, _nz = enc.pod_row_delta(vp)
                    claimed_rows.append((
                        lane, self._v_rows[i][j][m],
                        vec if vec.shape[0] == self._enc_r
                        else np.zeros(self._enc_r, np.int64),
                        bool(self._v_term[i][j][m]),
                    ))
        super()._claim(cand, pod, prio, req)
        # the victims just left the books; later what-ifs must drain
        # them from every state, and the preemptor is nominated load
        self._pre.extend(claimed_rows)
        if lane >= 0:
            enc = self.backend.enc
            vec, _nz = enc.pod_row_delta(pod)
            self._nom_entries.append((
                i, prio, self.backend._pod_self_rows(pod),
                vec if vec.shape[0] == self._enc_r
                else np.zeros(self._enc_r, np.int64),
            ))

    # -- per-pod rung routing ----------------------------------------------

    def _plan_one(self, pod: v1.Pod, limit: int):
        dev_ok, fast_ok = self.eligibility.get(v1.pod_key(pod),
                                               (False, True))
        if dev_ok:
            try:
                # own stage (not "planner"): this span nests inside the
                # wave-level planner span, and stage_stats sums per
                # stage — sharing the stage would double-count the
                # wave's wall-clock in the attribution tables. The
                # pod-key attr is gated on enabled(): this is per-POD
                # code, and the disabled path must not pay a string
                # build per preemptor
                sp = tracing.span(
                    "whatif", "whatif", pod=v1.pod_key(pod),
                ) if tracing.enabled() else tracing.NOOP_SPAN
                with sp:
                    fits, cand = self._plan_one_device(pod, limit)
                self.fits_now.append(fits)
                self.planner_paths.append("device")
                metrics.preemption_planner.inc(path="device")
                return cand
            except Exception as e:  # noqa: BLE001 — any device/prep
                # failure falls one rung; the wave must keep planning
                from ..ops.whatif import WhatifUnavailable

                if isinstance(e, DeviceFault):
                    reason = "fault"
                    self.backend.record_whatif_fault(e.kind)
                elif isinstance(e, WhatifUnavailable):
                    reason = e.reason
                else:
                    reason = "error"
                    logger.warning("what-if planning failed; falling back",
                                   exc_info=True)
                metrics.whatif_fallbacks.inc(reason=reason)
        if fast_ok:
            self.planner_paths.append("fast")
            return super()._plan_one(pod, limit)
        self.planner_paths.append("oracle")
        self.fits_now.append(False)
        return ORACLE_FALLBACK

    # -- the device rung ---------------------------------------------------

    def _plan_one_device(self, pod: v1.Pod, limit: int):
        """One fused what-if launch for this preemptor; returns
        (fits_now, Candidate | None). Raises WhatifUnavailable /
        DeviceFault to fall a rung."""
        from ..ops.whatif import WhatifUnavailable, slot_bucket
        from .volume_device import VolumeResolutionChanged

        backend = self.backend
        try:
            enc_pa = backend.pe.encode(pod)
        except VolumeResolutionChanged as e:
            raise WhatifUnavailable(str(e), reason="encode") from e
        pa = {k: v for k, v in enc_pa.items() if not k.startswith("_")}
        ctx = backend.whatif_context(pa)
        tj = ctx.template_index(pa)
        nps = ctx.np_slices(tj)
        prio = _prio(pod)
        req = self._req_vec(pod)
        lanes = self._enc_idx
        Ncap = ctx.n_lanes
        if (
            self.n == 0
            or (lanes < 0).any()
            or int(lanes.max()) >= Ncap
            # the lane map must describe the SAME encoding epoch the
            # context snapshotted: concurrent churn reorders lanes
            # in-range (capacities are pow2 buckets), so the version
            # pin — not the range check — is the real guard
            or backend.enc.version != self._books_version
        ):
            raise WhatifUnavailable("node table skew vs the encoding",
                                    reason="node-skew")

        # -- per-node reprieve slot order: PDB-violating group first,
        # then the rest, each in MoreImportantPod order (the oracle's
        # :633-646 walk; the split is host PDB bookkeeping shared with
        # the fast rung) --------------------------------------------------
        allC = np.arange(self.n)
        violating = self._pdb_violating(allC, prio)        # [n, Vmax]
        valid_ij = self._valive & (self._vprio < prio)     # [n, Vmax]
        js = self._vsort
        valid_sorted = np.take_along_axis(valid_ij, js, axis=1)
        vio_sorted = np.take_along_axis(violating, js, axis=1)
        max_valid = int(valid_sorted.sum(axis=1).max(initial=0))
        L = slot_bucket(max_valid)
        order_key = np.where(
            ~valid_sorted, 2, np.where(vio_sorted, 0, 1)
        )
        perm = np.argsort(order_key, axis=1, kind="stable")
        Lp = min(L, js.shape[1])
        slot_j = np.take_along_axis(js, perm, axis=1)[:, :Lp]
        slot_valid = np.take_along_axis(valid_sorted, perm, axis=1)[:, :Lp]
        slot_vio = np.take_along_axis(vio_sorted, perm, axis=1)[:, :Lp]
        if Lp < L:  # pad slots to the pow2 bucket
            pad = L - Lp
            slot_j = np.concatenate(
                [slot_j, np.zeros((self.n, pad), slot_j.dtype)], axis=1)
            slot_valid = np.concatenate(
                [slot_valid, np.zeros((self.n, pad), bool)], axis=1)
            slot_vio = np.concatenate(
                [slot_vio, np.zeros((self.n, pad), bool)], axis=1)

        # -- victim tensors in encoding-lane space: a slot aggregates
        # its unit's members (per-member match rows summed; request row
        # is the prebuilt unit sum; cnt carries the member count the
        # kernel's pod-count filter releases/re-adds per slot) ---------
        same_key = nps["f_same_key"].astype(np.int32)      # [C, C]
        C_n = same_key.shape[0]
        taa = nps["ipaaa_valid"].shape[0]
        flat_rows: List[Dict] = []
        flat_pos: List[Tuple[int, int, int]] = []  # (node, slot, member)
        for i in range(self.n):
            for s in range(L):
                if slot_valid[i, s]:
                    j = int(slot_j[i, s])
                    for m, row in enumerate(self._v_rows[i][j]):
                        flat_rows.append(row)
                        flat_pos.append((i, s, m))
        mf_flat, manti_flat, mall_flat = self._match_rows(
            ctx, nps, tj, flat_rows)
        # terminating victims never entered the PTS counts (~pterm gate)
        for b, (i, s, m) in enumerate(flat_pos):
            if self._v_term[i][int(slot_j[i, s])][m]:
                mf_flat[b] = 0
        mfs_flat = mf_flat @ same_key.T                    # [B, C]
        v = {
            "valid": np.zeros((Ncap, L), bool),
            "cnt": np.zeros((Ncap, L), np.int64),
            "req": np.zeros((Ncap, L, self._enc_r), np.int64),
            "mfs": np.zeros((Ncap, L, C_n), np.int32),
            "manti": np.zeros((Ncap, L, taa), np.int32),
            "mall": np.zeros((Ncap, L), np.int32),
        }
        for b, (i, s, m) in enumerate(flat_pos):
            lane = int(lanes[i])
            j = int(slot_j[i, s])
            if not v["valid"][lane, s]:
                v["valid"][lane, s] = True
                v["cnt"][lane, s] = self._vsize[i, j]
                v["req"][lane, s] = self._v_enc_req[i, j]
            v["mfs"][lane, s] += mfs_flat[b]
            v["manti"][lane, s] += manti_flat[b]
            v["mall"][lane, s] += mall_flat[b]

        nom = self._nom_tensors(ctx, nps, tj, prio, Ncap, C_n, taa,
                                same_key)
        pre = self._pre_tensors(ctx, nps, tj, Ncap, C_n, taa, same_key)

        # -- the launch ----------------------------------------------------
        try:
            backend.check_whatif_fault()
            metrics.whatif_launches.inc()
            ys = ctx.run(tj, v, nom, pre)
            if not backend._wait_ready(ys, backend.watchdog_timeout):
                raise DeviceFault("what-if launch exceeded the watchdog",
                                  kind="timeout")
            fits_now = np.asarray(ys["fits_now"])
            base = np.asarray(ys["base"])
            victims_dev = np.asarray(ys["victims"])
        except DeviceFault:
            raise
        except Exception as e:  # noqa: BLE001 — launch-path raise = fault
            raise DeviceFault(f"what-if launch raised: {e}",
                              kind="raise") from e

        # -- epilogue: candidate cut + pick, host-side like the fast
        # rung (snapshot order is the oracle's candidate order) -------------
        if bool(fits_now[lanes].any()):
            return True, None
        has_victims = slot_valid.any(axis=1)
        feasible = base[lanes] & has_victims
        idxs = np.flatnonzero(feasible)
        if idxs.size == 0:
            return False, None
        Cc = idxs[:limit]
        vmask = victims_dev[lanes[Cc]]                    # [Csz, L]
        vmask = vmask & slot_valid[Cc]
        sj = slot_j[Cc]
        vprio = self._vprio[Cc[:, None], sj]
        vsize = self._vsize[Cc[:, None], sj]
        # pick-ladder tallies are per POD, not per slot: a gang unit
        # contributes its member count / summed priorities / latest
        # highest-priority start
        n_vict = np.where(vmask, vsize, 0).sum(axis=1)
        n_pdbv = np.where(vmask & slot_vio[Cc], vsize, 0).sum(axis=1)
        sum_prio = np.where(
            vmask, self._vpriosum[Cc[:, None], sj], 0
        ).sum(axis=1)
        max_prio = np.where(vmask, vprio, _I64_MIN).max(
            axis=1, initial=_I64_MIN)
        hi_mask = vmask & (vprio == max_prio[:, None])
        latest = np.max(np.where(
            hi_mask, self._vlatest_hi[Cc[:, None], sj], -np.inf
        ), axis=1)
        ci = self._pick_index(n_vict > 0, n_pdbv, max_prio, sum_prio,
                              n_vict, latest)
        if ci is None:
            return False, None
        i = int(Cc[ci])
        victims = [
            vp
            for s in range(L) if vmask[ci, s]
            for vp in self._vpods[i][int(sj[ci, s])]
        ]
        cand = Candidate(
            self.nodes[i].node.metadata.name, victims,
            num_pdb_violations=int(n_pdbv[ci]),
        )
        self._claim(cand, pod, prio, req)
        return False, cand

    # -- host tensor prep helpers ------------------------------------------

    def _match_rows(self, ctx, nps, tj, rows: List[Optional[Dict]]):
        """(mf [B, C], manti [B, TAA], mall [B]) for a list of pod label
        rows against the preemptor's template. Memoized per (template,
        row-object): claim/nominated lists only GROW across a wave, and
        the books hold each row dict for the planner's lifetime, so
        later preemptors re-match only the entries their predecessors'
        claims appended — not the whole accumulated list."""
        from ..ops.hoisted import match_matrices_np
        from ..ops.whatif import ipa_victim_matches_np

        C_n = nps["f_same_key"].shape[0]
        taa = nps["ipaaa_valid"].shape[0]
        B = len(rows)
        mf = np.zeros((B, C_n), np.int32)
        manti = np.zeros((B, taa), np.int32)
        mall = np.zeros(B, np.int32)
        if B == 0:
            return mf, manti, mall
        miss = [
            b for b, r in enumerate(rows)
            if (tj, id(r)) not in self._match_memo
        ]
        if miss:
            miss_rows = [rows[b] for b in miss]
            mf_t, _ms_t = match_matrices_np(ctx.tp_np, miss_rows)
            mf_new = mf_t[tj].astype(np.int32)
            if ctx.dyn_ipa:
                manti_new, mall_new = ipa_victim_matches_np(nps, miss_rows)
            else:
                manti_new = np.zeros((len(miss), taa), np.int32)
                mall_new = np.zeros(len(miss), np.int32)
            for k, b in enumerate(miss):
                self._match_memo[(tj, id(rows[b]))] = (
                    mf_new[k], manti_new[k], mall_new[k])
        for b, r in enumerate(rows):
            mf[b], manti[b], mall[b] = self._match_memo[(tj, id(r))]
        return mf, manti, mall

    def _nom_tensors(self, ctx, nps, tj, prio, Ncap, C_n, taa, same_key):
        """Per-node aggregates of nominated pods with priority >= the
        preemptor's (framework.go:610's add set), as POSITIVE deltas."""
        entries = [e for e in self._nom_entries if e[1] >= prio]
        nom = {
            "req": np.zeros((Ncap, self._enc_r), np.int64),
            "cnt": np.zeros(Ncap, np.int64),
            "mfs": np.zeros((Ncap, C_n), np.int32),
            "manti": np.zeros((Ncap, taa), np.int32),
            "mall": np.zeros(Ncap, np.int32),
            "has_nom": bool(entries),
        }
        if not entries:
            return nom
        mf, manti, mall = self._match_rows(
            ctx, nps, tj, [e[2] for e in entries])
        mfs = mf @ same_key.T
        for b, (i, _p, _rows, vec) in enumerate(entries):
            lane = int(self._enc_idx[i])
            if lane < 0:
                continue
            nom["req"][lane] += vec
            nom["cnt"][lane] += 1
            nom["mfs"][lane] += mfs[b]
            nom["manti"][lane] += manti[b]
            nom["mall"][lane] += mall[b]
        return nom

    def _pre_tensors(self, ctx, nps, tj, Ncap, C_n, taa, same_key):
        """Already-claimed-victim drains, applied to every what-if
        state. Utilization is node-local; PTS/IPA counts drain at
        topology-PAIR granularity because a claimed victim on another
        node still empties this node's shared groups."""
        vnp = ctx.vnp
        pre = {
            "req": np.zeros((Ncap, self._enc_r), np.int64),
            "cnt": np.zeros(Ncap, np.int64),
            "shared": np.zeros((C_n, vnp), np.int32),
            "anti": np.zeros((taa, vnp), np.int32),
            "aff": np.zeros(vnp, np.int32),
            "atot": np.int32(0),
        }
        if not self._pre:
            return pre
        mf, manti, mall = self._match_rows(
            ctx, nps, tj, [e[1] for e in self._pre])
        pair_cn = nps["f_pair_cn"]  # [Ncap, C] for this template
        pok = ctx.pok_np()
        anti_keys = nps["ipaaa_key"]
        aff_keys = nps["ipaa_key"]
        aff_valid = nps["ipaa_valid"]
        raw = np.zeros((C_n, vnp), np.int32)
        for b, (lane, _rows, vec, terminating) in enumerate(self._pre):
            pre["req"][lane] += vec
            pre["cnt"][lane] += 1
            if not terminating:
                for c in range(C_n):
                    raw[c, pair_cn[lane, c]] += mf[b, c]
            if ctx.dyn_ipa:
                for t in range(taa):
                    pre["anti"][t, pok[lane, anti_keys[t]]] += manti[b, t]
                if mall[b]:
                    for t in range(aff_valid.shape[0]):
                        if aff_valid[t]:
                            pre["aff"][pok[lane, aff_keys[t]]] += 1
        pre["shared"] = (same_key @ raw).astype(np.int32)
        pre["shared"][:, 0] = 0
        pre["anti"][:, 0] = 0
        pre["aff"][0] = 0
        pre["atot"] = np.int32(pre["aff"].sum())
        return pre
