"""Scheduler internals: cache (assume protocol + incremental snapshot) and
the three-part scheduling queue (reference: pkg/scheduler/internal/)."""
