"""PodNominator: tracks preemptor pods nominated onto nodes they are
waiting to land on.

Reference: pkg/scheduler/internal/queue/scheduling_queue.go:711
nominatedPodMap — AddNominatedPod/DeleteNominatedPodIfExists/
UpdateNominatedPod + NominatedPodsForNode, consumed by
RunFilterPluginsWithNominatedPods (framework.go:610) to double-filter
against higher-priority nominated-but-unbound pods.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ...api import types as v1


class PodNominator:
    def __init__(self):
        self._lock = threading.RLock()
        self._by_node: Dict[str, List[v1.Pod]] = {}
        self._node_of: Dict[str, str] = {}  # pod key -> node name

    def add_nominated_pod(self, pod: v1.Pod, node_name: str = "") -> None:
        with self._lock:
            self._delete_locked(pod)
            node = node_name or pod.status.nominated_node_name
            if not node:
                return
            key = v1.pod_key(pod)
            self._node_of[key] = node
            self._by_node.setdefault(node, []).append(pod)

    def delete_nominated_pod_if_exists(self, pod: v1.Pod) -> None:
        with self._lock:
            self._delete_locked(pod)

    def _delete_locked(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        node = self._node_of.pop(key, None)
        if node is None:
            return
        pods = self._by_node.get(node, [])
        self._by_node[node] = [p for p in pods if v1.pod_key(p) != key]
        if not self._by_node[node]:
            del self._by_node[node]

    def update_nominated_pod(self, old: v1.Pod, new: v1.Pod) -> None:
        with self._lock:
            # preserve the nomination across updates that drop the field
            # (scheduling_queue.go:771 UpdateNominatedPod)
            node = self._node_of.get(v1.pod_key(old), "")
            self._delete_locked(old)
            target = new.status.nominated_node_name or node
            if target:
                self.add_nominated_pod(new, target)

    def nominated_pods_for_node(self, node_name: str) -> List[v1.Pod]:
        with self._lock:
            return list(self._by_node.get(node_name, []))

    def all_nominated_pods(self) -> List[v1.Pod]:
        """Every currently-nominated pod (the fast preemption planner's
        envelope check scans these for required anti-affinity terms)."""
        with self._lock:
            return [p for pods in self._by_node.values() for p in pods]
