"""Scheduling queue: activeQ + backoffQ + unschedulableQ.

Reference: pkg/scheduler/internal/queue/scheduling_queue.go:113
PriorityQueue —

  * activeQ: heap ordered by the profile's QueueSort less() (PrioritySort:
    higher .spec.priority first, then earlier timestamp;
    plugins/queuesort/priority_sort.go);
  * podBackoffQ: heap by backoff expiry; backoff = 1s * 2^attempts capped
    at 10s (:48 DefaultPodInitialBackoffDuration/DefaultPodMaxBackoff);
  * unschedulableQ: map of pods that failed scheduling, flushed to active/
    backoff by MoveAllToActiveOrBackoffQueue on cluster events (:292) or
    by the 60s leftover flusher (:60 unschedulableQTimeInterval);
  * schedulingCycle / moveRequestCycle (:120-134): a pod that failed in a
    cycle started BEFORE the last move request may have missed the event,
    so it goes to backoffQ instead of unschedulableQ (:365).

Pop blocks; flushes run lazily inside the pop wait loop (the reference
runs them on goroutine tickers — same observable behavior, no threads).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...api import types as v1
from ..framework.types import QueuedPodInfo

DEFAULT_POD_INITIAL_BACKOFF = 1.0  # scheduling_queue.go:48
DEFAULT_POD_MAX_BACKOFF = 10.0
UNSCHEDULABLE_Q_TIME_INTERVAL = 60.0  # scheduling_queue.go:60


def default_less(a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
    """PrioritySort.Less (plugins/queuesort/priority_sort.go:45).

    Equal priority ties break on pod CREATION time, then queue-entry
    time. The reference's QueuedPodInfo.Timestamp survives requeues, so
    its order is first-seen; ours is rebuilt per add, and an informer's
    initial list delivers in store-key (lexicographic) order — without
    the creation tie-break a cold-restarted scheduler would pop the
    same backlog in a different order than the instance that watched
    the pods arrive, and restart-reconcile parity (bit-identical
    assignments) breaks."""
    pa = a.pod.spec.priority or 0
    pb = b.pod.spec.priority or 0
    if pa != pb:
        return pa > pb
    ca = a.pod.metadata.creation_timestamp or a.timestamp
    cb = b.pod.metadata.creation_timestamp or b.timestamp
    if ca != cb:
        return ca < cb
    return a.timestamp < b.timestamp


class _Heap:
    """Stable heap over QueuedPodInfo with a less() comparator."""

    def __init__(self, less: Callable[[QueuedPodInfo, QueuedPodInfo], bool]):
        self._less = less
        self._seq = itertools.count()
        self._items: List[Tuple[object, QueuedPodInfo]] = []
        self._keys: Dict[str, object] = {}  # pod key -> wrapper identity

    class _Wrap:
        __slots__ = ("info", "less", "seq", "removed")

        def __init__(self, info, less, seq):
            self.info = info
            self.less = less
            self.seq = seq
            self.removed = False

        def __lt__(self, other):
            if self.less(self.info, other.info):
                return True
            if self.less(other.info, self.info):
                return False
            return self.seq < other.seq

    def push(self, info: QueuedPodInfo) -> None:
        key = v1.pod_key(info.pod)
        self.delete(info.pod)
        w = self._Wrap(info, self._less, next(self._seq))
        self._keys[key] = w
        heapq.heappush(self._items, (w, info))

    def pop(self) -> Optional[QueuedPodInfo]:
        while self._items:
            w, info = heapq.heappop(self._items)
            if not w.removed:
                del self._keys[v1.pod_key(info.pod)]
                return info
        return None

    def peek(self) -> Optional[QueuedPodInfo]:
        while self._items:
            w, info = self._items[0]
            if w.removed:
                heapq.heappop(self._items)
                continue
            return info
        return None

    def delete(self, pod: v1.Pod) -> bool:
        w = self._keys.pop(v1.pod_key(pod), None)
        if w is not None:
            w.removed = True
            return True
        return False

    def get(self, pod: v1.Pod) -> Optional[QueuedPodInfo]:
        w = self._keys.get(v1.pod_key(pod))
        return w.info if w else None

    def __contains__(self, pod_key: str) -> bool:
        return pod_key in self._keys

    def __len__(self) -> int:
        return len(self._keys)

    def items(self) -> List[QueuedPodInfo]:
        return [w.info for w in self._keys.values()]


class PriorityQueue:
    def __init__(
        self,
        less: Callable[[QueuedPodInfo, QueuedPodInfo], bool] = default_less,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        now=time.monotonic,
    ):
        self._lock = threading.Condition()
        self._now = now
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._active = _Heap(less)
        self._backoff = _Heap(self._backoff_less)
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._scheduling_cycle = 0
        self._move_request_cycle = 0
        self._closed = False
        self._last_leftover_flush = self._now()

    # -- backoff math (scheduling_queue.go:746 getBackoffTime) -------------

    def _backoff_duration(self, info: QueuedPodInfo) -> float:
        d = self._initial_backoff
        for _ in range(info.attempts - 1):
            d *= 2
            if d >= self._max_backoff:
                return self._max_backoff
        return d

    def _backoff_expiry(self, info: QueuedPodInfo) -> float:
        return info.last_failure_timestamp + self._backoff_duration(info)

    def _backoff_less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self._backoff_expiry(a) < self._backoff_expiry(b)

    def _is_backing_off(self, info: QueuedPodInfo) -> bool:
        return self._backoff_expiry(info) > self._now()

    # -- producers ---------------------------------------------------------

    def add(self, pod: v1.Pod) -> None:
        with self._lock:
            info = QueuedPodInfo(pod, timestamp=self._now())
            key = v1.pod_key(pod)
            self._backoff.delete(pod)
            self._unschedulable.pop(key, None)
            self._active.push(info)
            self._lock.notify()

    def requeue_with_backoff(self, pod: v1.Pod) -> None:
        """Failed-attempt requeue for a pod that HELD capacity (a
        rolled-back gang member): enter through the backoff heap at the
        initial backoff, not the active heap. An active-heap re-entry
        would let the rollback's own members instantly re-camp the
        capacity their rollback just released — under the gang deadlock
        breaker that is a livelock: the backed-off wave's members beat
        the stalled rival gang's pending member to every pop, the
        mutual stall re-forms, and the breaker alternates victims
        forever with zero progress."""
        with self._lock:
            key = v1.pod_key(pod)
            if (
                key in self._unschedulable
                or self._active.get(pod)
                or self._backoff.get(pod)
            ):
                return
            info = QueuedPodInfo(pod, timestamp=self._now())
            info.attempts = 1  # first backoff rung (initial_backoff)
            info.last_failure_timestamp = self._now()
            self._backoff.push(info)
            self._lock.notify()

    def add_unschedulable_if_not_present(
        self, info: QueuedPodInfo, pod_scheduling_cycle: int
    ) -> None:
        """scheduling_queue.go:365 AddUnschedulableIfNotPresent."""
        with self._lock:
            key = v1.pod_key(info.pod)
            if (
                key in self._unschedulable
                or self._active.get(info.pod)
                or self._backoff.get(info.pod)
            ):
                return
            info.last_failure_timestamp = self._now()
            if self._move_request_cycle >= pod_scheduling_cycle:
                self._backoff.push(info)
            else:
                self._unschedulable[key] = info
            self._lock.notify()

    def update(self, old_pod: Optional[v1.Pod], new_pod: v1.Pod) -> None:
        """scheduling_queue.go:445 Update: refresh in place; an update to an
        unschedulable pod that might make it schedulable moves it out."""
        with self._lock:
            info = self._active.get(new_pod)
            if info is not None:
                info.pod = new_pod
                self._active.push(info)
                return
            info = self._backoff.get(new_pod)
            if info is not None:
                info.pod = new_pod
                return
            key = v1.pod_key(new_pod)
            info = self._unschedulable.get(key)
            if info is not None:
                info.pod = new_pod
                if old_pod is not None and self._spec_changed(old_pod, new_pod):
                    del self._unschedulable[key]
                    if self._is_backing_off(info):
                        self._backoff.push(info)
                    else:
                        self._active.push(info)
                    self._lock.notify()
                return
            self._active.push(QueuedPodInfo(new_pod, timestamp=self._now()))
            self._lock.notify()

    @staticmethod
    def _spec_changed(old: v1.Pod, new: v1.Pod) -> bool:
        from ...utils import serde

        return serde.to_dict(old.spec) != serde.to_dict(new.spec) or (
            old.metadata.labels != new.metadata.labels
        )

    def delete(self, pod: v1.Pod) -> None:
        with self._lock:
            self._active.delete(pod)
            self._backoff.delete(pod)
            self._unschedulable.pop(v1.pod_key(pod), None)

    # -- cluster events (scheduling_queue.go:292) --------------------------

    def move_all_to_active_or_backoff_queue(self, event: str) -> None:
        with self._lock:
            for key, info in list(self._unschedulable.items()):
                if self._is_backing_off(info):
                    self._backoff.push(info)
                else:
                    self._active.push(info)
                del self._unschedulable[key]
            self._move_request_cycle = self._scheduling_cycle
            self._lock.notify_all()

    def activate(self, pod: v1.Pod) -> bool:
        """scheduling_queue.go Activate: move THIS pod to activeQ now,
        from wherever it is parked (unschedulableQ or backoffQ),
        skipping any remaining backoff. The scheduler calls it when an
        event provably resolves the pod's unschedulability — a nominated
        preemptor whose last victim's delete just echoed (the reference's
        queueing-hint immediate path; waiting out 2^attempts backoff
        after the victim is already gone is pure idle time — the r3
        preemption workload spent most of its 88.6s p50 pod latency
        exactly there). Returns False when the pod is not parked here
        (already active, or not yet re-added — callers handle that by
        checking pending state at add time)."""
        with self._lock:
            key = v1.pod_key(pod)
            info = self._unschedulable.pop(key, None)
            if info is None:
                info = self._backoff.get(pod)
                if info is not None:
                    self._backoff.delete(pod)
            if info is None:
                return False
            self._active.push(info)
            self._move_request_cycle = self._scheduling_cycle
            self._lock.notify_all()
            return True

    # -- consumer ----------------------------------------------------------

    @property
    def scheduling_cycle(self) -> int:
        with self._lock:
            return self._scheduling_cycle

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        """Blocks for the highest-priority active pod; counts the cycle."""
        deadline = None if timeout is None else self._now() + timeout
        with self._lock:
            while not self._closed:
                self._flush_locked()
                info = self._active.pop()
                if info is not None:
                    self._scheduling_cycle += 1
                    info.attempts += 1
                    return info
                wait = 0.1
                if deadline is not None:
                    remaining = deadline - self._now()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._lock.wait(wait)
            return None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()

    # -- flushers (lazy; reference: ticker goroutines at :257-259) ---------

    def _flush_locked(self) -> None:
        now = self._now()
        while True:
            info = self._backoff.peek()
            if info is None or self._backoff_expiry(info) > now:
                break
            self._backoff.pop()
            self._active.push(info)
        if now - self._last_leftover_flush >= UNSCHEDULABLE_Q_TIME_INTERVAL:
            self._last_leftover_flush = now
            for key, info in list(self._unschedulable.items()):
                if now - info.last_failure_timestamp >= UNSCHEDULABLE_Q_TIME_INTERVAL:
                    del self._unschedulable[key]
                    if self._is_backing_off(info):
                        self._backoff.push(info)
                    else:
                        self._active.push(info)

    # -- introspection -----------------------------------------------------

    def pending_pods(self) -> List[v1.Pod]:
        with self._lock:
            return (
                [i.pod for i in self._active.items()]
                + [i.pod for i in self._backoff.items()]
                + [i.pod for i in self._unschedulable.values()]
            )

    def num_active(self) -> int:
        with self._lock:
            return len(self._active)

    def depths(self) -> Tuple[int, int, int]:
        """(active, backoff, unschedulable) counts — the cheap form of
        pending_pods() for per-tick consumers (the overload monitor and
        the scheduler_pending_pods gauges) that must not copy the queue
        contents on every completed batch."""
        with self._lock:
            return (
                len(self._active),
                len(self._backoff),
                len(self._unschedulable),
            )
