"""Scheduler cache: authoritative in-scheduler cluster state.

Reference: pkg/scheduler/internal/cache/cache.go — the assume/confirm/
expire protocol for optimistic binding (:361 AssumePod, :415 ForgetPod,
:443 AddPod confirms, :734 cleanupAssumedPods 30s TTL) and the
generation-based incremental snapshot (:203 UpdateSnapshot: only NodeInfos
whose generation advanced since the last snapshot are re-copied; nodes form
a doubly-linked list, most-recently-updated first, so the scan stops at the
first unchanged entry).

Listeners: the TPU backend registers a CacheListener to mirror every
mutation into its dense ClusterEncoding (models/encoding.py), keeping the
device arrays in lock-step with the cache at O(changed rows) per cycle —
SURVEY.md §7 hard part (a).

Columnar hot state (KTPU_COLUMNAR_CACHE, default on): the cache keeps
per-node utilization rows, allocatable columns and pod/assumed-count
columns as numpy arrays mirroring the device encoding's layout, in
lock-step with the object-level NodeInfo map. The completion worker's
batched assume lands one harvest's decisions as a single vectorized
columnar delta (the host dual of the device-side carry-delta algebra),
and host-priced readers — the shadow sentinel's audit snapshot, the fast
preemption planner's utilization gather, min_pod_priority — read the
columnar state instead of rebuilding object snapshots. Bit-parity
contract: decisions, drift counts and expiry semantics are identical to
the object-path cache (KTPU_COLUMNAR_CACHE=0), pinned by
tests/test_columnar_cache.py and the pipeline-parity A/B.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...api import types as v1
from ...utils import knobs
from ..framework.snapshot import Snapshot
from ..framework.types import (
    ImageStateSummary,
    NodeInfo,
    PodInfo,
    calculate_resource,
)

ASSUME_EXPIRATION_SECONDS = 30.0  # cache.go durationToExpireAssumedPod


def _columnar_default() -> bool:
    return knobs.get_bool("KTPU_COLUMNAR_CACHE")


class CacheListener:
    """Mutation hooks (all called with the cache lock held)."""

    def on_add_pod(self, pod: v1.Pod, node_name: str) -> None: ...
    def on_remove_pod(self, pod: v1.Pod, node_name: str) -> None: ...
    def on_add_node(self, node: v1.Node) -> None: ...
    def on_update_node(self, node: v1.Node) -> None: ...
    def on_remove_node(self, node_name: str) -> None: ...

    def on_assume_pods(self, items: List[Tuple[v1.Pod, str]]) -> None:
        """One batched hook per assume_pods call (columnar path): the
        whole harvest's (pod, node_name) placements at once, so a
        listener can land them as one fused delta instead of N per-pod
        events. Default: per-pod on_add_pod, so listeners that only
        implement the per-pod hooks observe exactly the object-path
        event stream."""
        for pod, node_name in items:
            self.on_add_pod(pod, node_name)

    def on_forget_pods(self, items: List[Tuple[v1.Pod, str]]) -> None:
        """One batched hook per forget_pods call — the retraction dual
        of on_assume_pods: a gang rollback releases every member's
        reserved capacity at once, so a listener can land the whole
        wave as one delta batch. Default: per-pod on_remove_pod."""
        for pod, node_name in items:
            self.on_remove_pod(pod, node_name)


class _PodState:
    __slots__ = ("pod", "deadline", "binding_finished")

    def __init__(self, pod: v1.Pod):
        self.pod = pod
        self.deadline: Optional[float] = None
        self.binding_finished = False


class SchedulerCache:
    def __init__(self, ttl: float = ASSUME_EXPIRATION_SECONDS, now=time.monotonic,
                 columnar: Optional[bool] = None):
        self._lock = threading.RLock()
        self._ttl = ttl
        self._now = now
        self._pod_states: Dict[str, _PodState] = {}  # key -> state (all known pods)
        self._assumed_pods: Dict[str, bool] = {}  # key -> True
        # most-recently-updated FIRST — an OrderedDict used as the cache.go
        # doubly-linked node list (move_to_end(last=False) == moveToHead)
        self._nodes: "OrderedDict[str, NodeInfo]" = OrderedDict()
        self._listeners: List[CacheListener] = []
        # snapshot bookkeeping
        self._last_snapshot_generation: Dict[str, int] = {}
        # foreign-mutation generation: bumped by every state change that
        # did NOT originate from this scheduler's own assume protocol —
        # informer adds/updates/removes, node events, TTL expiry, forget.
        # The shadow parity sentinel compares the value it latched at
        # dispatch against the value at completion: any advance means the
        # completion-time cache is no longer the decision-time state and
        # the oracle replay would adjudicate against a world the device
        # never saw (audit skipped, counted). Own-batch assumes and bind
        # confirmations on the assumed node deliberately do NOT bump:
        # they are exactly the deltas FIFO completion already accounts
        # for.
        self._foreign_mutations = 0
        # incremental priority multiset: count per (spec.priority or 0)
        # over every cached pod, so min_pod_priority is O(distinct
        # priorities) instead of an O(all-pods) scan under the lock per
        # failure wave. Updated at every _pod_states transition.
        self._prio_counts: Dict[int, int] = {}
        # incremental image-spread index (snapshot.go
        # createImageExistenceMap): image name -> holder node names, plus
        # each node's last-seen name->size map for diffing, plus the set
        # of nodes whose NodeInfo.image_states needs re-deriving. Kept on
        # node events so update_snapshot refreshes O(changed) nodes
        # instead of rebuilding the index over ALL nodes.
        self._image_nodes: Dict[str, set] = {}
        self._node_images: Dict[str, Dict[str, int]] = {}
        self._image_dirty: set = set()
        # columnar hot state (mirrors the device encoding's row layout):
        # requested[cpu-milli, memory, ephemeral], non-zero[cpu, mem],
        # alloc[cpu-milli, memory, ephemeral, allowed-pods],
        # counts[pods, assumed]. Rows are swap-compacted on node removal;
        # capacity doubles amortized.
        self._columnar = _columnar_default() if columnar is None else columnar
        self._col_index: Dict[str, int] = {}
        self._col_names: List[str] = []
        self._col_len = 0
        self._col_cap = 0
        self._col_req = np.zeros((0, 3), np.int64)
        self._col_nz = np.zeros((0, 2), np.int64)
        self._col_alloc = np.zeros((0, 4), np.int64)
        self._col_counts = np.zeros((0, 2), np.int64)
        # audit-view clone cache: node name -> (generation, NodeInfo
        # clone). audit_view() re-clones only nodes whose generation
        # advanced — the O(changed) view the shadow sentinel reads.
        self._audit_clones: Dict[str, Tuple[int, NodeInfo]] = {}

    def add_listener(self, listener: CacheListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    @property
    def columnar(self) -> bool:
        return self._columnar

    # -- internal helpers --------------------------------------------------

    def _node_info(self, name: str) -> NodeInfo:
        ni = self._nodes.get(name)
        if ni is None:
            ni = NodeInfo()
            self._nodes[name] = ni
        return ni

    def _touch(self, name: str) -> None:
        """O(1) move-to-head (cache.go moveNodeInfoToHead)."""
        if name in self._nodes:
            self._nodes.move_to_end(name, last=False)

    def _add_pod_locked(self, pod: v1.Pod, node_name: str,
                        pod_info: Optional[PodInfo] = None,
                        res3=None) -> None:
        ni = self._node_info(node_name)
        if pod_info is None:
            pod_info = PodInfo(pod)
        if res3 is None:
            res3 = calculate_resource(pod)
        ni.add_pod_info(pod_info, res3)
        self._touch(node_name)
        if self._columnar:
            self._col_pod_delta(node_name, res3, +1)
        for l in self._listeners:
            l.on_add_pod(pod, node_name)

    def _remove_pod_locked(self, pod: v1.Pod, node_name: str) -> None:
        ni = self._nodes.get(node_name)
        if ni is not None:
            res3 = calculate_resource(pod)
            ni.remove_pod(pod, res3)
            self._touch(node_name)
            if self._columnar:
                self._col_pod_delta(node_name, res3, -1)
        for l in self._listeners:
            l.on_remove_pod(pod, node_name)

    # -- columnar row bookkeeping ------------------------------------------

    def _col_slot(self, name: str) -> int:
        i = self._col_index.get(name)
        if i is not None:
            return i
        if self._col_len == self._col_cap:
            new_cap = max(64, self._col_cap * 2)
            grow = new_cap - self._col_cap
            self._col_req = np.concatenate(
                [self._col_req, np.zeros((grow, 3), np.int64)])
            self._col_nz = np.concatenate(
                [self._col_nz, np.zeros((grow, 2), np.int64)])
            self._col_alloc = np.concatenate(
                [self._col_alloc, np.zeros((grow, 4), np.int64)])
            self._col_counts = np.concatenate(
                [self._col_counts, np.zeros((grow, 2), np.int64)])
            self._col_cap = new_cap
        i = self._col_len
        self._col_len += 1
        self._col_index[name] = i
        self._col_names.append(name)
        return i

    def _col_free(self, name: str) -> None:
        i = self._col_index.pop(name, None)
        if i is None:
            return
        last = self._col_len - 1
        if i != last:
            moved = self._col_names[last]
            self._col_req[i] = self._col_req[last]
            self._col_nz[i] = self._col_nz[last]
            self._col_alloc[i] = self._col_alloc[last]
            self._col_counts[i] = self._col_counts[last]
            self._col_names[i] = moved
            self._col_index[moved] = i
        self._col_names.pop()
        self._col_req[last] = 0
        self._col_nz[last] = 0
        self._col_alloc[last] = 0
        self._col_counts[last] = 0
        self._col_len = last

    def _col_pod_delta(self, node_name: str, res3, sign: int) -> None:
        i = self._col_slot(node_name)
        res, non0_cpu, non0_mem = res3
        self._col_req[i, 0] += sign * res.milli_cpu
        self._col_req[i, 1] += sign * res.memory
        self._col_req[i, 2] += sign * res.ephemeral_storage
        self._col_nz[i, 0] += sign * non0_cpu
        self._col_nz[i, 1] += sign * non0_mem
        self._col_counts[i, 0] += sign

    def _col_assumed_delta(self, node_name: str, delta: int) -> None:
        if not self._columnar:
            return
        i = self._col_index.get(node_name)
        if i is not None:
            self._col_counts[i, 1] += delta

    # -- priority multiset (min_pod_priority O(1)) -------------------------

    def _prio_add(self, pod: v1.Pod) -> None:
        p = pod.spec.priority or 0
        self._prio_counts[p] = self._prio_counts.get(p, 0) + 1

    def _prio_remove(self, pod: v1.Pod) -> None:
        p = pod.spec.priority or 0
        n = self._prio_counts.get(p, 0) - 1
        if n <= 0:
            self._prio_counts.pop(p, None)
        else:
            self._prio_counts[p] = n

    # -- assume protocol (cache.go:361-441) --------------------------------

    def assume_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            if key in self._pod_states:
                raise ValueError(f"pod {key} is in the cache, so can't be assumed")
            self._add_pod_locked(pod, pod.spec.node_name)
            ps = _PodState(pod)
            self._pod_states[key] = ps
            self._assumed_pods[key] = True
            self._prio_add(pod)
            self._col_assumed_delta(pod.spec.node_name, +1)

    def assume_pods(self, pods: List[v1.Pod]) -> List[bool]:
        """Batch AssumePod under ONE lock acquisition (the TPU batch path
        assumes thousands of pods per cycle; per-pod locking ping-pongs
        with the binder threads' finish_binding). Returns per-pod success;
        False = already in the cache (informer raced us), same condition
        assume_pod raises ValueError for.

        Columnar path: each pod's PodInfo and Quantity parse happen
        exactly ONCE (shared between the NodeInfo writeback and the
        columnar rows), the whole harvest lands on the columnar arrays as
        a single vectorized delta, and listeners get ONE batched
        on_assume_pods instead of N per-pod on_add_pod calls — the host
        dual of the device-side carry-delta fold."""
        if not self._columnar:
            return self._assume_pods_object(pods)
        out: List[bool] = []
        with self._lock:
            accepted: List[Tuple[v1.Pod, str]] = []
            rows: List[Tuple[int, Tuple]] = []  # (col row, res3)
            for pod in pods:
                key = v1.pod_key(pod)
                if key in self._pod_states:
                    out.append(False)
                    continue
                node_name = pod.spec.node_name
                pod_info = PodInfo(pod)
                res3 = calculate_resource(pod)
                self._node_info(node_name).add_pod_info(pod_info, res3)
                self._touch(node_name)
                self._pod_states[key] = _PodState(pod)
                self._assumed_pods[key] = True
                self._prio_add(pod)
                rows.append((self._col_slot(node_name), res3))
                accepted.append((pod, node_name))
                out.append(True)
            if accepted:
                k = len(accepted)
                idx = np.empty(k, np.int64)
                dreq = np.empty((k, 3), np.int64)
                dnz = np.empty((k, 2), np.int64)
                for j, (slot, (res, non0_cpu, non0_mem)) in enumerate(rows):
                    idx[j] = slot
                    dreq[j, 0] = res.milli_cpu
                    dreq[j, 1] = res.memory
                    dreq[j, 2] = res.ephemeral_storage
                    dnz[j, 0] = non0_cpu
                    dnz[j, 1] = non0_mem
                np.add.at(self._col_req, idx, dreq)
                np.add.at(self._col_nz, idx, dnz)
                # pods and assumed both +1 per placement
                np.add.at(self._col_counts, idx, 1)
                for l in self._listeners:
                    l.on_assume_pods(accepted)
        return out

    def _assume_pods_object(self, pods: List[v1.Pod]) -> List[bool]:
        """The per-pod object path (KTPU_COLUMNAR_CACHE=0 kill switch):
        N _add_pod_locked walks with per-pod listener events — the
        bit-parity reference the columnar path is pinned against."""
        out: List[bool] = []
        with self._lock:
            for pod in pods:
                key = v1.pod_key(pod)
                if key in self._pod_states:
                    out.append(False)
                    continue
                self._add_pod_locked(pod, pod.spec.node_name)
                self._pod_states[key] = _PodState(pod)
                self._assumed_pods[key] = True
                self._prio_add(pod)
                out.append(True)
        return out

    def finish_binding(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is not None and self._assumed_pods.get(key):
                ps.binding_finished = True
                ps.deadline = self._now() + self._ttl

    def finish_binding_many(self, pods: List[v1.Pod]) -> None:
        """Batch FinishBinding under one lock acquisition. pod_key is
        computed once per pod (it walks metadata twice per call)."""
        with self._lock:
            deadline = self._now() + self._ttl
            states = self._pod_states
            assumed = self._assumed_pods
            for pod in pods:
                key = v1.pod_key(pod)
                ps = states.get(key)
                if ps is not None and assumed.get(key):
                    ps.binding_finished = True
                    ps.deadline = deadline

    def forget_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            if self._assumed_pods.get(key):
                self._col_assumed_delta(ps.pod.spec.node_name, -1)
                self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                self._prio_remove(ps.pod)
                del self._pod_states[key]
                del self._assumed_pods[key]
                # a retracted assume breaks the FIFO accounting the
                # sentinel relies on — later in-flight batches decided
                # WITH this placement
                self._foreign_mutations += 1
            else:
                raise ValueError(f"pod {key} wasn't assumed so cannot be forgotten")

    def forget_pods(self, pods: List[v1.Pod]) -> None:
        """Batch forget_pod under ONE lock acquisition with ONE batched
        listener event (on_forget_pods): a gang rollback retracts every
        member's assumed placement as one wave, and the device-session
        listener absorbs the whole wave as one carry-delta batch
        instead of N per-pod removes. Pods not assumed (already
        forgotten, or never assumed) are skipped — rollback paths race
        informer echoes and must stay idempotent."""
        with self._lock:
            dropped: List[Tuple[v1.Pod, str]] = []
            for pod in pods:
                key = v1.pod_key(pod)
                ps = self._pod_states.get(key)
                if ps is None or not self._assumed_pods.get(key):
                    continue
                node_name = ps.pod.spec.node_name
                self._col_assumed_delta(node_name, -1)
                ni = self._nodes.get(node_name)
                if ni is not None:
                    res3 = calculate_resource(ps.pod)
                    ni.remove_pod(ps.pod, res3)
                    self._touch(node_name)
                    if self._columnar:
                        self._col_pod_delta(node_name, res3, -1)
                self._prio_remove(ps.pod)
                del self._pod_states[key]
                del self._assumed_pods[key]
                self._foreign_mutations += 1
                dropped.append((ps.pod, node_name))
            if dropped:
                for l in self._listeners:
                    l.on_forget_pods(dropped)

    def is_assumed_pod(self, pod: v1.Pod) -> bool:
        with self._lock:
            return bool(self._assumed_pods.get(v1.pod_key(pod)))

    def has_pod(self, key: str) -> bool:
        """Membership test by key — O(1), for callers (the Coscheduling
        prune) that would otherwise list_pods() + set-build per check."""
        with self._lock:
            return key in self._pod_states

    def min_pod_priority(self) -> int:
        """Lowest spec.priority among cached pods (0 when empty). A
        preemption dry-run can only evict strictly-lower-priority victims
        (defaultpreemption selectVictimsOnNode), so an incoming pod whose
        priority is <= this floor provably finds none — callers use that
        to skip the per-pod failure-status re-dispatch. O(distinct
        priorities) off the incremental multiset, not an O(all-pods)
        scan under the lock (tests/test_columnar_cache.py pins the
        multiset against the scan under random churn)."""
        with self._lock:
            if not self._prio_counts:
                return 0
            return min(self._prio_counts)

    # -- confirmed state from informers (cache.go:443-560) -----------------

    def add_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is not None and self._assumed_pods.get(key):
                if ps.pod.spec.node_name != pod.spec.node_name:
                    # scheduler sent it elsewhere; informer wins (cache.go:455)
                    self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                    self._add_pod_locked(pod, pod.spec.node_name)
                    self._foreign_mutations += 1
                # confirm on the assumed node: no state change, no bump
                self._col_assumed_delta(ps.pod.spec.node_name, -1)
                self._assumed_pods.pop(key, None)
                ps.deadline = None
                self._prio_remove(ps.pod)
                ps.pod = pod
                self._prio_add(pod)
            elif ps is None:
                self._add_pod_locked(pod, pod.spec.node_name)
                self._pod_states[key] = _PodState(pod)
                self._prio_add(pod)
                self._foreign_mutations += 1
            # else: duplicate add; ignore

    def update_pod(self, old: v1.Pod, new: v1.Pod) -> None:
        key = v1.pod_key(old)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None or self._assumed_pods.get(key):
                return
            self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
            self._add_pod_locked(new, new.spec.node_name)
            self._prio_remove(ps.pod)
            ps.pod = new
            self._prio_add(new)
            self._foreign_mutations += 1

    def remove_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            if self._assumed_pods.get(key):
                self._col_assumed_delta(ps.pod.spec.node_name, -1)
            self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
            self._prio_remove(ps.pod)
            del self._pod_states[key]
            self._assumed_pods.pop(key, None)
            self._foreign_mutations += 1

    def cleanup_expired_assumed_pods(self) -> int:
        """cache.go:734 cleanupAssumedPods: expire assumed pods whose
        binding finished but confirmation never arrived. Expiry routes
        through _remove_pod_locked like any other remove, so every
        CacheListener sees it — a live device session absorbs it as a
        carry-delta remove instead of drifting from the cache
        (tests/test_session_deltas.py pins expiry bit-identical to a
        rebuild). Returns the number expired; each one is a bind that
        was sent and never informer-confirmed, so the counter
        (scheduler_cache_expired_assumes_total) is a lost-bind signal,
        not bookkeeping. Also refreshes the assumed-pod gauges the
        endurance soak's TTL invariant reads."""
        from ..metrics import assumed_pods, expired_assumes, oldest_assume_age

        now = self._now()
        expired = 0
        oldest_age = 0.0
        with self._lock:
            for key in list(self._assumed_pods):
                ps = self._pod_states[key]
                if ps.binding_finished and ps.deadline is not None and now >= ps.deadline:
                    self._col_assumed_delta(ps.pod.spec.node_name, -1)
                    self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                    self._prio_remove(ps.pod)
                    del self._pod_states[key]
                    del self._assumed_pods[key]
                    self._foreign_mutations += 1
                    expired += 1
                elif ps.binding_finished and ps.deadline is not None:
                    # age past bind-finish of the oldest survivor: if
                    # this ever exceeds ttl + a few sweep periods, the
                    # sweep itself is stalled
                    oldest_age = max(
                        oldest_age, now - (ps.deadline - self._ttl))
            assumed_pods.set(len(self._assumed_pods))
        oldest_assume_age.set(oldest_age)
        if expired:
            expired_assumes.inc(expired)
        return expired

    # -- nodes (cache.go:562-650) ------------------------------------------

    def _set_node_locked(self, node: v1.Node) -> NodeInfo:
        name = node.metadata.name
        ni = self._node_info(name)
        ni.set_node(node)
        self._touch(name)
        self._foreign_mutations += 1
        if self._columnar:
            i = self._col_slot(name)
            alloc = ni.allocatable
            self._col_alloc[i, 0] = alloc.milli_cpu
            self._col_alloc[i, 1] = alloc.memory
            self._col_alloc[i, 2] = alloc.ephemeral_storage
            self._col_alloc[i, 3] = alloc.allowed_pod_number
        self._note_node_images_locked(node)
        return ni

    def add_node(self, node: v1.Node) -> None:
        with self._lock:
            self._set_node_locked(node)
            for l in self._listeners:
                l.on_add_node(node)

    def update_node(self, node: v1.Node) -> None:
        with self._lock:
            self._set_node_locked(node)
            for l in self._listeners:
                l.on_update_node(node)

    def remove_node(self, node_name: str) -> None:
        with self._lock:
            ni = self._nodes.pop(node_name, None)
            if ni is None:
                return
            self._last_snapshot_generation.pop(node_name, None)
            self._foreign_mutations += 1
            if self._columnar:
                self._col_free(node_name)
            self._audit_clones.pop(node_name, None)
            self._drop_node_images_locked(node_name)
            for l in self._listeners:
                l.on_remove_node(node_name)

    # -- incremental image-spread index ------------------------------------

    def _note_node_images_locked(self, node: v1.Node) -> None:
        """Diff this node's image set against its last-seen one and fold
        the change into the spread index. Nodes whose ImageStateSummary
        num_nodes moved (the holders of a gained/lost image) plus the
        node itself become dirty — exactly the O(changed) set whose
        image_states need re-deriving."""
        name = node.metadata.name
        new: Dict[str, int] = {}
        for image in node.status.images or []:
            for nm in image.names or []:
                new[nm] = image.size_bytes
        old = self._node_images.get(name)
        if old != new:
            for nm in (old or {}):
                if nm not in new:
                    holders = self._image_nodes.get(nm)
                    if holders is not None:
                        holders.discard(name)
                        if holders:
                            self._image_dirty.update(holders)
                        else:
                            del self._image_nodes[nm]
            for nm in new:
                if old is None or nm not in old:
                    holders = self._image_nodes.setdefault(nm, set())
                    holders.add(name)
                    self._image_dirty.update(holders)
            self._node_images[name] = new
        # the node itself always refreshes: set_node may have been
        # preceded by a remove (fresh NodeInfo, empty image_states)
        self._image_dirty.add(name)

    def _drop_node_images_locked(self, name: str) -> None:
        old = self._node_images.pop(name, None)
        self._image_dirty.discard(name)
        if old:
            for nm in old:
                holders = self._image_nodes.get(nm)
                if holders is not None:
                    holders.discard(name)
                    if holders:
                        self._image_dirty.update(holders)
                    else:
                        del self._image_nodes[nm]

    def _refresh_image_states_locked(self) -> None:
        """Re-derive NodeInfo.image_states for dirty nodes only
        (snapshot.go createImageExistenceMap semantics: per-node size,
        cluster-wide holder count). The satellite replacing the full
        rebuild update_snapshot used to run over ALL nodes on any
        membership change; tests/test_columnar_cache.py pins equivalence
        against the full rebuild."""
        if not self._image_dirty:
            return
        for name in self._image_dirty:
            ni = self._nodes.get(name)
            if ni is None or ni.node is None:
                continue
            states: Dict[str, ImageStateSummary] = {}
            for image in ni.node.status.images or []:
                for nm in image.names or []:
                    holders = self._image_nodes.get(nm)
                    states[nm] = ImageStateSummary(
                        image.size_bytes, len(holders) if holders else 0
                    )
            ni.image_states = states
            # image_states changed without a generation bump: the audit
            # clone for this node is stale
            self._audit_clones.pop(name, None)
        self._image_dirty.clear()

    def foreign_mutations(self) -> int:
        """Current foreign-mutation generation (see __init__). Latched at
        dispatch onto the batch handle; the shadow sentinel audits only
        when it is unchanged at completion."""
        with self._lock:
            return self._foreign_mutations

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    def list_pods(self) -> List[v1.Pod]:
        """All known pods, assumed included (cache.go ListPods). Used by the
        Coscheduling Permit plugin to count reserved gang members."""
        with self._lock:
            return [s.pod for s in self._pod_states.values()]

    def dump(self) -> "Tuple[List[v1.Node], List[v1.Pod]]":
        """One consistent read of the raw cluster objects: every node and
        every PLACED pod (assumed included). The shadow parity sentinel's
        object-path read — unlike update_snapshot it touches no generation
        bookkeeping (a throwaway snapshot from the completion worker must
        not starve the scheduling thread's incremental refreshes) and
        shares no NodeInfos (callers rebuild their own)."""
        with self._lock:
            nodes = [
                ni.node for ni in self._nodes.values() if ni.node is not None
            ]
            pods = [
                pi.pod for ni in self._nodes.values() for pi in ni.pods
            ]
            return nodes, pods

    def audit_view(self) -> Optional[List[NodeInfo]]:
        """Cheap O(changed) audit snapshot (columnar mode): cloned
        NodeInfos sharing immutable PodInfos — no PodInfo construction,
        no Quantity re-parse, unlike dump() + Snapshot.from_objects which
        rebuilt every NodeInfo from raw objects per audited batch. Clones
        are cached per node and re-taken only when the node's generation
        advanced; callers must treat the returned NodeInfos as READ-ONLY
        (the shadow sentinel copy-on-writes its prefix overlays). Node
        order matches dump(). None when columnar is off — callers fall
        back to the object path."""
        if not self._columnar:
            return None
        with self._lock:
            self._refresh_image_states_locked()
            out: List[NodeInfo] = []
            clones = self._audit_clones
            for name, ni in self._nodes.items():
                if ni.node is None:
                    continue
                c = clones.get(name)
                if c is None or c[0] != ni.generation:
                    clone = ni.clone()
                    clones[name] = (ni.generation, clone)
                else:
                    clone = c[1]
                out.append(clone)
            return out

    def utilization_view(self, names: List[str]) -> Optional[Dict]:
        """Columnar utilization rows gathered in the given node order —
        the fast preemption planner's wave-book seed (one fancy-index
        gather instead of a per-node Python attribute walk). Arrays are
        copies (fancy indexing), stable against later cache mutation.
        None when columnar is off or a name has no row (caller falls
        back to the object walk)."""
        if not self._columnar:
            return None
        with self._lock:
            n = len(names)
            idx = np.empty(n, np.int64)
            col_index = self._col_index
            for j, name in enumerate(names):
                i = col_index.get(name)
                if i is None:
                    return None
                idx[j] = i
            return {
                "names": list(names),
                "requested": self._col_req[idx],
                "nz": self._col_nz[idx],
                "alloc": self._col_alloc[idx, :3],
                "allowed_pods": self._col_alloc[idx, 3],
                "pod_count": self._col_counts[idx, 0],
                "assumed": self._col_counts[idx, 1],
            }

    # -- snapshot (cache.go:203 UpdateSnapshot) ----------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental: only NodeInfos whose generation advanced since this
        snapshot's last update are re-referenced; node list rebuilt only on
        membership change. NodeInfos are shared references — the scheduling
        cycle treats them as read-only for the cycle (the reference clones;
        we rely on the cycle not mutating, enforced by convention+tests).
        The image-spread index refresh is O(dirty nodes), not a full
        rebuild (see _refresh_image_states_locked)."""
        with self._lock:
            changed = False
            for name in self._nodes:
                ni = self._nodes.get(name)
                if ni is None or ni.node is None:
                    continue
                last = self._last_snapshot_generation.get(name)
                if last is not None and last >= ni.generation:
                    break  # list is MRU-first: the rest are unchanged
                self._last_snapshot_generation[name] = ni.generation
                changed = True
            names_with_node = [
                n for n, ni in self._nodes.items() if ni.node is not None
            ]
            if changed or len(snapshot.node_info_list) != len(names_with_node):
                self._refresh_image_states_locked()
                new_snap = Snapshot([self._nodes[n] for n in names_with_node])
                new_snap.generation = snapshot.generation + 1
                if self._columnar:
                    # one consistent columnar gather rides the snapshot:
                    # the preemption planner's utilization seed
                    new_snap.columnar_util = self.utilization_view(
                        names_with_node)
                return new_snap
            return snapshot
