"""Scheduler cache: authoritative in-scheduler cluster state.

Reference: pkg/scheduler/internal/cache/cache.go — the assume/confirm/
expire protocol for optimistic binding (:361 AssumePod, :415 ForgetPod,
:443 AddPod confirms, :734 cleanupAssumedPods 30s TTL) and the
generation-based incremental snapshot (:203 UpdateSnapshot: only NodeInfos
whose generation advanced since the last snapshot are re-copied; nodes form
a doubly-linked list, most-recently-updated first, so the scan stops at the
first unchanged entry).

Listeners: the TPU backend registers a CacheListener to mirror every
mutation into its dense ClusterEncoding (models/encoding.py), keeping the
device arrays in lock-step with the cache at O(changed rows) per cycle —
SURVEY.md §7 hard part (a).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ...api import types as v1
from ..framework.snapshot import Snapshot
from ..framework.types import ImageStateSummary, NodeInfo

ASSUME_EXPIRATION_SECONDS = 30.0  # cache.go durationToExpireAssumedPod


class CacheListener:
    """Mutation hooks (all called with the cache lock held)."""

    def on_add_pod(self, pod: v1.Pod, node_name: str) -> None: ...
    def on_remove_pod(self, pod: v1.Pod, node_name: str) -> None: ...
    def on_add_node(self, node: v1.Node) -> None: ...
    def on_update_node(self, node: v1.Node) -> None: ...
    def on_remove_node(self, node_name: str) -> None: ...


class _PodState:
    __slots__ = ("pod", "deadline", "binding_finished")

    def __init__(self, pod: v1.Pod):
        self.pod = pod
        self.deadline: Optional[float] = None
        self.binding_finished = False


class SchedulerCache:
    def __init__(self, ttl: float = ASSUME_EXPIRATION_SECONDS, now=time.monotonic):
        self._lock = threading.RLock()
        self._ttl = ttl
        self._now = now
        self._pod_states: Dict[str, _PodState] = {}  # key -> state (all known pods)
        self._assumed_pods: Dict[str, bool] = {}  # key -> True
        # most-recently-updated FIRST — an OrderedDict used as the cache.go
        # doubly-linked node list (move_to_end(last=False) == moveToHead)
        self._nodes: "OrderedDict[str, NodeInfo]" = OrderedDict()
        self._listeners: List[CacheListener] = []
        # snapshot bookkeeping
        self._last_snapshot_generation: Dict[str, int] = {}
        # foreign-mutation generation: bumped by every state change that
        # did NOT originate from this scheduler's own assume protocol —
        # informer adds/updates/removes, node events, TTL expiry, forget.
        # The shadow parity sentinel compares the value it latched at
        # dispatch against the value at completion: any advance means the
        # completion-time cache is no longer the decision-time state and
        # the oracle replay would adjudicate against a world the device
        # never saw (audit skipped, counted). Own-batch assumes and bind
        # confirmations on the assumed node deliberately do NOT bump:
        # they are exactly the deltas FIFO completion already accounts
        # for.
        self._foreign_mutations = 0

    def add_listener(self, listener: CacheListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    # -- internal helpers --------------------------------------------------

    def _node_info(self, name: str) -> NodeInfo:
        ni = self._nodes.get(name)
        if ni is None:
            ni = NodeInfo()
            self._nodes[name] = ni
        return ni

    def _touch(self, name: str) -> None:
        """O(1) move-to-head (cache.go moveNodeInfoToHead)."""
        if name in self._nodes:
            self._nodes.move_to_end(name, last=False)

    def _add_pod_locked(self, pod: v1.Pod, node_name: str) -> None:
        ni = self._node_info(node_name)
        ni.add_pod(pod)
        self._touch(node_name)
        for l in self._listeners:
            l.on_add_pod(pod, node_name)

    def _remove_pod_locked(self, pod: v1.Pod, node_name: str) -> None:
        ni = self._nodes.get(node_name)
        if ni is not None:
            ni.remove_pod(pod)
            self._touch(node_name)
        for l in self._listeners:
            l.on_remove_pod(pod, node_name)

    # -- assume protocol (cache.go:361-441) --------------------------------

    def assume_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            if key in self._pod_states:
                raise ValueError(f"pod {key} is in the cache, so can't be assumed")
            self._add_pod_locked(pod, pod.spec.node_name)
            ps = _PodState(pod)
            self._pod_states[key] = ps
            self._assumed_pods[key] = True

    def assume_pods(self, pods: List[v1.Pod]) -> List[bool]:
        """Batch AssumePod under ONE lock acquisition (the TPU batch path
        assumes thousands of pods per cycle; per-pod locking ping-pongs
        with the binder threads' finish_binding). Returns per-pod success;
        False = already in the cache (informer raced us), same condition
        assume_pod raises ValueError for."""
        out: List[bool] = []
        with self._lock:
            for pod in pods:
                key = v1.pod_key(pod)
                if key in self._pod_states:
                    out.append(False)
                    continue
                self._add_pod_locked(pod, pod.spec.node_name)
                self._pod_states[key] = _PodState(pod)
                self._assumed_pods[key] = True
                out.append(True)
        return out

    def finish_binding(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is not None and self._assumed_pods.get(key):
                ps.binding_finished = True
                ps.deadline = self._now() + self._ttl

    def finish_binding_many(self, pods: List[v1.Pod]) -> None:
        """Batch FinishBinding under one lock acquisition."""
        with self._lock:
            deadline = self._now() + self._ttl
            for pod in pods:
                ps = self._pod_states.get(v1.pod_key(pod))
                if ps is not None and self._assumed_pods.get(v1.pod_key(pod)):
                    ps.binding_finished = True
                    ps.deadline = deadline

    def forget_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            if self._assumed_pods.get(key):
                self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                del self._pod_states[key]
                del self._assumed_pods[key]
                # a retracted assume breaks the FIFO accounting the
                # sentinel relies on — later in-flight batches decided
                # WITH this placement
                self._foreign_mutations += 1
            else:
                raise ValueError(f"pod {key} wasn't assumed so cannot be forgotten")

    def is_assumed_pod(self, pod: v1.Pod) -> bool:
        with self._lock:
            return bool(self._assumed_pods.get(v1.pod_key(pod)))

    def has_pod(self, key: str) -> bool:
        """Membership test by key — O(1), for callers (the Coscheduling
        prune) that would otherwise list_pods() + set-build per check."""
        with self._lock:
            return key in self._pod_states

    def min_pod_priority(self) -> int:
        """Lowest spec.priority among cached pods (0 when empty). A
        preemption dry-run can only evict strictly-lower-priority victims
        (defaultpreemption selectVictimsOnNode), so an incoming pod whose
        priority is <= this floor provably finds none — callers use that
        to skip the per-pod failure-status re-dispatch."""
        with self._lock:
            return min(
                (ps.pod.spec.priority or 0 for ps in self._pod_states.values()),
                default=0,
            )

    # -- confirmed state from informers (cache.go:443-560) -----------------

    def add_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is not None and self._assumed_pods.get(key):
                if ps.pod.spec.node_name != pod.spec.node_name:
                    # scheduler sent it elsewhere; informer wins (cache.go:455)
                    self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                    self._add_pod_locked(pod, pod.spec.node_name)
                    self._foreign_mutations += 1
                # confirm on the assumed node: no state change, no bump
                self._assumed_pods.pop(key, None)
                ps.deadline = None
                ps.pod = pod
            elif ps is None:
                self._add_pod_locked(pod, pod.spec.node_name)
                self._pod_states[key] = _PodState(pod)
                self._foreign_mutations += 1
            # else: duplicate add; ignore

    def update_pod(self, old: v1.Pod, new: v1.Pod) -> None:
        key = v1.pod_key(old)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None or self._assumed_pods.get(key):
                return
            self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
            self._add_pod_locked(new, new.spec.node_name)
            ps.pod = new
            self._foreign_mutations += 1

    def remove_pod(self, pod: v1.Pod) -> None:
        key = v1.pod_key(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
            del self._pod_states[key]
            self._assumed_pods.pop(key, None)
            self._foreign_mutations += 1

    def cleanup_expired_assumed_pods(self) -> int:
        """cache.go:734 cleanupAssumedPods: expire assumed pods whose
        binding finished but confirmation never arrived. Expiry routes
        through _remove_pod_locked like any other remove, so every
        CacheListener sees it — a live device session absorbs it as a
        carry-delta remove instead of drifting from the cache
        (tests/test_session_deltas.py pins expiry bit-identical to a
        rebuild). Returns the number expired; each one is a bind that
        was sent and never informer-confirmed, so the counter
        (scheduler_cache_expired_assumes_total) is a lost-bind signal,
        not bookkeeping. Also refreshes the assumed-pod gauges the
        endurance soak's TTL invariant reads."""
        from ..metrics import assumed_pods, expired_assumes, oldest_assume_age

        now = self._now()
        expired = 0
        oldest_age = 0.0
        with self._lock:
            for key in list(self._assumed_pods):
                ps = self._pod_states[key]
                if ps.binding_finished and ps.deadline is not None and now >= ps.deadline:
                    self._remove_pod_locked(ps.pod, ps.pod.spec.node_name)
                    del self._pod_states[key]
                    del self._assumed_pods[key]
                    self._foreign_mutations += 1
                    expired += 1
                elif ps.binding_finished and ps.deadline is not None:
                    # age past bind-finish of the oldest survivor: if
                    # this ever exceeds ttl + a few sweep periods, the
                    # sweep itself is stalled
                    oldest_age = max(
                        oldest_age, now - (ps.deadline - self._ttl))
            assumed_pods.set(len(self._assumed_pods))
        oldest_assume_age.set(oldest_age)
        if expired:
            expired_assumes.inc(expired)
        return expired

    # -- nodes (cache.go:562-650) ------------------------------------------

    def add_node(self, node: v1.Node) -> None:
        with self._lock:
            ni = self._node_info(node.metadata.name)
            ni.set_node(node)
            self._touch(node.metadata.name)
            self._foreign_mutations += 1
            for l in self._listeners:
                l.on_add_node(node)

    def update_node(self, node: v1.Node) -> None:
        with self._lock:
            ni = self._node_info(node.metadata.name)
            ni.set_node(node)
            self._touch(node.metadata.name)
            self._foreign_mutations += 1
            for l in self._listeners:
                l.on_update_node(node)

    def remove_node(self, node_name: str) -> None:
        with self._lock:
            ni = self._nodes.pop(node_name, None)
            if ni is None:
                return
            self._last_snapshot_generation.pop(node_name, None)
            self._foreign_mutations += 1
            for l in self._listeners:
                l.on_remove_node(node_name)

    def foreign_mutations(self) -> int:
        """Current foreign-mutation generation (see __init__). Latched at
        dispatch onto the batch handle; the shadow sentinel audits only
        when it is unchanged at completion."""
        with self._lock:
            return self._foreign_mutations

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    def list_pods(self) -> List[v1.Pod]:
        """All known pods, assumed included (cache.go ListPods). Used by the
        Coscheduling Permit plugin to count reserved gang members."""
        with self._lock:
            return [s.pod for s in self._pod_states.values()]

    def dump(self) -> "Tuple[List[v1.Node], List[v1.Pod]]":
        """One consistent read of the raw cluster objects: every node and
        every PLACED pod (assumed included). The shadow parity sentinel's
        read path — unlike update_snapshot it touches no generation
        bookkeeping (a throwaway snapshot from the completion worker must
        not starve the scheduling thread's incremental refreshes) and
        shares no NodeInfos (callers rebuild their own)."""
        with self._lock:
            nodes = [
                ni.node for ni in self._nodes.values() if ni.node is not None
            ]
            pods = [
                pi.pod for ni in self._nodes.values() for pi in ni.pods
            ]
            return nodes, pods

    # -- snapshot (cache.go:203 UpdateSnapshot) ----------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental: only NodeInfos whose generation advanced since this
        snapshot's last update are re-referenced; node list rebuilt only on
        membership change. NodeInfos are shared references — the scheduling
        cycle treats them as read-only for the cycle (the reference clones;
        we rely on the cycle not mutating, enforced by convention+tests)."""
        with self._lock:
            changed = False
            for name in self._nodes:
                ni = self._nodes.get(name)
                if ni is None or ni.node is None:
                    continue
                last = self._last_snapshot_generation.get(name)
                if last is not None and last >= ni.generation:
                    break  # list is MRU-first: the rest are unchanged
                self._last_snapshot_generation[name] = ni.generation
                changed = True
            names_with_node = [
                n for n, ni in self._nodes.items() if ni.node is not None
            ]
            if changed or len(snapshot.node_info_list) != len(names_with_node):
                # rebuild image-spread index (snapshot.go createImageExistenceMap)
                image_nodes: Dict[str, set] = {}
                for name in names_with_node:
                    node = self._nodes[name].node
                    for image in node.status.images or []:
                        for nm in image.names or []:
                            image_nodes.setdefault(nm, set()).add(name)
                for name in names_with_node:
                    ni = self._nodes[name]
                    states: Dict[str, ImageStateSummary] = {}
                    for image in ni.node.status.images or []:
                        for nm in image.names or []:
                            states[nm] = ImageStateSummary(
                                image.size_bytes, len(image_nodes[nm])
                            )
                    ni.image_states = states
                new_snap = Snapshot([self._nodes[n] for n in names_with_node])
                new_snap.generation = snapshot.generation + 1
                return new_snap
            return snapshot
