"""Volume constraints on the device path.

Reference: the scheduler's volume filters —
pkg/scheduler/framework/plugins/volumebinding/volume_binding.go (bound-
PVC node-affinity conflicts), volumezone/volume_zone.go (PV zone/region
labels must match the node's), nodevolumelimits/csi.go (per-node attach
counts vs CSINode allocatable).

The r3 build diverted EVERY PVC-bearing pod to the host oracle
(scheduler.py _needs_oracle) — structurally oracle-slow for the whole
volume workload class. This module ends that: for pods whose PVCs are
all BOUND, the volume filters are statically resolvable at encode time
and ride the existing kernel machinery with NO new kernel code:

  * PV node affinity + VolumeZone label constraints become extra
    node-affinity OR-groups merged (by term distribution) into the
    pod's compiled node-affinity tables — the kernel's
    mask_node_affinity enforces them;
  * CSI attach limits become scalar resource dimensions named
    attachable-volumes-csi-<driver> (the reference's own resource-name
    convention for in-tree limits): the pod requests its per-driver
    volume count, nodes carry limit-as-allocatable and
    attached-count-as-requested, and the kernel's resource-fit mask
    enforces the limit.

Pods OUTSIDE the envelope keep the oracle path (correctness first):
unbound PVCs (provisioning decisions live in volume/binder.py),
PVCs shared with another pod (attach counting needs unique-handle
semantics), or affinity-term products too large to distribute.
Decision parity inside the envelope is pinned by
tests/test_volume_device.py against the oracle plugins.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as v1
from .plugins.volumes import DEFAULT_LIMITS, _ZONE_LABELS

MAX_DISTRIBUTED_TERMS = 16


def attach_resource_name(driver: str) -> str:
    """util.GetCSIAttachLimitKey: attachable-volumes-csi-<driver>."""
    return f"attachable-volumes-csi-{driver}"


_INTREE_TO_CSI = {
    "awsElasticBlockStore": "ebs.csi.aws.com",
    "gcePersistentDisk": "pd.csi.storage.gke.io",
    "azureDisk": "disk.csi.azure.com",
}


class VolumeResolution:
    """What the encoder needs for one kernel-safe PVC-bearing pod."""

    __slots__ = ("term_groups", "extra_scalars")

    def __init__(self, term_groups, extra_scalars):
        # each group: OR of v1.NodeSelectorTerm; groups are ANDed (by
        # distribution into the pod's single OR-group)
        self.term_groups: List[List[v1.NodeSelectorTerm]] = term_groups
        self.extra_scalars: Dict[str, int] = extra_scalars


def pod_pvc_names(pod: v1.Pod) -> List[str]:
    return [
        (vol.source or {}).get("persistentVolumeClaim", {}).get("claimName", "")
        for vol in pod.spec.volumes or []
        if (vol.source or {}).get("persistentVolumeClaim")
    ]


class VolumeDeviceResolver:
    """Resolves a pod's bound-PVC constraints into kernel inputs.

    version bumps on every PVC/PV/CSINode event — consumers key caches
    on it (a PVC binding after a pod was encoded must invalidate that
    encoding)."""

    def __init__(self, list_pvcs, list_pvs, list_csinodes):
        self._list_pvcs = list_pvcs
        self._list_pvs = list_pvs
        self._list_csinodes = list_csinodes
        self.version = 0
        self._lock = threading.Lock()
        # (ns, claim) -> count of ASSIGNED/ASSUMED pods using it (fed by
        # the encoding's add/remove hooks): a claim already in use takes
        # the oracle path (unique-handle attach counting)
        self._pvc_refs: Dict[Tuple[str, str], int] = {}
        self._drivers_in_use: Set[str] = set()
        self._index_cache = None  # (version, pvc index, pv index)
        self._csinode_cache = None  # (version, node -> {driver: count})

    # -- event hooks -------------------------------------------------------

    def bump(self, *_args) -> None:
        with self._lock:
            self.version += 1

    def pod_added(self, pod: v1.Pod) -> None:
        ns = pod.metadata.namespace
        with self._lock:
            for claim in pod_pvc_names(pod):
                key = (ns, claim)
                self._pvc_refs[key] = self._pvc_refs.get(key, 0) + 1

    def pod_removed(self, pod: v1.Pod) -> None:
        ns = pod.metadata.namespace
        with self._lock:
            for claim in pod_pvc_names(pod):
                key = (ns, claim)
                n = self._pvc_refs.get(key, 0) - 1
                if n <= 0:
                    self._pvc_refs.pop(key, None)
                else:
                    self._pvc_refs[key] = n

    # -- resolution --------------------------------------------------------

    def _indexes(self):
        """(pvc-by-key, pv-by-name) maps, rebuilt lazily per version —
        per-pod lister scans would be O(n^2) over a benchmark's PVC
        population."""
        with self._lock:
            idx = self._index_cache
            if idx is not None and idx[0] == self.version:
                return idx[1], idx[2]
        pvcs = {
            (c.metadata.namespace, c.metadata.name): c
            for c in self._list_pvcs()
        }
        pvs = {p.metadata.name: p for p in self._list_pvs()}
        with self._lock:
            self._index_cache = (self.version, pvcs, pvs)
        return pvcs, pvs

    def _pv_of(self, namespace: str, claim: str):
        pvcs, pvs = self._indexes()
        c = pvcs.get((namespace, claim))
        if c is None or not c.spec.volume_name:
            return None
        return pvs.get(c.spec.volume_name)

    def resolve(self, pod: v1.Pod) -> Optional[VolumeResolution]:
        """None = outside the kernel envelope (oracle path)."""
        claims = pod_pvc_names(pod)
        if not claims:
            return VolumeResolution([], {})
        ns = pod.metadata.namespace
        with self._lock:
            if any(self._pvc_refs.get((ns, c), 0) > 0 for c in claims):
                return None  # shared claim: unique-handle counting
        pvs = []
        for claim in claims:
            pv = self._pv_of(ns, claim)
            if pv is None:
                return None  # unbound / missing: binder territory
            pvs.append(pv)
        term_groups: List[List[v1.NodeSelectorTerm]] = []
        # VolumeZone (volume_zone.go): one combined group — every zone
        # constraint matches, OR the node has no zone labels at all
        zone_reqs: List[v1.NodeSelectorRequirement] = []
        for pv in pvs:
            for key, value in (pv.metadata.labels or {}).items():
                if key in _ZONE_LABELS:
                    vals = sorted(set(value.replace("__", ",").split(",")))
                    zone_reqs.append(
                        v1.NodeSelectorRequirement(
                            key=key, operator="In", values=vals
                        )
                    )
        if zone_reqs:
            no_labels = v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(key=k, operator="DoesNotExist")
                for k in _ZONE_LABELS
            ])
            term_groups.append([
                v1.NodeSelectorTerm(match_expressions=zone_reqs), no_labels,
            ])
        # PV nodeAffinity (volume_binding.go bound-PVC check): each PV's
        # required terms are one OR-group
        for pv in pvs:
            na = pv.spec.node_affinity
            if na is None or na.required is None:
                continue
            terms = na.required.node_selector_terms or []
            if not terms:
                return None  # required-with-no-terms matches nothing
            term_groups.append(list(terms))
        # term-product cap (distribution explodes combinatorially)
        product = 1
        own = _own_affinity_terms(pod)
        for g in [own] if own else []:
            product *= len(g)
        for g in term_groups:
            product *= len(g)
        if product > MAX_DISTRIBUTED_TERMS:
            return None
        # attach limits -> scalar requests per driver
        scalars: Dict[str, int] = {}
        for pv in pvs:
            drv = _pv_driver(pv)
            if drv:
                name = attach_resource_name(drv)
                scalars[name] = scalars.get(name, 0) + 1
                with self._lock:
                    self._drivers_in_use.add(drv)
        return VolumeResolution(term_groups, scalars)

    # -- node side ---------------------------------------------------------

    def _csinode_index(self) -> Dict[str, Dict[str, int]]:
        """node name -> {driver: count}, rebuilt lazily per version —
        an encoding rebuild calls node_extra_alloc once PER NODE, and a
        full CSINode list scan each time is O(nodes x csinodes)."""
        with self._lock:
            idx = self._csinode_cache
            if idx is not None and idx[0] == self.version:
                return idx[1]
        by_node: Dict[str, Dict[str, int]] = {}
        for cn in self._list_csinodes():
            limits = {
                drv.name: drv.count
                for drv in cn.spec.drivers or []
                if drv.count is not None
            }
            if limits:
                by_node[cn.metadata.name] = limits
        with self._lock:
            self._csinode_cache = (self.version, by_node)
        return by_node

    def node_extra_alloc(self, node: v1.Node) -> Dict[str, int]:
        """Per-driver attach limits as allocatable scalars, for every
        driver any resolved pod uses: CSINode allocatable wins, then the
        in-tree defaults, then effectively-unlimited (csi.go
        _limits_for semantics)."""
        with self._lock:
            drivers = set(self._drivers_in_use)
        if not drivers:
            return {}
        csinode_limits = self._csinode_index().get(node.metadata.name, {})
        out = {}
        for drv in drivers:
            limit = csinode_limits.get(drv, DEFAULT_LIMITS.get(drv))
            if limit is None:
                limit = 1 << 40  # no CSINode, no default: unlimited
            out[attach_resource_name(drv)] = limit
        return out

    def pod_extra_scalars(self, pod: v1.Pod) -> Dict[str, int]:
        """Attach-count scalars an ASSIGNED/ASSUMED pod consumes on its
        node row. Must mirror resolve()'s accounting; pods outside the
        envelope contribute too (their volumes occupy attach slots that
        kernel pods compete for)."""
        scalars: Dict[str, int] = {}
        seen: Set[Tuple[str, str]] = set()
        for vol in pod.spec.volumes or []:
            src = vol.source or {}
            drv = ident = None
            if "csi" in src:
                drv = src["csi"].get("driver", "")
                ident = src["csi"].get("volumeHandle", vol.name)
            else:
                for key, mapped in _INTREE_TO_CSI.items():
                    if key in src:
                        drv = mapped
                        d = src[key]
                        ident = (d.get("pdName") or d.get("volumeID")
                                 or d.get("diskName") or vol.name)
                        break
            pvc_src = src.get("persistentVolumeClaim")
            if drv is None and pvc_src:
                pv = self._pv_of(
                    pod.metadata.namespace, pvc_src.get("claimName", "")
                )
                if pv is not None:
                    drv = _pv_driver(pv)
                    ident = pv.metadata.name
            if drv and (drv, ident) not in seen:
                seen.add((drv, ident))
                name = attach_resource_name(drv)
                scalars[name] = scalars.get(name, 0) + 1
        if scalars:
            with self._lock:
                for name in scalars:
                    self._drivers_in_use.add(
                        name[len("attachable-volumes-csi-"):]
                    )
        return scalars


def _pv_driver(pv) -> Optional[str]:
    csi = getattr(pv.spec, "csi", None)
    if isinstance(csi, dict) and csi.get("driver"):
        return csi["driver"]
    src = getattr(pv.spec, "source", None) or {}
    if isinstance(src, dict):
        if "csi" in src and src["csi"].get("driver"):
            return src["csi"]["driver"]
        for key, mapped in _INTREE_TO_CSI.items():
            if key in src:
                return mapped
    return None


def distribute_term_groups(
    own: Optional[List[v1.NodeSelectorTerm]],
    groups: List[List[v1.NodeSelectorTerm]],
) -> List[v1.NodeSelectorTerm]:
    """AND of OR-groups -> ONE OR-group by distribution (the kernel's
    affinity tables hold a single OR-of-conjunctions). Empty terms match
    nothing (api.labels semantics) and are dropped; a group left empty
    makes the whole conjunction unsatisfiable -> a single never-term."""
    all_groups = ([own] if own is not None else []) + groups
    cleaned: List[List[v1.NodeSelectorTerm]] = []
    for g in all_groups:
        kept = [t for t in g if t.match_expressions or t.match_fields]
        if not kept:
            return [_NEVER_TERM]
        cleaned.append(kept)
    if not cleaned:
        return []
    combos: List[List[v1.NodeSelectorTerm]] = [[]]
    for g in cleaned:
        combos = [c + [t] for c in combos for t in g]
    out = []
    for parts in combos:
        me: List[v1.NodeSelectorRequirement] = []
        mf: List[v1.NodeSelectorRequirement] = []
        for t in parts:
            me.extend(t.match_expressions or [])
            mf.extend(t.match_fields or [])
        out.append(
            v1.NodeSelectorTerm(
                match_expressions=me or None, match_fields=mf or None
            )
        )
    return out


# In with an empty value set can never match
_NEVER_TERM = v1.NodeSelectorTerm(match_expressions=[
    v1.NodeSelectorRequirement(key="kubernetes.io/hostname",
                               operator="In", values=[])
])


def _own_affinity_terms(pod: v1.Pod) -> Optional[List[v1.NodeSelectorTerm]]:
    a = pod.spec.affinity
    if a is None or a.node_affinity is None:
        return None
    req = a.node_affinity.required_during_scheduling_ignored_during_execution
    if req is None:
        return None
    return list(req.node_selector_terms or [])
