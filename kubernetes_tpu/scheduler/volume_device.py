"""Volume constraints on the device path.

Reference: the scheduler's volume filters —
pkg/scheduler/framework/plugins/volumebinding/volume_binding.go (bound-
PVC node-affinity conflicts), volumezone/volume_zone.go (PV zone/region
labels must match the node's), nodevolumelimits/csi.go (per-node attach
counts vs CSINode allocatable).

The r3 build diverted EVERY PVC-bearing pod to the host oracle
(scheduler.py _needs_oracle) — structurally oracle-slow for the whole
volume workload class. This module ends that: for pods whose PVCs are
all BOUND, the volume filters are statically resolvable at encode time
and ride the existing kernel machinery with NO new kernel code:

  * PV node affinity + VolumeZone label constraints become extra
    node-affinity OR-groups merged (by term distribution) into the
    pod's compiled node-affinity tables — the kernel's
    mask_node_affinity enforces them;
  * CSI attach limits become scalar resource dimensions named
    attachable-volumes-csi-<driver> (the reference's own resource-name
    convention for in-tree limits): the pod requests its per-driver
    volume count, nodes carry limit-as-allocatable and
    attached-count-as-requested, and the kernel's resource-fit mask
    enforces the limit.

Pods OUTSIDE the envelope keep the oracle path (correctness first):
unbound PVCs (provisioning decisions live in volume/binder.py),
PVCs shared with another pod (attach counting needs unique-handle
semantics), or affinity-term products too large to distribute.
Decision parity inside the envelope is pinned by
tests/test_volume_device.py against the oracle plugins.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as v1
from .plugins.volumes import DEFAULT_LIMITS, _ZONE_LABELS

MAX_DISTRIBUTED_TERMS = 16


def attach_resource_name(driver: str) -> str:
    """util.GetCSIAttachLimitKey: attachable-volumes-csi-<driver>."""
    return f"attachable-volumes-csi-{driver}"


class VolumeResolutionChanged(Exception):
    """A pod gated kernel-safe resolved differently at encode time (a
    PVC/assume event raced the scheduling cycle). The backend fails the
    pod's attempt; the retry re-gates against fresh state."""


class VolumeResolution:
    """What the encoder needs for one kernel-safe PVC-bearing pod."""

    __slots__ = ("term_groups", "extra_scalars")

    def __init__(self, term_groups, extra_scalars):
        # each group: OR of v1.NodeSelectorTerm; groups are ANDed (by
        # distribution into the pod's single OR-group)
        self.term_groups: List[List[v1.NodeSelectorTerm]] = term_groups
        self.extra_scalars: Dict[str, int] = extra_scalars


def pod_pvc_names(pod: v1.Pod) -> List[str]:
    return [
        (vol.source or {}).get("persistentVolumeClaim", {}).get("claimName", "")
        for vol in pod.spec.volumes or []
        if (vol.source or {}).get("persistentVolumeClaim")
    ]


class VolumeDeviceResolver:
    """Resolves a pod's bound-PVC constraints into kernel inputs.

    version bumps on every PVC/PV/CSINode event — consumers key caches
    on it (a PVC binding after a pod was encoded must invalidate that
    encoding)."""

    def __init__(self, list_pvcs, list_pvs, list_csinodes):
        self._list_pvcs = list_pvcs
        self._list_pvs = list_pvs
        self._list_csinodes = list_csinodes
        self.version = 0
        self._lock = threading.Lock()
        # (ns, claim) -> count of ASSIGNED/ASSUMED pods using it (fed by
        # the encoding's add/remove hooks): a claim already in use takes
        # the oracle path (unique-handle attach counting)
        self._pvc_refs: Dict[Tuple[str, str], int] = {}
        self._drivers_in_use: Set[str] = set()
        self._index_cache = None  # (version, pvc index, pv index)
        self._csinode_cache = None  # (version, node -> {driver: count})
        # (node, driver, handle) -> refcount of encoded pods using it
        self._node_handles: Dict[Tuple[str, str, str], int] = {}
        # fired (outside the lock) when a driver enters _drivers_in_use:
        # node rows built before it have no limit column for it (column
        # reads 0 = limit 0 = everything infeasible) — the backend hooks
        # this to queue an encoding rebuild
        self.on_new_driver = None

    # -- event hooks -------------------------------------------------------

    def bump(self, *_args) -> None:
        with self._lock:
            self.version += 1

    def claim_referenced(self, key: Tuple[str, str]) -> bool:
        """True when an ASSIGNED/ASSUMED (encoded) pod uses this claim.
        Callers may hold the backend lock — this lock nests inside it."""
        with self._lock:
            return self._pvc_refs.get(key, 0) > 0

    def drivers_referenced(self, drivers) -> bool:
        with self._lock:
            return bool(self._drivers_in_use & set(drivers))

    def pod_added(self, pod: v1.Pod) -> None:
        ns = pod.metadata.namespace
        with self._lock:
            for claim in pod_pvc_names(pod):
                key = (ns, claim)
                self._pvc_refs[key] = self._pvc_refs.get(key, 0) + 1

    def pod_removed(self, pod: v1.Pod) -> None:
        ns = pod.metadata.namespace
        with self._lock:
            for claim in pod_pvc_names(pod):
                key = (ns, claim)
                n = self._pvc_refs.get(key, 0) - 1
                if n <= 0:
                    self._pvc_refs.pop(key, None)
                else:
                    self._pvc_refs[key] = n

    # -- resolution --------------------------------------------------------

    def _indexes(self):
        """(pvc-by-key, pv-by-name) maps, rebuilt lazily per version —
        per-pod lister scans would be O(n^2) over a benchmark's PVC
        population. The version is captured BEFORE listing: a bump()
        racing the build must leave the cache stamped stale (a stale
        index stamped with the NEW version would serve wrong
        resolutions until an unrelated event)."""
        with self._lock:
            idx = self._index_cache
            if idx is not None and idx[0] == self.version:
                return idx[1], idx[2]
            version = self.version
        pvcs = {
            (c.metadata.namespace, c.metadata.name): c
            for c in self._list_pvcs()
        }
        # CSI migration at index time (volume/csi_translation.py): an
        # in-tree cloud-disk PV reaches everything downstream — driver
        # attach scalars, zone terms, node affinity — as its CSI twin
        from ..volume.csi_translation import translate_pv

        pvs = {p.metadata.name: translate_pv(p) for p in self._list_pvs()}
        with self._lock:
            self._index_cache = (version, pvcs, pvs)
        return pvcs, pvs

    def _pv_of(self, namespace: str, claim: str):
        pvcs, pvs = self._indexes()
        c = pvcs.get((namespace, claim))
        if c is None or not c.spec.volume_name:
            return None
        return pvs.get(c.spec.volume_name)

    def resolve(self, pod: v1.Pod) -> Optional[VolumeResolution]:
        """None = outside the kernel envelope (oracle path)."""
        claims = pod_pvc_names(pod)
        if not claims:
            return VolumeResolution([], {})
        ns = pod.metadata.namespace
        with self._lock:
            if any(self._pvc_refs.get((ns, c), 0) > 0 for c in claims):
                return None  # shared claim: unique-handle counting
        pvs = []
        for claim in claims:
            pv = self._pv_of(ns, claim)
            if pv is None:
                return None  # unbound / missing: binder territory
            pvs.append(pv)
        term_groups: List[List[v1.NodeSelectorTerm]] = []
        # VolumeZone (volume_zone.go): one combined group — every zone
        # constraint matches, OR the node has no zone labels at all
        zone_reqs: List[v1.NodeSelectorRequirement] = []
        for pv in pvs:
            for key, value in (pv.metadata.labels or {}).items():
                if key in _ZONE_LABELS:
                    vals = sorted(set(value.replace("__", ",").split(",")))
                    zone_reqs.append(
                        v1.NodeSelectorRequirement(
                            key=key, operator="In", values=vals
                        )
                    )
        if zone_reqs:
            no_labels = v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(key=k, operator="DoesNotExist")
                for k in _ZONE_LABELS
            ])
            term_groups.append([
                v1.NodeSelectorTerm(match_expressions=zone_reqs), no_labels,
            ])
        # PV nodeAffinity (volume_binding.go bound-PVC check): each PV's
        # required terms are one OR-group
        for pv in pvs:
            na = pv.spec.node_affinity
            if na is None or na.required is None:
                continue
            terms = na.required.node_selector_terms or []
            if not terms:
                return None  # required-with-no-terms matches nothing
            term_groups.append(list(terms))
        # term-product cap (distribution explodes combinatorially)
        product = 1
        own = _own_affinity_terms(pod)
        for g in [own] if own else []:
            product *= len(g)
        for g in term_groups:
            product *= len(g)
        if product > MAX_DISTRIBUTED_TERMS:
            return None
        # attach limits -> scalar requests per driver
        scalars: Dict[str, int] = {}
        new_drivers = []
        for pv in pvs:
            drv = _pv_driver(pv)
            if drv:
                name = attach_resource_name(drv)
                scalars[name] = scalars.get(name, 0) + 1
                with self._lock:
                    if drv not in self._drivers_in_use:
                        self._drivers_in_use.add(drv)
                        new_drivers.append(drv)
        if new_drivers and self.on_new_driver is not None:
            self.on_new_driver()
        return VolumeResolution(term_groups, scalars)

    # -- node side ---------------------------------------------------------

    def _csinode_index(self) -> Dict[str, Dict[str, int]]:
        """node name -> {driver: count}, rebuilt lazily per version —
        an encoding rebuild calls node_extra_alloc once PER NODE, and a
        full CSINode list scan each time is O(nodes x csinodes)."""
        with self._lock:
            idx = self._csinode_cache
            if idx is not None and idx[0] == self.version:
                return idx[1]
            version = self.version
        by_node: Dict[str, Dict[str, int]] = {}
        for cn in self._list_csinodes():
            limits = {
                drv.name: drv.count
                for drv in cn.spec.drivers or []
                if drv.count is not None
            }
            if limits:
                by_node[cn.metadata.name] = limits
        with self._lock:
            self._csinode_cache = (version, by_node)
        return by_node

    def node_extra_alloc(self, node: v1.Node) -> Dict[str, int]:
        """Per-driver attach limits as allocatable scalars, for every
        driver any resolved pod uses: CSINode allocatable wins, then the
        in-tree defaults, then effectively-unlimited (csi.go
        _limits_for semantics)."""
        with self._lock:
            drivers = set(self._drivers_in_use)
        if not drivers:
            return {}
        csinode_limits = self._csinode_index().get(node.metadata.name, {})
        out = {}
        for drv in drivers:
            limit = csinode_limits.get(drv, DEFAULT_LIMITS.get(drv))
            if limit is None:
                limit = 1 << 40  # no CSINode, no default: unlimited
            out[attach_resource_name(drv)] = limit
        return out

    def _pod_volumes_by_driver(self, pod: v1.Pod):
        """driver -> volume handles, via the oracle plugin's own walk
        (_csi_volumes_of) so the fast path's accounting and
        NodeVolumeLimits can never diverge."""
        from .plugins.volumes import _csi_volumes_of

        def lookup(namespace: str, name: str):
            pv = self._pv_of(namespace, name)
            if pv is None:
                return None
            drv = _pv_driver(pv)
            return (drv, pv.metadata.name) if drv else None

        return _csi_volumes_of(pod, lookup)

    def pod_extra_scalars(self, pod: v1.Pod) -> Dict[str, int]:
        """The pod's OWN attach requirement (vocab interning + pending
        encode). Node-row accounting goes through attach_delta, which is
        refcounted by handle."""
        return {
            attach_resource_name(drv): len(idents)
            for drv, idents in self._pod_volumes_by_driver(pod).items()
        }

    def attach_delta(self, pod: v1.Pod, node_name: str, sign: int) -> Dict[str, int]:
        """Node-row attach-count delta for adding (sign=+1) or removing
        (sign=-1) this pod on node_name, REFCOUNTED per volume handle:
        the oracle counts unique handles per node
        (plugins/volumes.py NodeVolumeLimits.filter unions idents), so
        the second pod sharing a handle on a node contributes 0 — a
        per-pod count would overcount and reject nodes the oracle
        accepts. Returned values are always positive (the caller applies
        the sign). Handle drift between add and remove (a PV rebinding
        while the pod runs) leaves a stale refcount until the next full
        rebuild (reset_attach) realigns."""
        by_driver = self._pod_volumes_by_driver(pod)
        delta: Dict[str, int] = {}
        new_drivers = []
        with self._lock:
            for drv, idents in by_driver.items():
                d = 0
                for h in idents:
                    key = (node_name, drv, h)
                    n = self._node_handles.get(key, 0)
                    if sign > 0:
                        if n == 0:
                            d += 1
                        self._node_handles[key] = n + 1
                    else:
                        if n <= 1:
                            self._node_handles.pop(key, None)
                            if n == 1:
                                d += 1
                        else:
                            self._node_handles[key] = n - 1
                if d:
                    delta[attach_resource_name(drv)] = d
                if sign > 0 and drv not in self._drivers_in_use:
                    self._drivers_in_use.add(drv)
                    new_drivers.append(drv)
        if new_drivers and self.on_new_driver is not None:
            self.on_new_driver()
        return delta

    def reset_attach(self) -> None:
        """A full encoding rebuild re-applies every pod's attach_delta
        from scratch."""
        with self._lock:
            self._node_handles.clear()


def _pv_driver(pv) -> Optional[str]:
    """PV -> CSI driver (PersistentVolumeSpec models only the CSI
    source; in-tree pod-level sources map through the oracle plugin's
    _INTREE_TO_CSI inside _csi_volumes_of)."""
    csi = getattr(pv.spec, "csi", None)
    if isinstance(csi, dict) and csi.get("driver"):
        return csi["driver"]
    return None


def distribute_term_groups(
    own: Optional[List[v1.NodeSelectorTerm]],
    groups: List[List[v1.NodeSelectorTerm]],
) -> List[v1.NodeSelectorTerm]:
    """AND of OR-groups -> ONE OR-group by distribution (the kernel's
    affinity tables hold a single OR-of-conjunctions). Empty terms match
    nothing (api.labels semantics) and are dropped; a group left empty
    makes the whole conjunction unsatisfiable -> a single never-term."""
    all_groups = ([own] if own is not None else []) + groups
    cleaned: List[List[v1.NodeSelectorTerm]] = []
    for g in all_groups:
        kept = [t for t in g if t.match_expressions or t.match_fields]
        if not kept:
            return [_NEVER_TERM]
        cleaned.append(kept)
    if not cleaned:
        return []
    combos: List[List[v1.NodeSelectorTerm]] = [[]]
    for g in cleaned:
        combos = [c + [t] for c in combos for t in g]
    out = []
    for parts in combos:
        me: List[v1.NodeSelectorRequirement] = []
        mf: List[v1.NodeSelectorRequirement] = []
        for t in parts:
            me.extend(t.match_expressions or [])
            mf.extend(t.match_fields or [])
        out.append(
            v1.NodeSelectorTerm(
                match_expressions=me or None, match_fields=mf or None
            )
        )
    return out


# In with an empty value set can never match
_NEVER_TERM = v1.NodeSelectorTerm(match_expressions=[
    v1.NodeSelectorRequirement(key="kubernetes.io/hostname",
                               operator="In", values=[])
])


def _own_affinity_terms(pod: v1.Pod) -> Optional[List[v1.NodeSelectorTerm]]:
    a = pod.spec.affinity
    if a is None or a.node_affinity is None:
        return None
    req = a.node_affinity.required_during_scheduling_ignored_during_execution
    if req is None:
        return None
    return list(req.node_selector_terms or [])
