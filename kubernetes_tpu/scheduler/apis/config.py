"""KubeSchedulerConfiguration: the typed config that assembles a scheduler.

Reference: pkg/scheduler/apis/config/types.go:49 KubeSchedulerConfiguration
(Parallelism, PercentageOfNodesToScore, PodInitialBackoffSeconds,
PodMaxBackoffSeconds, Profiles, Extenders), :109 KubeSchedulerProfile,
:170 Plugins / :200 PluginSet / :219 Plugin, :336 Extender; defaulting
pkg/scheduler/apis/config/v1beta1/defaults.go; validation
pkg/scheduler/apis/config/validation/validation.go.

The TPU backend is selected exactly the way the reference selects custom
behavior — through the config surface: a profile-level `backend: tpu`
field (our one extension; the reference's analog is a PluginConfig args
object or an Extenders entry, SURVEY.md §5 config system). Enabled/
disabled plugin merging follows the v1beta1 rules: profile plugins extend
the defaults; a Disabled entry of "*" wipes the point's defaults first.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..plugins.registry import default_plugins

DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 0  # adaptive (types.go:240)


@dataclass
class Plugin:
    name: str = ""
    weight: int = 0


@dataclass
class PluginSet:
    enabled: List[Plugin] = field(default_factory=list)
    disabled: List[Plugin] = field(default_factory=list)


EXTENSION_POINTS = (
    "queueSort", "preFilter", "filter", "postFilter", "preScore", "score",
    "reserve", "permit", "preBind", "bind", "postBind",
)


@dataclass
class Plugins:
    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)

    _FIELD_OF_POINT = {
        "queueSort": "queue_sort", "preFilter": "pre_filter", "filter": "filter",
        "postFilter": "post_filter", "preScore": "pre_score", "score": "score",
        "reserve": "reserve", "permit": "permit", "preBind": "pre_bind",
        "bind": "bind", "postBind": "post_bind",
    }

    def point(self, name: str) -> PluginSet:
        return getattr(self, self._FIELD_OF_POINT[name])


@dataclass
class KubeSchedulerProfile:
    scheduler_name: str = "default-scheduler"
    plugins: Optional[Plugins] = None
    plugin_config: Dict[str, dict] = field(default_factory=dict)
    backend: str = "tpu"  # tpu | oracle (the TPU build's selector)
    # multi-chip: shard the node axis over the first N devices as a
    # jax.sharding.Mesh (0 = single device). The analog of the
    # reference's `parallelism` knob, pointed at chips instead of
    # goroutines (parallel/sharded.py).
    mesh_devices: int = 0


@dataclass
class Extender:
    """types.go:336 Extender (the HTTP webhook config)."""

    url_prefix: str = ""
    filter_verb: str = ""
    preempt_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout_seconds: float = 30.0
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: List[str] = field(default_factory=list)


@dataclass
class KubeSchedulerConfiguration:
    parallelism: int = 16
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    profiles: List[KubeSchedulerProfile] = field(default_factory=list)
    extenders: List[Extender] = field(default_factory=list)
    max_batch: int = 128  # TPU scan-batch width (TPU-build extension)


def default_configuration() -> KubeSchedulerConfiguration:
    """defaults.go: one default profile, adaptive scoring percentage."""
    return KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile()])


def gang_configuration(
    permit_timeout: float = 60.0,
) -> KubeSchedulerConfiguration:
    """The default profile plus the Coscheduling gang gate, enabled at
    BOTH of its extension points (Permit gates the wave, Reserve indexes
    members into it) — the config-surface analog of the perf harness's
    gang_size wiring, for clusters (drills, soaks) built through
    `Cluster(scheduler_config=...)`."""
    plugins = Plugins()
    plugins.permit.enabled.append(Plugin("Coscheduling", 1))
    plugins.reserve.enabled.append(Plugin("Coscheduling", 1))
    profile = KubeSchedulerProfile(
        plugins=plugins,
        plugin_config={
            "Coscheduling": {"permit_timeout_seconds": permit_timeout}
        },
    )
    return KubeSchedulerConfiguration(profiles=[profile])


# -- plugin merge (v1beta1 mergePlugins semantics) --------------------------


def merged_plugins_for_profile(
    profile: KubeSchedulerProfile,
) -> Dict[str, List[Tuple[str, int]]]:
    """Defaults + profile's Enabled minus Disabled ('*' clears the point).

    Returns the framework's {point: [(name, weight)]} map."""
    merged = {k: list(v) for k, v in default_plugins().items()}
    if profile.plugins is None:
        return merged
    for point in EXTENSION_POINTS:
        ps = profile.plugins.point(point)
        current = merged.get(point, [])
        disabled_names = {p.name for p in ps.disabled}
        if "*" in disabled_names:
            current = []
        else:
            current = [(n, w) for n, w in current if n not in disabled_names]
        for p in ps.enabled:
            weight = p.weight if p.weight else 1
            current = [(n, w) for n, w in current if n != p.name]
            current.append((p.name, weight))
        merged[point] = current
    return merged


# -- validation (validation.go) ---------------------------------------------


class ConfigError(ValueError):
    pass


def validate_configuration(cfg: KubeSchedulerConfiguration) -> None:
    if cfg.parallelism <= 0:
        raise ConfigError("parallelism must be greater than 0")
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        raise ConfigError("percentageOfNodesToScore must be in [0, 100]")
    if cfg.pod_initial_backoff_seconds <= 0:
        raise ConfigError("podInitialBackoffSeconds must be greater than 0")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        raise ConfigError("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
    if not cfg.profiles:
        raise ConfigError("at least one profile is required")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        raise ConfigError(f"duplicate profile schedulerName in {names}")
    for profile in cfg.profiles:
        if not profile.scheduler_name:
            raise ConfigError("schedulerName is required")
        if profile.backend not in ("tpu", "oracle"):
            raise ConfigError(f"unknown backend {profile.backend!r}")
        if profile.mesh_devices < 0:
            raise ConfigError("meshDevices must be >= 0")
        if profile.mesh_devices and profile.backend != "tpu":
            raise ConfigError("meshDevices requires the tpu backend")
        merged = merged_plugins_for_profile(profile)
        for name, weight in merged.get("score", []):
            if weight < 0:
                raise ConfigError(f"score plugin {name}: weight must be >= 0")
        if len(merged.get("queueSort", [])) != 1:
            raise ConfigError("exactly one queueSort plugin is required")
        if not merged.get("bind"):
            raise ConfigError("at least one bind plugin is required")
    for ext in cfg.extenders:
        if not ext.url_prefix:
            raise ConfigError("extender urlPrefix is required")
        if ext.weight <= 0:
            raise ConfigError("extender weight must be positive")


# -- loading ----------------------------------------------------------------


def _from_camel(d: dict, keymap: Dict[str, str]) -> dict:
    return {keymap.get(k, k): v for k, v in d.items()}


def load_configuration(text: str) -> KubeSchedulerConfiguration:
    """Parse YAML/JSON config (the --config file). Shape follows
    kube-scheduler's v1beta1 wire format (camelCase keys)."""
    try:
        import yaml  # type: ignore

        data = yaml.safe_load(text)
    except ImportError:
        data = json.loads(text)
    if not isinstance(data, dict):
        raise ConfigError("config root must be a mapping")
    cfg = KubeSchedulerConfiguration()
    cfg.parallelism = data.get("parallelism", cfg.parallelism)
    cfg.percentage_of_nodes_to_score = data.get(
        "percentageOfNodesToScore", cfg.percentage_of_nodes_to_score
    )
    cfg.pod_initial_backoff_seconds = data.get(
        "podInitialBackoffSeconds", cfg.pod_initial_backoff_seconds
    )
    cfg.pod_max_backoff_seconds = data.get(
        "podMaxBackoffSeconds", cfg.pod_max_backoff_seconds
    )
    cfg.max_batch = data.get("maxBatch", cfg.max_batch)
    for pd in data.get("profiles", []) or []:
        profile = KubeSchedulerProfile(
            scheduler_name=pd.get("schedulerName", "default-scheduler"),
            backend=pd.get("backend", "tpu"),
            mesh_devices=pd.get("meshDevices", 0),
        )
        if "plugins" in pd and pd["plugins"]:
            plugins = Plugins()
            for point, body in pd["plugins"].items():
                if point not in Plugins._FIELD_OF_POINT:
                    raise ConfigError(f"unknown extension point {point!r}")
                ps = plugins.point(point)
                for e in body.get("enabled", []) or []:
                    ps.enabled.append(Plugin(e["name"], e.get("weight", 0)))
                for e in body.get("disabled", []) or []:
                    ps.disabled.append(Plugin(e["name"], e.get("weight", 0)))
            profile.plugins = plugins
        for pc in pd.get("pluginConfig", []) or []:
            profile.plugin_config[pc["name"]] = pc.get("args", {})
        cfg.profiles.append(profile)
    if not cfg.profiles:
        cfg.profiles = [KubeSchedulerProfile()]
    for ed in data.get("extenders", []) or []:
        cfg.extenders.append(
            Extender(
                url_prefix=ed.get("urlPrefix", ""),
                filter_verb=ed.get("filterVerb", ""),
                preempt_verb=ed.get("preemptVerb", ""),
                prioritize_verb=ed.get("prioritizeVerb", ""),
                bind_verb=ed.get("bindVerb", ""),
                weight=ed.get("weight", 1),
                enable_https=ed.get("enableHTTPS", False),
                http_timeout_seconds=ed.get("httpTimeout", 30.0),
                node_cache_capable=ed.get("nodeCacheCapable", False),
                ignorable=ed.get("ignorable", False),
                managed_resources=[
                    r.get("name", "") for r in ed.get("managedResources", []) or []
                ],
            )
        )
    validate_configuration(cfg)
    return cfg
