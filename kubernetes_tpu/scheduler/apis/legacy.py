"""Legacy Policy API: JSON predicates/priorities mapped onto plugins.

Reference: pkg/scheduler/apis/config/legacy_types.go:26 Policy and
pkg/scheduler/framework/plugins/legacy_registry.go — each legacy
predicate/priority name maps to modern plugin registrations at the
correct extension points; custom predicates (CheckNodeLabelPresence,
TestServiceAffinity) carry typed arguments that become plugin args.

`policy_to_profile` produces a KubeSchedulerProfile whose plugins REPLACE
the default sets ('*' disabled + explicit enables), matching
factory.go:207 createFromConfig semantics: a Policy fully determines the
predicate/priority sets.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .config import ConfigError, KubeSchedulerProfile, Plugin, Plugins

# legacy predicate name -> [(extension point, plugin name)]
# (legacy_registry.go NewLegacyRegistry predicate mappings)
PREDICATE_TO_PLUGIN: Dict[str, List[Tuple[str, str]]] = {
    "PodFitsResources": [("preFilter", "NodeResourcesFit"), ("filter", "NodeResourcesFit")],
    "PodFitsHostPorts": [("preFilter", "NodePorts"), ("filter", "NodePorts")],
    "HostName": [("filter", "NodeName")],
    "MatchNodeSelector": [("filter", "NodeAffinity")],
    "NoDiskConflict": [("filter", "VolumeRestrictions")],
    "NoVolumeZoneConflict": [("preFilter", "VolumeZone"), ("filter", "VolumeZone")],
    "MaxCSIVolumeCountPred": [("preFilter", "NodeVolumeLimits"), ("filter", "NodeVolumeLimits")],
    "MaxEBSVolumeCount": [("preFilter", "EBSLimits"), ("filter", "EBSLimits")],
    "MaxGCEPDVolumeCount": [("preFilter", "GCEPDLimits"), ("filter", "GCEPDLimits")],
    "MaxAzureDiskVolumeCount": [("preFilter", "AzureDiskLimits"), ("filter", "AzureDiskLimits")],
    "CheckNodeUnschedulable": [("filter", "NodeUnschedulable")],
    "PodToleratesNodeTaints": [("filter", "TaintToleration")],
    "MatchInterPodAffinity": [("preFilter", "InterPodAffinity"), ("filter", "InterPodAffinity")],
    "EvenPodsSpread": [("preFilter", "PodTopologySpread"), ("filter", "PodTopologySpread")],
    "CheckVolumeBinding": [
        ("preFilter", "VolumeBinding"),
        ("filter", "VolumeBinding"),
        ("reserve", "VolumeBinding"),
        ("preBind", "VolumeBinding"),
    ],
    "CheckNodeLabelPresence": [("filter", "NodeLabel")],
    "TestServiceAffinity": [("preFilter", "ServiceAffinity"), ("filter", "ServiceAffinity")],
}

# legacy priority name -> [(extension point, plugin name)]
PRIORITY_TO_PLUGIN: Dict[str, List[Tuple[str, str]]] = {
    "LeastRequestedPriority": [("score", "NodeResourcesLeastAllocated")],
    "MostRequestedPriority": [("score", "NodeResourcesMostAllocated")],
    "BalancedResourceAllocation": [("score", "NodeResourcesBalancedAllocation")],
    "RequestedToCapacityRatioPriority": [("score", "RequestedToCapacityRatio")],
    "SelectorSpreadPriority": [("preScore", "SelectorSpread"), ("score", "SelectorSpread")],
    "ServiceSpreadingPriority": [("preScore", "SelectorSpread"), ("score", "SelectorSpread")],
    "NodeAffinityPriority": [("preScore", "NodeAffinity"), ("score", "NodeAffinity")],
    "TaintTolerationPriority": [("preScore", "TaintToleration"), ("score", "TaintToleration")],
    "InterPodAffinityPriority": [("preScore", "InterPodAffinity"), ("score", "InterPodAffinity")],
    "EvenPodsSpreadPriority": [("preScore", "PodTopologySpread"), ("score", "PodTopologySpread")],
    "ImageLocalityPriority": [("score", "ImageLocality")],
    "NodePreferAvoidPodsPriority": [("score", "NodePreferAvoidPods")],
    "NodeLabelPriority": [("score", "NodeLabel")],
    "ServiceAntiAffinityPriority": [("preScore", "ServiceAffinity"), ("score", "ServiceAffinity")],
}

# always-on plugins regardless of Policy content (createFromConfig keeps
# QueueSort/Bind/PostFilter wiring)
_MANDATORY = {
    "queueSort": [("PrioritySort", 1)],
    "postFilter": [("DefaultPreemption", 1)],
    "bind": [("DefaultBinder", 1)],
}


def policy_to_profile(policy: dict, backend: str = "oracle") -> KubeSchedulerProfile:
    """Parse a legacy Policy dict (the JSON/ConfigMap format) into a
    profile with fully-specified plugin sets."""
    if policy.get("kind") not in (None, "Policy"):
        raise ConfigError(f"not a Policy: kind={policy.get('kind')!r}")
    points: Dict[str, List[Tuple[str, int]]] = {k: list(v) for k, v in _MANDATORY.items()}
    plugin_config: Dict[str, dict] = {}

    def add(point: str, name: str, weight: int = 1) -> None:
        cur = points.setdefault(point, [])
        for i, (n, w) in enumerate(cur):
            if n == name:
                if point == "score":
                    # two legacy priorities mapping to one plugin sum their
                    # weights (legacy_registry.go ProcessPriorityPolicy)
                    cur[i] = (n, w + weight)
                return
        cur.append((name, weight))

    for pred in policy.get("predicates", []) or []:
        name = pred.get("name", "")
        arg = pred.get("argument") or {}
        if name not in PREDICATE_TO_PLUGIN:
            raise ConfigError(f"unknown predicate {name!r}")
        for point, plugin in PREDICATE_TO_PLUGIN[name]:
            add(point, plugin)
        if name == "CheckNodeLabelPresence" and "labelsPresence" in arg:
            lp = arg["labelsPresence"]
            key = "presentLabels" if lp.get("presence", True) else "absentLabels"
            cfg = plugin_config.setdefault("NodeLabel", {})
            cfg.setdefault(key, []).extend(lp.get("labels", []))
        if name == "TestServiceAffinity" and "serviceAffinity" in arg:
            cfg = plugin_config.setdefault("ServiceAffinity", {})
            cfg.setdefault("affinityLabels", []).extend(
                arg["serviceAffinity"].get("labels", [])
            )

    for prio in policy.get("priorities", []) or []:
        name = prio.get("name", "")
        weight = int(prio.get("weight", 1))
        arg = prio.get("argument") or {}
        if name not in PRIORITY_TO_PLUGIN:
            raise ConfigError(f"unknown priority {name!r}")
        for point, plugin in PRIORITY_TO_PLUGIN[name]:
            add(point, plugin, weight if point == "score" else 1)
        if name == "NodeLabelPriority" and "labelPreference" in arg:
            lp = arg["labelPreference"]
            key = (
                "presentLabelsPreference"
                if lp.get("presence", True)
                else "absentLabelsPreference"
            )
            cfg = plugin_config.setdefault("NodeLabel", {})
            cfg.setdefault(key, []).extend(lp.get("labels", []))
        if name == "ServiceAntiAffinityPriority" and "serviceAntiAffinity" in arg:
            cfg = plugin_config.setdefault("ServiceAffinity", {})
            cfg.setdefault("antiAffinityLabelsPreference", []).append(
                arg["serviceAntiAffinity"].get("label", "")
            )

    # build a Plugins override: disable '*' then enable exactly `points`
    plugins = Plugins()
    for point, entries in points.items():
        ps = plugins.point(point)
        ps.disabled.append(Plugin("*", 0))
        for name, weight in entries:
            ps.enabled.append(Plugin(name, weight))
    # clear extension points the Policy doesn't populate
    for point in Plugins._FIELD_OF_POINT:
        if point not in points:
            plugins.point(point).disabled.append(Plugin("*", 0))
    return KubeSchedulerProfile(
        scheduler_name=policy.get("schedulerName", "default-scheduler"),
        plugins=plugins,
        plugin_config=plugin_config,
        backend=backend,
    )
