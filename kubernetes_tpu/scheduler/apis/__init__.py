"""Scheduler ComponentConfig (reference: pkg/scheduler/apis/config)."""

from .config import (  # noqa: F401
    Extender,
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    Plugin,
    PluginSet,
    Plugins,
    default_configuration,
    load_configuration,
    validate_configuration,
)
