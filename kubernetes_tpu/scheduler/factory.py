"""Scheduler assembly from KubeSchedulerConfiguration.

Reference: pkg/scheduler/factory.go:90 create — config profiles →
framework instances, extender configs → HTTPExtenders, queue/backoff
knobs → PriorityQueue; cmd/kube-scheduler/app/server.go:299 Setup.
"""

from __future__ import annotations

from typing import Optional

from ..client.clientset import Clientset
from ..client.informer import SharedInformerFactory
from .apis.config import (
    ConfigError,
    KubeSchedulerConfiguration,
    default_configuration,
    merged_plugins_for_profile,
    validate_configuration,
)
from .extender import HTTPExtender
from .framework.runtime import Framework
from .plugins.registry import new_in_tree_registry
from .scheduler import Scheduler
from .tpu_backend import TPUBackend
from ..volume.binder import SchedulerVolumeBinder

# score plugin name -> kernel weight key (ops/kernel.py DEFAULT_WEIGHTS)
_KERNEL_WEIGHT_KEYS = {
    "NodeResourcesBalancedAllocation": "balanced",
    "ImageLocality": "image",
    "InterPodAffinity": "ipa",
    "NodeResourcesLeastAllocated": "least",
    "NodeAffinity": "node_affinity",
    "NodePreferAvoidPods": "prefer_avoid",
    "PodTopologySpread": "pts",
    "TaintToleration": "taint",
}


def create_scheduler(
    clientset: Clientset,
    informer_factory: SharedInformerFactory,
    cfg: Optional[KubeSchedulerConfiguration] = None,
    profile_name: Optional[str] = None,
    registry=None,
) -> Scheduler:
    cfg = cfg or default_configuration()
    validate_configuration(cfg)
    if profile_name is None:
        profile = cfg.profiles[0]
    else:
        by_name = {p.scheduler_name: p for p in cfg.profiles}
        if profile_name not in by_name:
            raise ConfigError(f"no profile named {profile_name!r}")
        profile = by_name[profile_name]
    merged = merged_plugins_for_profile(profile)

    tpu_backend = None
    if profile.backend == "tpu":
        if cfg.extenders:
            raise ConfigError(
                "extenders require the oracle backend (profile backend: oracle)"
            )
        weights = {k: 0 for k in _KERNEL_WEIGHT_KEYS.values()}
        for name, weight in merged.get("score", []):
            key = _KERNEL_WEIGHT_KEYS.get(name)
            if key is None:
                raise ConfigError(
                    f"score plugin {name!r} has no TPU kernel equivalent; "
                    f"use backend: oracle for this profile"
                )
            weights[key] = weight
        mesh = None
        if profile.mesh_devices:
            import jax

            from ..parallel.sharded import make_mesh

            n_avail = len(jax.devices())
            if n_avail < profile.mesh_devices:
                # silently truncating to fewer chips would hide a
                # topology misconfiguration behind halved throughput
                raise ConfigError(
                    f"meshDevices: {profile.mesh_devices} but only "
                    f"{n_avail} devices are available"
                )
            mesh = make_mesh(n_devices=profile.mesh_devices)
        tpu_backend = TPUBackend(weights=weights, mesh=mesh)

    sched = Scheduler(
        clientset,
        informer_factory,
        backend=profile.backend,
        tpu_backend=tpu_backend,
        percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score,
        max_batch=cfg.max_batch,
        pod_initial_backoff=cfg.pod_initial_backoff_seconds,
        pod_max_backoff=cfg.pod_max_backoff_seconds,
        extenders=[HTTPExtender(e) for e in cfg.extenders],
        parallelism=cfg.parallelism,
    )
    # Volume subsystem wiring: informer-cache listers + API client for the
    # binder (volume_binding.go New → SchedulerVolumeBinder).
    pvc_inf = informer_factory.informer_for("persistentvolumeclaims")
    pv_inf = informer_factory.informer_for("persistentvolumes")
    sc_inf = informer_factory.informer_for("storageclasses")
    csi_inf = informer_factory.informer_for("csinodes")
    # Spread/service-affinity informers only when a profile plugin consumes
    # them (the default profile doesn't); created eagerly — BEFORE
    # informer_factory.start() — because a lazily-created informer would
    # never be started.
    enabled_names = {n for entries in merged.values() for n, _ in entries}
    spread_listers = None
    service_lister = None
    if enabled_names & {"SelectorSpread", "ServiceAffinity"}:
        svc_inf = informer_factory.informer_for("services")
        rc_inf = informer_factory.informer_for("replicationcontrollers")
        rs_inf = informer_factory.informer_for("replicasets")
        ss_inf = informer_factory.informer_for("statefulsets")
        service_lister = svc_inf.list
        spread_listers = (
            lambda: (svc_inf.list(), rc_inf.list(), rs_inf.list(), ss_inf.list())
        )
    volume_binder = SchedulerVolumeBinder(
        list_pvcs=pvc_inf.list,
        list_pvs=pv_inf.list,
        list_storage_classes=sc_inf.list,
        client=clientset,
        get_pvc=pvc_inf.get,
    )
    framework = Framework(
        registry or new_in_tree_registry(),
        profile_name=profile.scheduler_name,
        plugins=merged,
        plugin_config=profile.plugin_config,
        snapshot_fn=lambda: sched.snapshot,
        parallelism=cfg.parallelism,
        handle_extras={
            "volume_binder": volume_binder,
            "volume_listers": (pvc_inf.list, pv_inf.list),
            "csi_node_lister": csi_inf.list,
            "client": clientset,
            "service_lister": service_lister,
            "spread_listers": spread_listers,
        },
    )
    framework.nominator = sched.nominator
    framework.pdb_lister = sched._list_pdbs
    framework.cache = sched.cache
    sched.framework = framework
    sched.profile_name = profile.scheduler_name
    return sched
