"""The scheduler: queue → scheduleOne → assume → async bind.

Reference: pkg/scheduler/scheduler.go — New (:188), Run (:311,
wait.UntilWithContext(scheduleOne)), scheduleOne (:427), assume (:359),
bind (:381); event wiring pkg/scheduler/eventhandlers.go:364
addAllEventHandlers.

Pipeline shape preserved exactly: the SCHEDULING cycle is serial (one pod
at a time against the assumed state), the BINDING cycle is asynchronous
per pod (a worker thread doing the apiserver bind), bridged by the
assume/forget protocol in the cache — plus the TPU twist: the scheduling
cycle drains a RUN of pending pods from the queue and schedules them in
one batched device dispatch (ops/batch.py) when their specs allow,
preserving sequential assume semantics.

TPU mode runs those cycles as a three-stage pipeline (pipeline_depth,
default 2): the scheduler thread pops + encodes + dispatches batch k+1,
the device scans batch k (double-buffered dispatches chained on the
session carry), and a completion worker — the async bind queue —
harvests batch k-1 and runs assume -> reserve/permit -> bind-submit ->
failure handling strictly in dispatch order. Decisions are bit-identical
to the sequential depth-0 path (tests/test_pipeline_parity.py): the
device carry is the assume cache, so completion order — not completion
TIME — is what sequential assume semantics require.
"""

from __future__ import annotations

import copy
import logging
import os
import random
import threading
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import time as _time

from ..api import types as v1
from ..apiserver.server import APIError, FenceExpired
from ..client.clientset import Clientset
from ..client.events import EventRecorder
from ..client.informer import EventHandler, SharedInformerFactory, meta_namespace_key
from ..utils import devtime, knobs, serde, tracing
from . import metrics
from .core import GenericScheduler, ScheduleResult
from .framework.interface import Code, CycleState, FitError
from .framework.runtime import Framework
from .framework.snapshot import Snapshot
from .internal.cache import SchedulerCache
from .internal.nominator import PodNominator
from .internal.queue import PriorityQueue
from . import preemption as fast_preemption
from .plugins.defaultpreemption import get_lower_priority_nominated_pods
from .plugins.registry import default_plugins, new_in_tree_registry
from .degradation import RUNG_ORACLE, DeviceFault
from .tpu_backend import TPUBackend

logger = logging.getLogger(__name__)


class WorkerKilled(Exception):
    """A pipeline worker thread was told to die (FaultInjector kill seam
    / ChaosMonkey crash-scheduler). Escapes the per-iteration isolation
    so the supervision wrapper sees a real crash."""


class PipelineStalled(RuntimeError):
    """_drain_pipeline exceeded its timeout: in-flight batches did not
    land even though every device wait is watchdog-bounded. The raiser
    has already demoted the ladder; callers requeue their pods instead
    of blocking the scheduler forever."""


def _has_required_anti_affinity(pod: v1.Pod) -> bool:
    a = pod.spec.affinity
    return (
        a is not None
        and a.pod_anti_affinity is not None
        and bool(a.pod_anti_affinity.required_during_scheduling_ignored_during_execution)
    )


class Scheduler:
    def __init__(
        self,
        clientset: Clientset,
        informer_factory: SharedInformerFactory,
        framework: Optional[Framework] = None,
        backend: str = "tpu",  # "tpu" | "oracle"
        tpu_backend: Optional[TPUBackend] = None,
        percentage_of_nodes_to_score: int = 100,
        max_batch: int = 128,
        rng: Optional[random.Random] = None,
        pod_initial_backoff: float = 1.0,
        pod_max_backoff: float = 10.0,
        extenders: Optional[List] = None,
        parallelism: int = 16,
        pipeline_depth: int = 2,
    ):
        self.client = clientset
        self.informers = informer_factory
        self.cache = SchedulerCache()
        self.queue = PriorityQueue(
            pod_initial_backoff=pod_initial_backoff,
            pod_max_backoff=pod_max_backoff,
        )
        self.extenders = extenders or []
        self.parallelism = parallelism
        self.backend = backend
        self.max_batch = max_batch
        self.rng = rng or random.Random()
        self.snapshot = Snapshot()
        self.nominator = PodNominator()
        # a Framework exists in BOTH modes: TPU mode uses it for the long
        # tail (preemption dry-runs, extenders) — SURVEY.md §7 stage 4.
        # The default framework gets real volume listers: the kernel
        # path's bound-PVC pods pass through VolumeBinding's Reserve and
        # the oracle diversion needs a working binder (the factory wires
        # richer extras for configured profiles, factory.py:126)
        self.framework = framework or Framework(
            new_in_tree_registry(),
            plugins=default_plugins(),
            snapshot_fn=lambda: self.snapshot,
            handle_extras=self._volume_handle_extras(),
        )
        self.framework.nominator = self.nominator
        self.framework.pdb_lister = self._list_pdbs
        self.framework.cache = self.cache  # Coscheduling counts reservations
        # The oracle algorithm exists in BOTH modes: TPU mode routes pods
        # whose constraints the kernel can't express (PVC volumes) to it
        self.algorithm = GenericScheduler(
            percentage_of_nodes_to_score=percentage_of_nodes_to_score,
            extenders=self.extenders,
            rng=self.rng,
        )
        # pipelined scheduling loop (PERF_NOTES "kernel-to-loop gap"):
        # depth N lets N dispatched batches ride ahead of their
        # completions. The scheduler thread only pops + encodes +
        # dispatches; a dedicated completion worker (the async bind
        # queue) harvests device results and runs assume -> reserve/
        # permit -> bind-submit -> failure handling, strictly in
        # dispatch order — so the device scans batch k while the host
        # encodes k+1 and binds k-1. Depth 0 = fully sequential
        # (dispatch then complete inline on the scheduler thread): the
        # bit-parity reference path (tests/test_pipeline_parity.py).
        self.pipeline_depth = max(0, pipeline_depth)
        if backend == "tpu":
            self.tpu = tpu_backend or TPUBackend(rng=self.rng)
            self.tpu.max_pending = max(1, self.pipeline_depth)
            # with a completion worker present (depth >= 1), a full
            # _pending FIFO back-pressures dispatch_many on a condition
            # variable instead of harvesting inline — the scheduler
            # thread never decodes a harvest (the dispatch critical
            # path never pays harvest+assume+decode)
            self.tpu.async_harvest_drain = self.pipeline_depth >= 1
            self.cache.add_listener(self.tpu)
            self._wire_volume_device()
        else:
            self.tpu = None
        self._stop = threading.Event()
        self._paused = threading.Event()
        # completion queue: (todo, handle, cycle) in dispatch order. The
        # worker pops the HEAD, completes it, THEN removes it — so an
        # empty deque means every dispatched batch has fully landed
        # (assumed + bind submitted + failures handled).
        self._completions: deque = deque()
        self._completion_cv = threading.Condition()
        self._completion_thread: Optional[threading.Thread] = None
        # decided placements that never landed in the cache (assume lost
        # to an informer race, RETRY re-gates, recovery abandons): while
        # the dropping batch was in flight, LATER in-flight batches
        # chained on a carry containing the dropped placement — a basis
        # the cache never held. Latched onto each handle at dispatch
        # (with the cache's foreign-mutation generation) so the shadow
        # sentinel voids audits whose flight overlapped a drop. Plain
        # int under the GIL: written by the completion worker, read at
        # dispatch.
        self._dropped_decisions = 0
        # exact per-pod scheduling latencies (seconds) for the perf
        # harness: (queue-admission->bind-sent, pop->bind-sent, attempts).
        # The histograms carry the same data bucket-quantized; the harness
        # wants exact percentiles (scheduler_perf util.go:177 extracts
        # Perc50/90/99 from the live histogram — ours keeps the samples).
        self.latency_samples: deque = deque(maxlen=200_000)
        # monotonic bind-sent time per bound pod: the perf harness reads
        # the EXACT first-bind..last-bind window from these instead of a
        # 1s polling grid (whose quantization turned every sub-second
        # 500-node run into a 1000/k pods/s artifact)
        self.bind_timestamps: deque = deque(maxlen=200_000)
        # permit drainer state: pods parked at Permit (WAIT) register a
        # listener and a single thread releases them in waves
        self._permit_lock = threading.Lock()
        self._permit_parked: Dict[str, Tuple] = {}
        self._permit_released: List[Tuple] = []
        self._permit_wake = threading.Event()
        self._permit_thread: Optional[threading.Thread] = None
        # gang deadlock-breaker hysteresis: (ns, group) -> (membership
        # signature, consecutive stalled ticks); a back-off fires only
        # after KTPU_GANG_DEADLOCK_TICKS identical observations with
        # >=2 gangs stalled, and never the same gang twice in a row
        self._gang_stall: Dict[Tuple[str, str], Tuple] = {}
        self._gang_tick_last = 0.0
        self._gang_last_backoff: Optional[Tuple[str, str]] = None
        # in-flight preemptions, tracked per NOMINATED NODE: a node's
        # preemptors are parked until the node's ENTIRE claimed victim
        # set has delete-echoed, then queue.activate()d together —
        # precise event-driven re-admission (scheduling_queue.go
        # Activate / queueing-hints semantics) instead of flushing every
        # parked pod on every delete. Waking each preemptor on its OWN
        # victims alone thrashes when several preemptors share a node
        # (the planner's pick-one legitimately piles them up): the early
        # riser fails the nominated-node filter against its siblings'
        # still-dying victims, falls into the kernel path, and replans —
        # measured as a mid-window session teardown + 14s recompile.
        # The pod-key set also backs the guard that stops a re-popped
        # preemptor from planning a SECOND victim set while the first is
        # dying (the oracle's PodEligibleToPreemptOthers
        # terminating-victim check, default_preemption.go:539).
        self._preempt_lock = threading.Lock()
        self._node_waves: Dict[str, Tuple[set, List]] = {}  # node -> (victim keys, infos)
        self._victim_waiters: Dict[str, str] = {}  # victim key -> node
        self._inflight_preemptors: set = set()  # pod keys
        self._thread: Optional[threading.Thread] = None
        # device-fault plumbing: the injector seam (None in production),
        # and the drain budget — generous relative to the backend's
        # dispatch watchdog, which is what actually unsticks a wedged
        # wait; the drain timeout is the second line of defense
        self.faults = None
        self.drain_timeout = knobs.get_float(
            "KTPU_DRAIN_TIMEOUT", default=None)
        # leader election / fencing (enable_leader_election): every
        # state-changing write carries self._fence; the apiserver
        # rejects a token whose lease epoch has moved on. The token is
        # LATCHED — demotion deliberately leaves the stale token in
        # place so straggler binder-thread writes are rejected server-
        # side instead of going out unfenced; only the next promotion
        # replaces it.
        self.elector = None
        self._fence = None
        # requeue-exactly-once across the demote -> promote round trip:
        # pod key -> metadata.generation of every pod the demotion
        # drain sent back to the queue; the next reconcile_from_store
        # consults (then clears) it so the relist cannot requeue the
        # same generation a second time
        self._drain_requeued: Dict[str, int] = {}
        self._reconcile_lock = threading.Lock()
        self._binders = ThreadPoolExecutor(max_workers=8, thread_name_prefix="binder")
        self._inflight = 0  # scheduling batches + binds not yet finished
        self._inflight_lock = threading.Lock()
        self.profile_name = (
            self.framework.profile_name if self.framework else "default-scheduler"
        )
        self.recorder = EventRecorder(clientset, self.profile_name)
        # backend-health Events involve the SCHEDULER itself (there is
        # no single pod to attach a ladder demotion to); observers watch
        # Events on this pseudo-object the way they watch node Events
        import types as _pytypes

        self._self_ref = _pytypes.SimpleNamespace(
            kind="Scheduler",
            metadata=v1.ObjectMeta(
                name=self.profile_name, namespace="default", uid=""),
        )
        if self.tpu is not None:
            self.tpu.health_cb = self._health_event
        from ..utils import configz

        configz.install_knobs(
            "ktpu",
            pipeline_depth=self.pipeline_depth,
            max_batch=self.max_batch,
            # the RESOLVED drain budget (the /configz contract is
            # runtime-effective values): mirror _drain_pipeline's
            # default derivation when KTPU_DRAIN_TIMEOUT is unset
            drain_timeout=(
                self.drain_timeout
                if self.drain_timeout is not None
                else max(30.0, 3.0 * (self.tpu.watchdog_timeout
                                      if self.tpu is not None else 30.0))
            ),
            backend=self.backend,
        )
        # host-overload monitor (degradation.OverloadMonitor): watches
        # completion-FIFO age, queue depth and completion-stage latency
        # once per completed batch; under sustained pressure sheds
        # optional work in a fixed order with hysteretic LIFO restore.
        # Decision-inert by construction (tests/test_overload.py pins a
        # never-triggered run bit-identical) — levers only change how
        # much audit/overlap work the host pays for.
        self._shed_saved: Dict[str, object] = {}
        self._completion_durations: deque = deque(maxlen=64)
        self.overload = None
        if self.tpu is not None and knobs.get_bool("KTPU_OVERLOAD"):
            from .degradation import OverloadMonitor

            high_age = knobs.get_float("KTPU_OVERLOAD_FIFO_AGE")
            high_q = knobs.get_int(
                "KTPU_OVERLOAD_QUEUE_DEPTH",
                default=max(256, 4 * self.max_batch))
            self.overload = OverloadMonitor(
                self._overload_levers(),
                high_fifo_age=high_age,
                low_fifo_age=knobs.get_float(
                    "KTPU_OVERLOAD_FIFO_AGE_LOW", default=high_age * 0.2),
                high_queue_depth=high_q,
                low_queue_depth=knobs.get_int(
                    "KTPU_OVERLOAD_QUEUE_DEPTH_LOW", default=high_q // 4),
                # stage-latency signal is opt-in: per-stage p99 is
                # workload-shaped, the deployment sets the water mark
                high_stage_p99=knobs.get_float("KTPU_OVERLOAD_STAGE_P99"),
                shed_dwell=knobs.get_int("KTPU_OVERLOAD_SHED_DWELL"),
                restore_dwell=knobs.get_int("KTPU_OVERLOAD_RESTORE_DWELL"),
                cooldown=knobs.get_float("KTPU_OVERLOAD_COOLDOWN"),
                on_shed=lambda what, sig: self._health_event(
                    "Warning", "OverloadShed",
                    f"host overload: shed {what} ({sig})"),
                on_restore=lambda what, sig: self._health_event(
                    "Normal", "OverloadRestore",
                    f"host pressure cleared: restored {what}"),
            )
            configz.install_knobs(
                "ktpu",
                overload=True,
                overload_fifo_age=self.overload.high_fifo_age,
                overload_fifo_age_low=self.overload.low_fifo_age,
                overload_queue_depth=self.overload.high_queue_depth,
                overload_queue_depth_low=self.overload.low_queue_depth,
                overload_stage_p99=self.overload.high_stage_p99,
                overload_shed_dwell=self.overload.shed_dwell,
                overload_restore_dwell=self.overload.restore_dwell,
                overload_cooldown=self.overload.cooldown,
                overload_levers=[
                    name for name, _, _ in self.overload.levers],
            )
        else:
            configz.install_knobs("ktpu", overload=False)
        self._add_event_handlers()

    def _overload_levers(self) -> List[Tuple]:
        """The fixed shed order, cheapest-loss first: each lever is
        (name, shed, restore) and touches only OPTIONAL work — the
        explain decode, the parity sentinel's sample rate, the flight
        recorder, dispatch speculation. None of them can change a
        placement; none tears down the live device session (that is the
        point: shedding must cost ~nothing, see
        TPUBackend.set_shadow_rate_only)."""
        from ..utils import configz

        tpu = self.tpu
        saved = self._shed_saved

        def shed_explain():
            tpu.explain_harvest = False

        def restore_explain():
            tpu.explain_harvest = True

        def shed_shadow():
            saved["shadow"] = tpu.shadow_sample
            tpu.set_shadow_rate_only(0.0)

        def restore_shadow():
            tpu.set_shadow_rate_only(saved.pop("shadow", 0.0))

        def shed_devtime():
            saved["devtime"] = devtime.level()
            devtime.set_level(0)
            configz.install_knobs("ktpu", devtime_level=0)

        def restore_devtime():
            lvl = saved.pop("devtime", 0)
            devtime.set_level(lvl)
            configz.install_knobs("ktpu", devtime_level=lvl)

        def shed_trace():
            saved["trace"] = tracing.level()
            tracing.set_level(0)
            configz.install_knobs("ktpu", trace_level=0)

        def restore_trace():
            lvl = saved.pop("trace", 0)
            tracing.set_level(lvl)
            configz.install_knobs("ktpu", trace_level=lvl)

        def shed_speculation():
            saved["speculation"] = tpu.speculation
            tpu.speculation = False
            configz.install_knobs("ktpu", speculation=False)

        def restore_speculation():
            spec = saved.pop("speculation", True)
            tpu.speculation = spec
            configz.install_knobs("ktpu", speculation=spec)

        return [
            ("explain-harvest", shed_explain, restore_explain),
            ("shadow-sample", shed_shadow, restore_shadow),
            ("devtime", shed_devtime, restore_devtime),
            ("trace", shed_trace, restore_trace),
            ("speculation", shed_speculation, restore_speculation),
        ]

    def _health_event(self, event_type: str, reason: str,
                      message: str) -> None:
        """Backend/pipeline health transition -> k8s Event on the
        scheduler pseudo-object (the TPUBackend's health_cb target and
        the pipeline seams' own reporter). Repeats aggregate into one
        Event with a bumped count (EventRecorder semantics), so a miss
        storm or a flapping ladder stays one line per transition kind."""
        self.recorder.event(self._self_ref, event_type, reason, message)

    # -- event wiring (eventhandlers.go:364) -------------------------------

    def _add_event_handlers(self) -> None:
        pods = self.informers.pods()
        nodes = self.informers.nodes()

        def assigned(pod: v1.Pod) -> bool:
            return bool(pod.spec.node_name)

        def on_pod_add(pod: v1.Pod) -> None:
            if assigned(pod):
                self.cache.add_pod(pod)  # may confirm an assumed pod
                self.nominator.delete_nominated_pod_if_exists(pod)
                self._clear_preempt_tracking(pod)
            elif self._schedulable(pod):
                if pod.status.nominated_node_name:
                    self.nominator.add_nominated_pod(pod)
                self.queue.add(pod)

        def on_pod_update(old: v1.Pod, new: v1.Pod) -> None:
            if assigned(new):
                if assigned(old):
                    self.cache.update_pod(old, new)
                else:
                    self.cache.add_pod(new)
                self.nominator.delete_nominated_pod_if_exists(new)
                # a pod can BECOME assigned while a queue entry for it
                # exists (another scheduler instance bound it, or a
                # relist refresh after restart delivers the bound state
                # as an update) — retire the entry and any preemption
                # tracking exactly as the add path does, or the ghost
                # entry 409s on every future bind attempt
                self.queue.delete(new)
                self._clear_preempt_tracking(new)
            elif self._schedulable(new):
                self.nominator.update_nominated_pod(old, new)
                self.queue.update(old, new)

        def on_pod_delete(pod: v1.Pod) -> None:
            if assigned(pod):
                self.cache.remove_pod(pod)
                self.queue.move_all_to_active_or_backoff_queue("AssignedPodDelete")
                self._on_victim_deleted(pod)
            else:
                self.nominator.delete_nominated_pod_if_exists(pod)
                self.queue.delete(pod)
                self._clear_preempt_tracking(pod)
                # a deleted pod parked at Permit must resolve NOW, not
                # camp assumed until its timeout — and if it is a gang
                # member, the whole gang rolls back with it (its wave
                # can never complete; partial gangs must not hold
                # capacity)
                fwk = self.framework
                if fwk is not None and hasattr(fwk, "get_waiting_pod") \
                        and fwk.get_waiting_pod(v1.pod_key(pod)) is not None:
                    gang = self._gang_plugin()
                    if gang is not None:
                        gang.reject_gang_of(
                            pod, "member-deleted",
                            message=f"gang member "
                                    f"{pod.metadata.name!r} was deleted "
                                    f"while waiting at Permit",
                        )
                    # non-gang waiting pods (or a raced gate): direct
                    # rejection is the idempotent backstop
                    fwk.reject_waiting_pod(
                        v1.pod_key(pod), "Scheduler",
                        "pod deleted while waiting at Permit",
                    )

        pods.add_event_handler(
            EventHandler(on_add=on_pod_add, on_update=on_pod_update, on_delete=on_pod_delete)
        )

        def on_node_add(node: v1.Node) -> None:
            self.cache.add_node(node)
            self.queue.move_all_to_active_or_backoff_queue("NodeAdd")

        def on_node_update(old: v1.Node, new: v1.Node) -> None:
            self.cache.update_node(new)
            self.queue.move_all_to_active_or_backoff_queue("NodeUpdate")

        def on_node_delete(node: v1.Node) -> None:
            self.cache.remove_node(node.metadata.name)

        nodes.add_event_handler(
            EventHandler(on_add=on_node_add, on_update=on_node_update, on_delete=on_node_delete)
        )

    @staticmethod
    def _schedulable(pod: v1.Pod) -> bool:
        return pod.metadata.deletion_timestamp is None

    def _volume_handle_extras(self) -> dict:
        from ..volume.binder import SchedulerVolumeBinder

        pvc_inf = self.informers.informer_for("persistentvolumeclaims")
        pv_inf = self.informers.informer_for("persistentvolumes")
        sc_inf = self.informers.informer_for("storageclasses")
        csi_inf = self.informers.informer_for("csinodes")
        return {
            "volume_binder": SchedulerVolumeBinder(
                list_pvcs=pvc_inf.list,
                list_pvs=pv_inf.list,
                list_storage_classes=sc_inf.list,
                client=self.client,
                get_pvc=pvc_inf.get,
            ),
            "volume_listers": (pvc_inf.list, pv_inf.list),
            "csi_node_lister": csi_inf.list,
        }

    def _wire_volume_device(self) -> None:
        """Volume device path (volume_device.py): PVC/PV/CSINode listers
        feed the resolver; any volume-object event bumps its version and
        queues an encoding rebuild. Informers are created HERE — before
        factory.start() — because lazily-created informers never start."""
        from .volume_device import VolumeDeviceResolver

        pvc_inf = self.informers.informer_for("persistentvolumeclaims")
        pv_inf = self.informers.informer_for("persistentvolumes")
        csi_inf = self.informers.informer_for("csinodes")
        resolver = VolumeDeviceResolver(pvc_inf.list, pv_inf.list, csi_inf.list)
        self.tpu.set_volume_resolver(resolver)

        def bump_for(kind):
            return EventHandler(
                on_add=lambda obj: self.tpu.on_volume_change(kind, obj),
                on_update=lambda old, new: self.tpu.on_volume_change(kind, new),
                on_delete=lambda obj: self.tpu.on_volume_change(kind, obj),
            )

        pvc_inf.add_event_handler(bump_for("pvc"))
        pv_inf.add_event_handler(bump_for("pv"))
        csi_inf.add_event_handler(bump_for("csinode"))

    # -- leader election / split-brain-safe failover -----------------------

    def enable_leader_election(self, identity: str, config=None) -> None:
        """Arm lease-based leader election (call before start()): the
        instance then starts PAUSED and only pops pods while it holds
        the leader lease. Every state-changing write — binds,
        nominatedNodeName patches, victim deletes — carries the lease
        fencing token, and the apiserver rejects a deposed epoch's
        writes with FenceExpired; on fence loss the instance demotes
        (pause, abandon the device FIFO, flush completions) and rejoins
        the election."""
        from ..client.leaderelection import LeaderElectionConfig, LeaderElector

        if config is None:
            config = LeaderElectionConfig(identity=identity)
        elif not config.identity:
            config.identity = identity
        self.elector = LeaderElector(
            self.client,
            config,
            on_started_leading=self._on_started_leading,
            on_stopped_leading=self._on_stopped_leading,
        )

    def _on_started_leading(self) -> None:
        """Promotion (elector thread): latch the fencing token FIRST —
        every write from here on carries the new epoch — then reconcile
        the authoritative store into the caches, then open the pop
        gate. Order matters: reconcile-before-resume is what makes a
        restarted leader's decisions bit-identical to a never-crashed
        one's on the surviving pod set."""
        self._fence = self.elector.fencing_token()
        metrics.leader_transitions.inc()
        logger.info(
            "%s promoted to leader (epoch %s)",
            self.profile_name, getattr(self._fence, "transitions", None),
        )
        self._health_event(
            "Normal", "LeaderElected",
            f"{self.profile_name} acquired the scheduler lease",
        )
        try:
            self.reconcile_from_store()
        except Exception:  # noqa: BLE001 — the informer relist is the
            # backstop for anything a failed reconcile missed
            traceback.print_exc()
        self.resume()

    def _on_stopped_leading(self) -> None:
        """Demotion (fence loss, abdication, or stop): close the pop
        gate, abandon not-yet-harvested device batches and flush the
        completion FIFO (abandoned batches resolve RETRY_NODE and
        requeue), and record what the drain requeued so the NEXT
        promotion's reconcile can't requeue the same generation twice.
        The stale fencing token is deliberately NOT cleared: straggler
        writes still in binder threads must be rejected server-side,
        not escape unfenced."""
        self.pause()
        # roll back every waiting gang BEFORE draining: the parked
        # members hold assumed capacity this instance no longer owns —
        # the successor relists and reschedules them, and a deposed
        # leader completing a gang later would only bounce off the
        # fence one member-bind at a time. Whole waves, never a prefix.
        gang = self._gang_plugin()
        if gang is not None:
            for gate in gang.waiting_gangs():
                gang.reject_gang(
                    gate.namespace, gate.group, "demotion",
                    message="scheduler demoted while the gang waited "
                            "at Permit",
                )
        with self._completion_cv:
            fifo_pods = [
                info.pod for item in self._completions for info in item[0]
            ]
        # the completion worker is STILL RUNNING here (demotion is not
        # teardown) — it owns the FIFO, so flush through it: abandon the
        # un-harvested device batches (their results resolve RETRY_NODE)
        # and wait for the worker to land everything. Popping the FIFO
        # from this thread (_recover_completions) would race the worker.
        try:
            if self.tpu is not None:
                self.tpu.abandon_pending()
            self._drain_pipeline()
        except Exception:  # noqa: BLE001 — demotion must complete
            traceback.print_exc()
        pending = {v1.pod_key(p) for p in self.queue.pending_pods()}
        for pod in fifo_pods:
            key = v1.pod_key(pod)
            if key in pending:
                self._drain_requeued[key] = pod.metadata.generation or 0
        logger.info("%s demoted: lease lost or released", self.profile_name)

    def reconcile_from_store(self) -> Dict[str, int]:
        """Cold-restart / promotion reconciliation: relist pods from the
        authoritative store and repair this instance's view so a
        restarted (or newly promoted) scheduler treats the surviving pod
        set exactly as a never-crashed one would.

        - adopted: already-bound pods the cache doesn't know (a prior
          leader's binds that landed while this instance was down);
        - cleared: stale nominatedNodeName on unbound pods with no
          preemption in flight HERE — the old leader died mid-
          preemption and nobody is freeing that capacity anymore;
        - requeued: unbound, undeleted, unassumed pods entered into the
          queue exactly once (deduped by pod key + generation against
          both the live queue and the demotion drain's requeues).
        """
        with self._reconcile_lock:
            counts = {"adopted": 0, "requeued": 0, "cleared": 0}
            try:
                pods, _ = self.client.pods.list()
            except APIError:
                traceback.print_exc()
                return counts
            queued = {v1.pod_key(p) for p in self.queue.pending_pods()}
            # the store lists by key (lexicographic); requeue must
            # replay CREATION order or the restarted queue pops pod-2
            # after pod-19 and the batch placements diverge from the
            # never-crashed run's (restart parity is bit-identical
            # assignments, not just all-bound)
            pods.sort(key=lambda p: (
                p.metadata.creation_timestamp or 0.0,
                int(p.metadata.resource_version or 0),
            ))
            for pod in pods:
                key = v1.pod_key(pod)
                if pod.spec.node_name:
                    if not self.cache.has_pod(key):
                        self.cache.add_pod(pod)
                        counts["adopted"] += 1
                    continue
                if pod.metadata.deletion_timestamp is not None:
                    continue
                if (pod.status.nominated_node_name
                        and not self._preemption_in_flight(pod)):
                    self._reconcile_clear_nomination(pod)
                    counts["cleared"] += 1
                gen = pod.metadata.generation or 0
                if key in queued or self._drain_requeued.get(key) == gen:
                    continue  # already pending exactly once
                if self.cache.is_assumed_pod(pod):
                    continue  # an in-flight bind of ours owns it
                self.queue.add(pod)
                counts["requeued"] += 1
            gang = self._gang_plugin()
            if gang is not None:
                try:
                    self._reconcile_gangs(gang, pods)
                except Exception:  # noqa: BLE001 — gang healing must
                    # not break the base reconcile
                    traceback.print_exc()
            self._drain_requeued.clear()
            for outcome, n in counts.items():
                if n:
                    metrics.restart_reconcile.inc(n, outcome=outcome)
            logger.info(
                "%s reconciled from store: %d adopted, %d requeued, "
                "%d nominations cleared", self.profile_name,
                counts["adopted"], counts["requeued"], counts["cleared"],
            )
            return counts

    def _reconcile_gangs(self, gang, pods: List[v1.Pod]) -> None:
        """Promotion-time gang healing (the gang extension of the
        cold-restart reconcile): (1) bound gang members from a prior
        leader SEED the reserved-member index, so their re-driven
        siblings rejoin the partially-bound gang instead of waiting on
        a full fresh wave that can never assemble; (2) orphaned gang
        reservations — waves still parked HERE (a re-promoted leader)
        whose members are gone from the store, bound by another
        instance, or older than KTPU_GANG_PERMIT_TIMEOUT — roll back
        whole (reason=reconcile), releasing the capacity a dead
        transaction was camping on. A deposed leader's own late
        member-binds need no handling here: they bounce off the lease
        fence server-side (FenceExpired -> forget, never requeue)."""
        for pod in pods:
            if pod.spec.node_name and pod.metadata.deletion_timestamp is None:
                gang.seed_reserved(pod)
        by_key = {v1.pod_key(p): p for p in pods}
        timeout = knobs.get_float("KTPU_GANG_PERMIT_TIMEOUT") or 0.0
        now = _time.monotonic()
        for gate in gang.waiting_gangs():
            reason = None
            if gate.age(now) > timeout:
                reason = (
                    f"gang {gate.group!r}: wave older than "
                    f"KTPU_GANG_PERMIT_TIMEOUT ({timeout:.0f}s) at "
                    f"promotion"
                )
            else:
                for k in gate.members():
                    p = by_key.get(k)
                    if p is None or p.metadata.deletion_timestamp is not None \
                            or p.spec.node_name:
                        reason = (
                            f"gang {gate.group!r}: waiting member {k} is "
                            f"no longer pending in the store"
                        )
                        break
            if reason is not None:
                gang.reject_gang(
                    gate.namespace, gate.group, "reconcile", message=reason
                )

    def _reconcile_clear_nomination(self, pod: v1.Pod) -> None:
        """A relisted unbound pod carries a nomination from a preemption
        this instance never started: the victims are gone or will never
        be deleted — either way the nomination is a lie. Clear it in
        the nominator, the API object, and the local copy headed for
        the queue (synchronous, unlike _clear_nomination's binder-pool
        path: reconcile must finish before the pop gate opens)."""
        self.nominator.delete_nominated_pod_if_exists(pod)
        try:
            fresh = self.client.pods.get(
                pod.metadata.name, pod.metadata.namespace
            )
            fresh.status.nominated_node_name = ""
            self.client.pods.update_status(fresh, fence=self._fence)
        except APIError:
            pass
        pod.status.nominated_node_name = ""

    # -- run loop ----------------------------------------------------------

    def install_fault_injector(self, inj) -> None:
        """Wire a FaultInjector seam (testing/faults.py) into the
        pipeline workers and the TPU backend — the ChaosMonkey
        wedge-device / crash-scheduler disruptions arm faults on it."""
        self.faults = inj
        if self.tpu is not None:
            self.tpu.faults = inj

    def _check_kill(self, worker: str) -> None:
        inj = self.faults
        if inj is not None and inj.take_kill(worker):
            raise WorkerKilled(worker)

    def _supervised(self, name: str, fn, recover=None) -> None:
        """Panic isolation for a pipeline worker thread (the Supervisor's
        policy — controllers/manager.py — at thread granularity): a crash
        is counted, recovered (in-flight work drained back to the queue),
        and the loop restarts with fresh state under capped exponential
        backoff + full jitter. A clean return (stop) ends supervision."""
        backoff = 0.02
        while not self._stop.is_set():
            try:
                fn()
                return
            except BaseException:  # noqa: BLE001 — isolation is the point
                traceback.print_exc()
                metrics.worker_restarts.inc(worker=name)
                tracing.event("worker-crash", "fault", worker=name)
                metrics.dump_seam(f"worker-restart-{name}", worker=name)
                self._health_event(
                    "Warning", "WorkerRestart",
                    f"supervised pipeline worker '{name}' crashed and "
                    f"was restarted (in-flight work drained back to the "
                    f"queue)",
                )
                if recover is not None:
                    try:
                        recover()
                    except Exception:  # noqa: BLE001 — recovery best-effort
                        traceback.print_exc()
                delay = min(backoff, 2.0) * (1 + 0.5 * self.rng.random())
                backoff *= 2
                if self._stop.wait(delay):
                    return

    def start(self) -> None:
        if self._thread is None:
            if self.elector is not None:
                # standby until elected: the loop runs but the pop gate
                # stays closed — _on_started_leading opens it
                self.pause()
                self.elector.start()
            self._thread = threading.Thread(
                target=self._supervised, args=("scheduler", self._run),
                name="scheduler-loop", daemon=True,
            )
            self._thread.start()

    def pause(self) -> None:
        """Suspend popping (the queue keeps accumulating). Lets a caller
        stage a large backlog so the batch path drains it at full
        max_batch width instead of racing the producer with small ragged
        batches (each distinct batch bucket is an XLA compile)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def stop(self) -> None:
        self.shutdown()

    def shutdown(self, timeout: float = 30.0) -> bool:
        """Deterministic teardown: stop the loop, land (or abandon) every
        in-flight batch, JOIN every worker thread, shut the binder pool.
        Idempotent. Returns True when every thread joined in time — the
        test suites' no-leaked-threads contract (daemon-flag teardown is
        the fallback, not the plan)."""
        ok = True
        if self.elector is not None:
            # vacate the lease FIRST so a standby takes over on its next
            # retry instead of waiting out expiry; on_stopped_leading
            # (pause + FIFO drain) is harmless ahead of full teardown
            try:
                self.elector.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                traceback.print_exc()
        self._stop.set()
        self._permit_wake.set()  # let the permit drainer exit
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            ok &= not self._thread.is_alive()
        # the drainer submits released waves to the binder pool — join it
        # BEFORE the pool shuts down, or a mid-wave submit would raise and
        # strand the wave's assumed pods
        if self._permit_thread is not None:
            self._permit_thread.join(timeout=timeout)
            ok &= not self._permit_thread.is_alive()
        if self.backend == "tpu":
            try:
                # loop is dead; the completion worker lands the tail
                # batches (it drains the queue before honoring _stop),
                # and their binds must enter the pool before it shuts.
                # Every device wait inside is watchdog-bounded, so this
                # drain converges (or PipelineStalled demotes + escapes).
                self._drain_pipeline(timeout=timeout)
            except Exception:  # noqa: BLE001 — teardown best-effort
                traceback.print_exc()
        if self._completion_thread is not None:
            with self._completion_cv:
                self._completion_cv.notify_all()
            self._completion_thread.join(timeout=timeout)
            ok &= not self._completion_thread.is_alive()
        if self._completions and (
            self._completion_thread is None
            or not self._completion_thread.is_alive()
        ):
            # worker gone with batches still queued (stall/crash at
            # teardown): flush the FIFO deterministically — harvested
            # batches bind, abandoned ones requeue their pods
            self._recover_completions()
        if self.tpu is not None:
            self.tpu.close()  # stop the ladder probe thread
        self._binders.shutdown(wait=True)
        if not self.recorder.flush(timeout=5.0):  # events are async
            logger.warning(
                "event queue did not drain within 5s at scheduler stop "
                "(%d events dropped during the run)",
                self.recorder.dropped_events,
            )
        return ok

    def _run(self) -> None:
        import time

        last_cleanup = time.monotonic()
        while not self._stop.is_set():
            # kill seam OUTSIDE the isolation try: a WorkerKilled must
            # reach the supervision wrapper, not the keep-alive except.
            # It fires at the loop boundary — nothing popped, nothing in
            # flight — so the restart needs no recovery pass.
            self._check_kill("scheduler")
            try:
                if self._paused.is_set():
                    if self.backend == "tpu":
                        self._drain_pipeline()
                    time.sleep(0.02)
                    continue
                self.schedule_one(timeout=0.2)
                now = time.monotonic()
                if now - last_cleanup >= 1.0:  # cache.go:125 1s cleanup ticker
                    last_cleanup = now
                    self.cache.cleanup_expired_assumed_pods()
                    active, backoff, unsched = self.queue.depths()
                    metrics.pending_pods.set(active, queue="active")
                    metrics.pending_pods.set(backoff, queue="backoff")
                    metrics.pending_pods.set(
                        unsched, queue="unschedulable")
            except Exception:  # keep the loop alive; scheduleOne logs errors
                traceback.print_exc()

    # -- scheduling cycle --------------------------------------------------

    def schedule_one(self, timeout: Optional[float] = None) -> bool:
        """One scheduling cycle; returns False on queue timeout. In TPU
        mode, drains up to max_batch pods and schedules them in batched
        dispatches with sequential assume semantics."""
        info = self.queue.pop(timeout=timeout)
        if info is None:
            if self.backend == "tpu":
                self._drain_pipeline()  # idle: land the tail batches
            return False
        if self._paused.is_set():
            # pause() landed while this thread was already blocked in
            # pop: hand the pod back instead of scheduling past the
            # pause — a demoted leader must not pop work its successor
            # now owns
            self.queue.add(info.pod)
            return False
        info.pop_timestamp = _time.monotonic()
        with self._inflight_lock:
            self._inflight += 1
        t0 = _time.perf_counter()
        n_scheduled = 1
        try:
            if self.backend == "tpu":
                infos = [info]
                with tracing.span("pop", "pop") as sp:
                    while len(infos) < self.max_batch:
                        nxt = self.queue.pop(timeout=0)
                        if nxt is None:
                            break
                        nxt.pop_timestamp = info.pop_timestamp
                        infos.append(nxt)
                    sp.set(n=len(infos))
                n_scheduled = len(infos)
                metrics.batch_size.observe(n_scheduled)
                self._schedule_batch_tpu(infos)
            else:
                self._schedule_one_oracle(info)
        finally:
            dt = _time.perf_counter() - t0
            for _ in range(n_scheduled):
                metrics.scheduling_algorithm_duration.observe(dt / n_scheduled)
            with self._inflight_lock:
                self._inflight -= 1
        return True

    def _skip(self, pod: v1.Pod) -> bool:
        """scheduler.go:620 skipPodSchedule: deleted or already assumed.
        A pod ABSENT from the informer cache is deleted too: its delete
        event raced the pod's in-flight window (popped at delete time,
        so queue.delete was a no-op) and a failed bind re-queued it
        afterwards — scheduling it again would 404-bind and re-queue
        forever, a ghost entry cycling the queue (the reference's
        MakeDefaultErrorFunc drops exactly this case; surfaced by the
        soak's queue-returns-to-baseline invariant under delete churn)."""
        current = self.informers.pods().get(meta_namespace_key(pod))
        if current is None:
            return True
        if current.metadata.deletion_timestamp is not None:
            return True
        return self.cache.is_assumed_pod(pod)

    def _needs_oracle(self, pod: v1.Pod) -> bool:
        """Pods whose constraints live outside the TPU kernel take the
        oracle path. PVC-bearing pods ride the kernel when their volume
        constraints are statically resolvable (all PVCs bound, claims
        unshared — volume_device.py); unbound PVCs keep the oracle
        (VolumeBinding's provisioning decisions are host-side)."""
        if not any(
            (vol.source or {}).get("persistentVolumeClaim")
            for vol in pod.spec.volumes or []
        ):
            return False
        return self.tpu is None or not self.tpu.volume_kernel_safe(pod)

    def _schedule_batch_tpu(self, infos: List) -> None:
        cycle = self.queue.scheduling_cycle
        todo = [i for i in infos if not self._skip(i.pod)]
        if todo and self.tpu.ladder.rung() <= RUNG_ORACLE:
            # degradation ladder fully demoted: no device dispatch at
            # all — every pod rides the oracle until the background
            # probe re-promotes the backend (degradation.py)
            if not self._drain_or_requeue(todo):
                return
            for info in todo:
                self._schedule_one_oracle(info)
            return
        if self.framework is not None:
            # one partition pass: _needs_oracle runs a resolver pass for
            # PVC pods, and pending pods SHARING a claim within this
            # batch must not both ride the kernel (attach counting is
            # unique-handle; the refcount gate only sees assumed pods)
            from .volume_device import pod_pvc_names

            oracle_infos, kernel_infos = [], []
            batch_claims: set = set()
            for i in todo:
                claims = {
                    (i.pod.metadata.namespace, c)
                    for c in pod_pvc_names(i.pod)
                } if i.pod.spec.volumes else set()
                if self._needs_oracle(i.pod) or (claims & batch_claims):
                    oracle_infos.append(i)
                else:
                    kernel_infos.append(i)
                    batch_claims |= claims
            todo = kernel_infos
            if oracle_infos:
                # the oracle schedules against the cache snapshot: every
                # pipelined batch's assumes must land first
                if not self._drain_or_requeue(oracle_infos + todo):
                    return
                for info in oracle_infos:
                    self._schedule_one_oracle(info)
            # nominated-node short-circuit (generic_scheduler.go:235
            # evaluateNominatedNode): a preemptor whose victims were
            # evicted re-arrives with a nominated node — feasibility is
            # checked on THAT node only and the pod binds there without a
            # kernel dispatch (and without racing other waves' pods for
            # the freed capacity)
            nominated = [
                i for i in todo
                if (i.nominated_node or i.pod.status.nominated_node_name)
            ]
            if nominated:
                # feasibility runs on the cache snapshot — same drain
                # requirement as the oracle path
                if not self._drain_or_requeue(todo):
                    return
                placed = self._place_nominated(nominated)
                if placed:
                    todo = [i for i in todo if id(i) not in placed]
        if not todo:
            return
        # pipelined dispatch: enqueue this batch's scan (async on the
        # live session — it chains on the previous batch's carry), hand
        # the completion (harvest -> assume -> bind -> failures) to the
        # completion worker, and return to pop + encode the next batch.
        # The device double-buffers (tpu.max_pending); the worker
        # preserves dispatch order. Depth 0 completes inline — the
        # sequential reference path the parity gate compares against.
        # latch the basis BEFORE dispatch: a foreign event landing
        # between the latch and the session's delta fold is in the carry
        # but reads as "advanced" at completion — a conservative audit
        # skip. Latching after would invert that into false drift.
        basis_gen = (self.cache.foreign_mutations(),
                     self._dropped_decisions)
        try:
            handle = self.tpu.dispatch_many([i.pod for i in todo])
        except Exception:  # noqa: BLE001 — the backend recovers its own
            # faults internally; an escape here is defensive: the pods
            # were never handed to the pipeline, so requeue exactly once
            traceback.print_exc()
            for info in todo:
                self.queue.add(info.pod)
            return
        handle.basis_mutations = basis_gen
        if self.pipeline_depth <= 0:
            self._complete_batch(todo, handle, cycle, _time.monotonic())
            return
        with self._completion_cv:
            if self._completion_thread is None:
                self._completion_thread = threading.Thread(
                    target=self._supervised,
                    args=("completion", self._completion_loop,
                          self._recover_completions),
                    name="batch-completions", daemon=True,
                )
                self._completion_thread.start()
            # the enqueue timestamp rides the FIFO item: queue-to-
            # completion age is the overload monitor's primary signal
            self._completions.append((todo, handle, cycle,
                                      _time.monotonic()))
            self._completion_cv.notify_all()
            # backpressure: the assume/bind lag stays bounded by the
            # pipeline depth (an unbounded queue would let the cache
            # trail arbitrarily far behind the device carry)
            while (
                len(self._completions) > self.pipeline_depth
                and not self._stop.is_set()
            ):
                self._completion_cv.wait(0.2)

    def _completion_loop(self) -> None:
        """The async bind queue: completes dispatched batches strictly in
        dispatch order, off the scheduling thread's critical path.
        assume-before-bind: a batch's decisions enter the scheduler cache
        (the device carry already holds them) before its bind POSTs go
        out; a failed bind forgets the assumed pod and requeues it
        unassigned — the reference's assume -> async bind ->
        confirm/forget contract (scheduler.go:359,:540)."""
        while True:
            with self._completion_cv:
                while not self._completions and not self._stop.is_set():
                    self._completion_cv.wait(0.2)
                if not self._completions:
                    return  # stopped and fully drained
                item = self._completions[0]
            # kill seam OUTSIDE the per-batch isolation: the worker dies
            # at a batch boundary (nothing harvested, nothing assumed)
            # and the supervision wrapper recovers + restarts it
            self._check_kill("completion")
            try:
                self._complete_batch(*item)
            except Exception:  # the worker must outlive batch bugs:
                # its death would strand every queued completion
                traceback.print_exc()
            finally:
                # remove AFTER completing: an empty deque means every
                # dispatched batch has fully landed (_drain_pipeline).
                # Guarded: a teardown-time _recover_completions flush may
                # have raced this item out already.
                with self._completion_cv:
                    if self._completions and self._completions[0] is item:
                        self._completions.popleft()
                    self._completion_cv.notify_all()

    def _recover_completions(self) -> None:
        """Completion-worker crash recovery: restore the invariant
        "every popped pod is either bound exactly once or back in the
        queue" before the fresh worker starts. Not-yet-harvested device
        batches are abandoned at the backend (their results resolve to
        RETRY_NODE; nothing of theirs ever touched the host encoding),
        then every queued completion is run to its terminal state:
        already-decided batches assume + bind exactly once, abandoned
        ones send their pods back to the scheduling queue."""
        if self.tpu is not None:
            self.tpu.abandon_pending()
        while True:
            with self._completion_cv:
                if not self._completions:
                    self._completion_cv.notify_all()
                    return
                item = self._completions[0]
            try:
                self._complete_batch(*item)
            except Exception:  # noqa: BLE001 — keep flushing the FIFO
                traceback.print_exc()
            finally:
                with self._completion_cv:
                    if self._completions and self._completions[0] is item:
                        self._completions.popleft()
                    self._completion_cv.notify_all()

    def _drain_pipeline(self, timeout: Optional[float] = None) -> bool:
        """Block until every dispatched batch has fully completed
        (assumed + binds submitted + failures handled). Runs on idle,
        pause, and stop, and before any path that reads the scheduler
        cache as ground truth (oracle scheduling, nominated placement).

        The wait is BOUNDED: every device wait inside the completion
        worker is already watchdog-bounded (TPUBackend.harvest), so a
        wedged device resolves through the fault/retry path well inside
        the drain budget. Exceeding it anyway means the pipeline is
        stalled beyond what retries can fix — demote the ladder and
        raise PipelineStalled; callers requeue their pods. Blocking the
        whole scheduler forever is the one forbidden outcome."""
        if self.pipeline_depth <= 0:
            return True
        if timeout is None:
            timeout = self.drain_timeout
        if timeout is None:
            watchdog = self.tpu.watchdog_timeout if self.tpu is not None \
                else 30.0
            # budget: every queued batch may burn a full watchdog +
            # retry storm before resolving
            timeout = max(30.0, 3.0 * watchdog)
        deadline = _time.monotonic() + timeout
        while True:
            with self._completion_cv:
                if not self._completions:
                    return True
                # orphaned-batch seam: the dispatching thread can append
                # a batch AFTER the worker saw (empty deque, _stop set)
                # and exited — the enqueue path only spawns a worker
                # when the thread slot is None, so nothing would ever
                # land it. The worker is dead, so the FIFO has no other
                # owner: land it from here.
                worker = self._completion_thread
                orphan = (
                    self._stop.is_set()
                    and (worker is None or not worker.is_alive())
                )
                item = self._completions[0] if orphan else None
                if item is None:
                    wait = min(0.2, deadline - _time.monotonic())
                    if wait <= 0:
                        stuck = len(self._completions)
                        break
                    self._completion_cv.wait(wait)
                    continue
            try:
                self._complete_batch(*item)
            except Exception:  # noqa: BLE001 — keep flushing the FIFO
                traceback.print_exc()
            finally:
                with self._completion_cv:
                    if self._completions and self._completions[0] is item:
                        self._completions.popleft()
                    self._completion_cv.notify_all()
        tracing.event("pipeline-stalled", "fault", stuck=stuck,
                      timeout=timeout)
        metrics.dump_seam("pipeline-stalled", stuck=stuck)
        demoted = self.tpu is not None and self.tpu.ladder.demote()
        self._health_event(
            "Warning", "PipelineStalled",
            "dispatched batches failed to land within the drain budget"
            + ("; backend demoted" if demoted else ""),
        )
        if demoted:
            logger.warning(
                "pipeline stalled: %d batches undrained after %.1fs — "
                "backend demoted to %s", stuck, timeout,
                self.tpu.ladder.mode(),
            )
            self.tpu._ensure_probe_thread()
        raise PipelineStalled(
            f"{stuck} dispatched batches failed to land within {timeout}s"
        )

    def _drain_or_requeue(self, infos: List) -> bool:
        """_drain_pipeline for the mid-cycle callers: on a stall the
        given (popped, not yet dispatched) infos go back to the queue
        exactly once and the cycle aborts."""
        try:
            self._drain_pipeline()
            return True
        except PipelineStalled:
            traceback.print_exc()
            for info in infos:
                self.queue.add(info.pod)
            return False

    def _complete_batch(self, todo: List, handle, cycle: int,
                        enq_ts: Optional[float] = None) -> None:
        # overload injection seam (ChaosMonkey kind="overload"): a
        # transient completion-worker stall, the synthetic form of the
        # host falling behind. Before harvest so the whole batch ages.
        if self.faults is not None:
            self.faults.on_completion()
        t0 = _time.monotonic()
        try:
            self._complete_batch_inner(todo, handle, cycle)
        finally:
            now = _time.monotonic()
            self._completion_durations.append(now - t0)
            age = (now - enq_ts) if enq_ts is not None else 0.0
            depth = len(self._completions)
            metrics.completion_fifo_depth.set(depth)
            metrics.completion_fifo_age.set(age)
            metrics.attempt_duration.observe(now - t0, stage="complete")
            metrics.attempt_duration.observe(age, stage="fifo-wait")
            if self.overload is not None:
                # completion-stage p99 over the recent window — the
                # same seam the PR-8 recorder spans as stage=complete
                durs = sorted(self._completion_durations)
                p99 = durs[int(0.99 * (len(durs) - 1))] if durs else 0.0
                active, backoff, unsched = self.queue.depths()
                self.overload.observe(
                    fifo_depth=depth,
                    fifo_age=age,
                    queue_depth=active + backoff,
                    stage_p99=p99,
                )

    def _complete_batch_inner(self, todo: List, handle,
                              cycle: int) -> None:
        results = self.tpu.harvest(handle)
        by_key = {v1.pod_key(p): node for p, node in results}
        from .tpu_backend import RETRY_NODE

        if self.tpu.shadow_sample > 0:
            # shadow parity sentinel: audit BEFORE this batch's assumes
            # land — the cache still holds the decision-time state for
            # pod 0 (completion is strictly FIFO, so every earlier
            # batch's assumes are already in)
            try:
                self._shadow_audit(results, handle)
            except Exception:  # noqa: BLE001 — the auditor observes the
                # pipeline, it must never break it
                traceback.print_exc()

        bound: List[Tuple] = []  # (info, node)
        failed: List = []
        gang = self._gang_plugin()
        for info in todo:
            node = by_key.get(v1.pod_key(info.pod))
            if node == RETRY_NODE:
                # volume gate/encode race: not unschedulable — re-gate
                # on the next pop instead of parking for the flusher.
                # Counts as a dropped decision for the sentinel's basis
                # gate: a recovery-abandoned batch resolves RETRY while
                # overlapping flights chained on its carry.
                self._dropped_decisions += 1
                if gang is not None:
                    # a gang member's dispatch abandoned (device fault /
                    # recovery): re-drive the ENTIRE gang, never a
                    # prefix — roll back its waiting wave so parked
                    # siblings release their reservations and requeue
                    # alongside this member
                    gang.reject_gang_of(
                        info.pod, "device-fault",
                        message=f"gang member "
                                f"{info.pod.metadata.name!r} abandoned "
                                f"mid-dispatch (device fault recovery)",
                    )
                self.queue.add(info.pod)
            elif node is None:
                failed.append(info)
            else:
                bound.append((info, node))
        if bound:
            self._assume_and_bind_batch(bound)
        if failed:
            self._handle_failure_wave(failed, cycle)

    def _shadow_audit(self, results: List[Tuple], handle) -> None:
        """Shadow parity sentinel (KTPU_SHADOW_SAMPLE): replay sampled
        decided pods through the oracle filter/score chain against the
        decision-time cache state and count per-plugin drift.

        Runs on the completion worker BEFORE this batch's assumes land,
        so the cache holds exactly what the device carry held when the
        batch dispatched. Informer events that raced the flight would
        break that equality — the stale-basis gate (the handle's
        dispatch-latched foreign-mutation generation vs the cache's now)
        voids those audits (scheduler_shadow_skips_total{reason=
        "stale-basis"}) instead of reporting drift the device never
        caused; under completion lag (overload stalls, crash recovery)
        coverage drops but the zero-drift invariant stays meaningful.
        Pod i of the batch decided
        against the carry plus pods 0..i-1 of its own batch, so each
        sampled pod gets a private Snapshot with those prefix decisions
        cloned in — the shared cache NodeInfos are never touched.

        Drift = the device's node is infeasible per the oracle, or scores
        strictly below the oracle's max total; with an explain payload on
        the handle, ANY per-plugin mask/score mismatch counts even when
        the decision agrees (attribution_diff — the early-warning case).
        Each drift bumps scheduler_parity_drift_total{plugin}, dumps the
        flight-recorder ring through the shadow-drift seam, and freezes a
        replayable repro bundle."""
        from . import explain as explain_mod
        from .tpu_backend import RETRY_NODE

        rate = self.tpu.shadow_sample
        sampled = [
            i for i, (_, node) in enumerate(results)
            if node is not None and node != RETRY_NODE
            and self.rng.random() < rate
        ]
        if not sampled:
            return
        # decision-time cluster state, once per audited batch. Columnar
        # mode: an O(changed) clone view off the cache's generation-keyed
        # audit cache — no per-audit NodeInfo reconstruction from raw
        # objects, no Quantity re-parse (the reason production shadow
        # sample rates were capped). Object mode (KTPU_COLUMNAR_CACHE=0):
        # the raw dump + Snapshot.from_objects rebuild. Neither touches
        # update_snapshot's generation bookkeeping — a throwaway audit
        # must not starve the scheduling thread's incremental refreshes.
        base_infos = self.cache.audit_view()
        base_nodes = base_pods = None
        if base_infos is None:
            base_nodes, base_pods = self.cache.dump()
        basis = getattr(handle, "basis_mutations", None)
        if basis is not None and (self.cache.foreign_mutations(),
                                  self._dropped_decisions) != basis:
            # stale-basis gate, checked AFTER the state read so nothing
            # can land between the check and the read: either the cluster
            # moved under this flight (foreign event, expiry, forget) or
            # an overlapping in-flight batch dropped a decided placement
            # the chained carry had — in both cases the read is not the
            # decision-time state. Void the audit, keep the drift
            # counter honest.
            metrics.shadow_skips.inc(len(sampled), reason="stale-basis")
            return
        node_names = handle.node_names or []
        if base_infos is not None:
            # prefix decisions land incrementally across ascending
            # samples: each touched node is copy-on-write cloned once
            # (the audit_view clones are shared and must stay pristine),
            # then pod i's snapshot is just the current overlay state
            by_name = {
                ni.node.metadata.name: ni for ni in base_infos
            }
            overlaid: set = set()
            applied = 0
        for i in sampled:
            pod, node = results[i]
            metrics.shadow_samples.inc()
            if base_infos is not None:
                for p, n in results[applied:i]:
                    if n is None or n == RETRY_NODE:
                        continue
                    clone = copy.copy(p)
                    clone.spec = copy.copy(p.spec)
                    clone.spec.node_name = n
                    tgt = by_name.get(n)
                    if tgt is None:
                        continue  # from_objects also drops unknown nodes
                    if n not in overlaid:
                        tgt = tgt.clone()
                        by_name[n] = tgt
                        overlaid.add(n)
                    tgt.add_pod(clone)
                applied = i
                shadow_snap = Snapshot(list(by_name.values()))
            else:
                prefix = []
                for p, n in results[:i]:
                    if n is None or n == RETRY_NODE:
                        continue
                    clone = serde.from_dict(v1.Pod, serde.to_dict(p))
                    clone.spec.node_name = n
                    prefix.append(clone)
                shadow_pods = base_pods + prefix
                shadow_snap = Snapshot.from_objects(shadow_pods, base_nodes)
            oracle_bd = explain_mod.oracle_breakdown(shadow_snap, pod)
            device_bd = None
            if handle.explain is not None and i < len(handle.explain) \
                    and node_names:
                device_bd = explain_mod.payload_breakdown(
                    handle.explain[i], node_names)
            if explain_mod.decision_drifts(oracle_bd, node):
                plugins = explain_mod.drift_plugins(
                    oracle_bd, device_bd, node)
            elif device_bd is not None:
                plugins = explain_mod.attribution_diff(oracle_bd, device_bd)
            else:
                plugins = []
            if not plugins:
                continue
            key = v1.pod_key(pod)
            for plugin in plugins:
                metrics.parity_drift.inc(plugin=plugin)
            metrics.dump_seam(
                "shadow-drift", pod=key, node=node,
                plugins=",".join(plugins),
            )
            if base_infos is not None:
                # bundle inputs only materialize on drift (the rare
                # case) — never on the clean-audit hot path
                bundle_nodes = [ni.node for ni in by_name.values()]
                bundle_pods = [
                    pi.pod for ni in by_name.values() for pi in ni.pods
                ]
            else:
                bundle_nodes, bundle_pods = base_nodes, shadow_pods
            try:
                bundle = explain_mod.write_bundle(
                    pod, bundle_nodes, bundle_pods, node, plugins,
                    oracle_bd, device_bd, weights=self.tpu.weights,
                )
            except Exception:  # noqa: BLE001 — an unwritable bundle dir
                # must not swallow the drift signal itself
                traceback.print_exc()
                bundle = "<bundle write failed>"
            logger.warning(
                "shadow parity drift: pod %s on %s disagrees with the "
                "oracle replay (plugins: %s); repro bundle: %s",
                key, node, ",".join(plugins), bundle,
            )
            self._health_event(
                "Warning", "ShadowParityDrift",
                f"device decision for {key} diverged from the oracle "
                f"replay ({','.join(plugins)})",
            )

    def _handle_failure_wave(self, failed: List, cycle: int) -> None:
        """Failure handling for a whole batch at once. Preemption can
        only evict strictly-lower-priority victims, so pods at or below
        the cluster's priority floor park immediately (no dry-run can
        help). The rest split between the batched fast planner
        (preemption.py — one numpy pass over every node for the whole
        wave) and the oracle path (a batched kernel re-evaluation
        recovers per-node statuses, then DefaultPreemption runs per
        pod). The per-pod schedule() the redispatch replaces was a
        session teardown + full kernel launch each (r2's preemption
        crawl); the fast planner removes even the redispatch."""
        has_post_filter = bool(
            self.framework is not None and self.framework.post_filter_plugins
        )
        min_prio = self.cache.min_pod_priority() if has_post_filter else 0
        redispatch: List = []
        preemptable: List = []
        for info in failed:
            if self._preemption_in_flight(info.pod):
                # victims from a previous plan are still dying — park and
                # wait for their delete echoes (the oracle's terminating-
                # victim eligibility gate); planning a SECOND victim set
                # now would double-evict. Re-check after parking: the
                # last echo may have landed in between, with activate()
                # a no-op because the pod wasn't parked yet
                self._record_failure(info, cycle, {})
                if not self._preemption_in_flight(info.pod):
                    self.queue.activate(info.pod)
            elif not has_post_filter or (info.pod.spec.priority or 0) <= min_prio:
                self._record_failure(info, cycle, {})
            else:
                preemptable.append(info)
        if preemptable:
            self.snapshot = self.cache.update_snapshot(self.snapshot)
            pdbs = self._list_pdbs()
            # a nominated pod's required anti-affinity only matters to a
            # preemptor its terms MATCH (the nominated pod is ADDed in
            # RunFilterPluginsWithNominatedPods) — collect the terms once,
            # gate per pod
            from .framework.types import PodInfo as _PI

            nominated_anti_terms = [
                t
                for p in self.nominator.all_nominated_pods()
                if _has_required_anti_affinity(p)
                for t in _PI(p).required_anti_affinity_terms
            ]
            from .preemption_device import (
                ORACLE_FALLBACK,
                DevicePreemptionPlanner,
                device_eligible,
            )

            # ONE cluster pass over the pods with required anti-affinity
            # for the whole wave (satellite of the planner-ladder PR):
            # fast_eligible used to re-walk them per failed pod
            anti_terms = fast_preemption.WaveAntiTerms(self.snapshot)
            use_device = self.tpu is not None and self.tpu.whatif_enabled()
            fast: List = []
            eligibility: Dict[str, Tuple[bool, bool]] = {}
            for info in preemptable:
                pod = info.pod
                nominated_hit = any(
                    t.matches(pod) for t in nominated_anti_terms
                )
                fast_ok = not nominated_hit and fast_preemption.fast_eligible(
                    pod, self.snapshot, pdbs, self.extenders,
                    anti_terms=anti_terms,
                )
                dev_ok = (
                    use_device
                    and not nominated_hit
                    and device_eligible(pod, self.extenders, anti_terms)
                )
                if fast_ok or dev_ok:
                    eligibility[v1.pod_key(pod)] = (dev_ok, fast_ok)
                    fast.append(info)
                else:
                    redispatch.append(info)
            if fast:
                # victims claimed by in-flight waves whose delete echoes
                # have not landed in the cache yet must not be claimed
                # again (their capacity is already spoken for by the
                # claiming preemptor's nominator entry)
                with self._preempt_lock:
                    claimed = set(self._victim_waiters)
                if use_device:
                    # three-rung planner ladder: device what-if scan ->
                    # numpy fast planner -> oracle redispatch, one shared
                    # set of wave books so rungs never double-claim
                    planner = DevicePreemptionPlanner(
                        self.snapshot, self.nominator, self.tpu,
                        args=self._preemption_args(),
                        claimed_victims=claimed,
                        pdbs=pdbs,
                        eligibility=eligibility,
                    )
                else:
                    planner = fast_preemption.FastPreemptionPlanner(
                        self.snapshot, self.nominator,
                        args=self._preemption_args(),
                        claimed_victims=claimed,
                        pdbs=pdbs,
                    )
                with tracing.span("preemption-plan", "planner",
                                  n=len(fast)) as psp:
                    cands = planner.plan([i.pod for i in fast])
                    paths = getattr(planner, "planner_paths", None)
                    if paths and tracing.enabled():
                        mix: Dict[str, int] = {}
                        for p in paths:
                            mix[p] = mix.get(p, 0) + 1
                        psp.set(**mix)
                        if tracing.RECORDER.pod_level():
                            for info, path in zip(fast, paths):
                                tracing.provenance(
                                    v1.pod_key(info.pod), planner=path)
                preempted: List[Tuple] = []
                for info, cand, fits in zip(fast, cands, planner.fits_now):
                    if cand is ORACLE_FALLBACK:
                        # mid-wave rung exhaustion (device fault on a pod
                        # the numpy envelope rejects): the oracle rung
                        redispatch.append(info)
                    elif fits:
                        # cluster state moved since the batch dispatched:
                        # the pod fits without preemption — let the
                        # kernel re-evaluate (scores + sequential assume)
                        redispatch.append(info)
                    elif cand is None:
                        # preemption cannot help anymore: a stale
                        # nomination would keep short-circuiting the
                        # batch path for nothing — clear it and take
                        # normal backoff
                        if info.nominated_node or \
                                info.pod.status.nominated_node_name:
                            self._clear_nomination(info)
                        self._record_failure(info, cycle, {})
                    else:
                        preempted.append((info, cand))
                if preempted:
                    self._apply_preemptions(preempted, cycle)
        if redispatch:
            # ONE batched re-evaluation recovers per-node failure
            # statuses for every failed pod (the preemption dry-run's
            # input). A pod that now FITS (state moved since its batch)
            # binds; the batched evaluation is against one state, so only
            # the first fit binds directly — later fits re-dispatch
            # singly to keep sequential-assume semantics (rare: failure
            # waves mostly stay failed).
            from .tpu_backend import RETRY_NODE

            bound_once = False
            for info, (node, statuses) in zip(
                redispatch, self.tpu.reevaluate([i.pod for i in redispatch])
            ):
                if node == RETRY_NODE:
                    self.queue.add(info.pod)
                elif node is None:
                    self._record_failure(info, cycle, statuses)
                elif not bound_once:
                    bound_once = True
                    self._assume_and_bind(info.pod, node, info=info)
                else:
                    try:
                        r = self.tpu.schedule(info.pod)
                        self._assume_and_bind(
                            info.pod, r.suggested_host, info=info
                        )
                    except FitError as fe:
                        self._record_failure(
                            info, cycle, fe.filtered_nodes_statuses
                        )
                    except DeviceFault:
                        # retries exhausted inside schedule(): back to
                        # the queue exactly once; the ladder (already
                        # fault-counted) decides the next attempt's path
                        self.queue.add(info.pod)

    def _preemption_args(self) -> dict:
        """The DefaultPreemption plugin's candidate-count args, so the
        fast planner scans exactly as far as the oracle would."""
        if self.framework is not None:
            for pl in self.framework.post_filter_plugins:
                if getattr(pl, "name", "") == "DefaultPreemption":
                    return {
                        "minCandidateNodesPercentage":
                            pl.min_candidate_nodes_percentage,
                        "minCandidateNodesAbsolute":
                            pl.min_candidate_nodes_absolute,
                    }
        return {}

    def _apply_preemptions(self, items: List[Tuple], cycle: int) -> None:
        """PrepareCandidate (default_preemption.go:690) for a wave of
        fast-planned candidates. Scheduler-thread work is the in-memory
        bookkeeping only (nominations, metrics, queue parking); the API
        effects — victim deletes, then nominatedNodeName status patches —
        run on a worker so the scheduler is already parked on the queue
        when the delete echoes flush the wave back (the r3 serial apply
        held the scheduling thread for the whole wave)."""
        for info, cand in items:
            pod = info.pod
            metrics.preemption_attempts.inc()
            metrics.preemption_victims.observe(len(cand.victims))
            self.recorder.event(
                pod, "Normal", "Preempted",
                f"preempted {len(cand.victims)} pod(s) on node "
                f"{cand.node_name}",
            )
            self.nominator.add_nominated_pod(pod, cand.node_name)
            info.nominated_node = cand.node_name
            for lower in get_lower_priority_nominated_pods(
                self.nominator, pod, cand.node_name
            ):
                self.nominator.delete_nominated_pod_if_exists(lower)
            # register the victim set on the node's wave, THEN park: the
            # node's preemptors re-activate together when its last
            # claimed victim's delete echoes
            pkey = v1.pod_key(pod)
            vkeys = {v1.pod_key(v) for v in cand.victims}
            with self._preempt_lock:
                pending, infos = self._node_waves.setdefault(
                    cand.node_name, (set(), [])
                )
                pending |= vkeys
                infos.append(info)
                self._inflight_preemptors.add(pkey)
                for vk in vkeys:
                    self._victim_waiters[vk] = cand.node_name
            self._record_failure(info, cycle, {})
            # the wave may have fully drained between registration and
            # parking — activate now rather than never
            if not self._preemption_in_flight(pod):
                self.queue.activate(pod)

        extra_victims = self._gang_preemption_closure(items)

        def _effects(items=items, extra_victims=extra_victims):
            # victims first — their deletion unblocks the preemptors; the
            # status patch is observability (the in-memory nominated_node
            # already steers the queue and the placement short-circuit)
            from ..apiserver.server import NotFound

            for info, cand in items:
                for victim in cand.victims:
                    try:
                        self.client.pods.delete(
                            victim.metadata.name, victim.metadata.namespace,
                            fence=self._fence,
                        )
                    except NotFound:
                        # already gone — but ONLY resolve the wave here
                        # if the delete echo has also been processed
                        # (victim absent from the informer cache);
                        # otherwise the in-flight echo fires
                        # _on_victim_deleted itself, and resolving
                        # early would activate preemptors against a
                        # cache that still shows the victim
                        if self.informers.pods().get(
                            meta_namespace_key(victim)
                        ) is None:
                            self._on_victim_deleted(victim)
                    except APIError:
                        # transient server error: the victim may still
                        # be alive — leave the wave pending (the 60s
                        # leftover flush is the honest fallback)
                        logger.warning(
                            "victim delete failed for %s",
                            v1.pod_key(victim), exc_info=True,
                        )
            # gang closure: bound siblings of evicted gang members go
            # too (whole gangs or none), same echo bookkeeping
            for victim in extra_victims:
                try:
                    self.client.pods.delete(
                        victim.metadata.name, victim.metadata.namespace,
                        fence=self._fence,
                    )
                except NotFound:
                    if self.informers.pods().get(
                        meta_namespace_key(victim)
                    ) is None:
                        self._on_victim_deleted(victim)
                except APIError:
                    logger.warning(
                        "gang sibling delete failed for %s",
                        v1.pod_key(victim), exc_info=True,
                    )
            for info, cand in items:
                try:
                    fresh = self.client.pods.get(
                        info.pod.metadata.name, info.pod.metadata.namespace
                    )
                    fresh.status.nominated_node_name = cand.node_name
                    self.client.pods.update_status(fresh, fence=self._fence)
                except APIError:
                    pass

        with self._inflight_lock:
            self._inflight += 1
        try:
            self._binders.submit(self._run_then_release, _effects)
        except RuntimeError:  # pool shut down (stop() race)
            with self._inflight_lock:
                self._inflight -= 1
            _effects()

    def _gang_preemption_closure(self, items: List[Tuple]) -> List[v1.Pod]:
        """Whole-gangs-or-none eviction closure for a preemption wave.

        The planners already emit same-node gang victims as indivisible
        units; what they cannot see is a victim gang's members bound on
        OTHER nodes.  One informer pass finds those bound siblings and
        registers them on the claiming preemptor's node wave (so the
        preemptor re-activates only once the whole gang's deletes have
        echoed), returning them for _effects to delete.  Any
        still-waiting wave of a victim gang is rolled back too — its
        parked members release their reservations rather than straggle
        in as a partial gang."""
        from .plugins.coscheduling import pod_group

        # (ns, group) -> node wave that claims the closure's echoes
        gang_nodes: Dict[Tuple[str, str], str] = {}
        claimed = set()
        for info, cand in items:
            for victim in cand.victims:
                claimed.add(v1.pod_key(victim))
                group, min_available = pod_group(victim)
                if group and min_available > 1:
                    gk = (victim.metadata.namespace, group)
                    gang_nodes.setdefault(gk, cand.node_name)
        if not gang_nodes:
            return []

        extra: List[v1.Pod] = []
        for pod in self.informers.pods().list():
            group, min_available = pod_group(pod)
            if not group or min_available <= 1:
                continue
            node = gang_nodes.get((pod.metadata.namespace, group))
            if node is None:
                continue
            key = v1.pod_key(pod)
            if key in claimed:
                continue
            if not pod.spec.node_name or pod.metadata.deletion_timestamp:
                continue
            with self._preempt_lock:
                if key in self._victim_waiters:
                    continue  # already claimed by an in-flight wave
                pending, _infos = self._node_waves.setdefault(
                    node, (set(), [])
                )
                pending.add(key)
                self._victim_waiters[key] = node
            claimed.add(key)
            extra.append(pod)

        gangpl = self._gang_plugin()
        for (ns, group), _node in gang_nodes.items():
            metrics.gang_preempted.inc()
            if gangpl is not None:
                gangpl.reject_gang(
                    ns, group, "preempted",
                    message=f"gang {group!r} preempted by higher-priority "
                            f"pod(s); rolling back its waiting members",
                )
        return extra

    def _clear_nomination(self, info) -> None:
        """util.ClearNominatedNodeName equivalent: the nomination can no
        longer lead anywhere (no candidate and no fit) — drop it from the
        nominator, the queue bookkeeping, and the API status."""
        pod = info.pod
        info.nominated_node = ""
        self.nominator.delete_nominated_pod_if_exists(pod)
        if pod.status.nominated_node_name:
            def _clear(pod=pod):
                try:
                    fresh = self.client.pods.get(
                        pod.metadata.name, pod.metadata.namespace
                    )
                    fresh.status.nominated_node_name = ""
                    self.client.pods.update_status(fresh, fence=self._fence)
                except APIError:
                    pass
            with self._inflight_lock:
                self._inflight += 1
            try:
                self._binders.submit(self._run_then_release, _clear)
            except RuntimeError:  # pool shut down (stop() race)
                with self._inflight_lock:
                    self._inflight -= 1
                _clear()

    def _on_victim_deleted(self, pod: v1.Pod) -> None:
        """A deleted assigned pod may be a claimed preemption victim:
        when its node's LAST outstanding victim goes, activate every
        preemptor nominated there (skip any remaining backoff — the
        capacity they were promised just finished freeing)."""
        key = v1.pod_key(pod)
        ready: List = []
        with self._preempt_lock:
            node = self._victim_waiters.pop(key, None)
            if node is None:
                return
            wave = self._node_waves.get(node)
            if wave is None:
                return
            pending, infos = wave
            pending.discard(key)
            if not pending:
                del self._node_waves[node]
                for info in infos:
                    self._inflight_preemptors.discard(v1.pod_key(info.pod))
                ready = infos
        for info in ready:
            self.queue.activate(info.pod)

    def _clear_preempt_tracking(self, pod: v1.Pod) -> None:
        """The preemptor bound or was deleted: drop its in-flight state.
        Its node wave keeps draining for any sibling preemptors."""
        key = v1.pod_key(pod)
        with self._preempt_lock:
            if key not in self._inflight_preemptors:
                return
            self._inflight_preemptors.discard(key)
            for node, (pending, infos) in list(self._node_waves.items()):
                infos[:] = [i for i in infos if v1.pod_key(i.pod) != key]
                if not infos and not pending:
                    del self._node_waves[node]

    def _preemption_in_flight(self, pod: v1.Pod) -> bool:
        with self._preempt_lock:
            return v1.pod_key(pod) in self._inflight_preemptors

    def _run_then_release(self, fn) -> None:
        try:
            fn()
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _place_nominated(self, infos: List) -> set:
        """Feasibility on the nominated node ONLY (the reference's
        evaluateNominatedNode); feasible pods assume+bind directly.
        Returns ids of placed infos."""
        self.snapshot = self.cache.update_snapshot(self.snapshot)
        bound: List[Tuple] = []
        placed: set = set()
        for info in infos:
            node_name = (
                info.nominated_node or info.pod.status.nominated_node_name
            )
            ni = self.snapshot.node_info_map.get(node_name)
            if ni is None:
                continue
            state = CycleState()
            st = self.framework.run_pre_filter_plugins(state, info.pod)
            if st is not None and not st.is_success():
                continue
            st = self.framework.run_filter_plugins_with_nominated_pods(
                state, info.pod, ni, self.nominator
            )
            if st is not None:
                continue
            bound.append((info, node_name))
            placed.add(id(info))
        if bound:
            self._assume_and_bind_batch(bound)
        return placed

    def _assume_and_bind_batch(self, bound: List[Tuple]) -> None:
        """Batched assume + binding-cycle kickoff. Per-pod semantics match
        _assume_and_bind exactly; the batching removes the host costs the
        full-loop profile blamed: per-pod serde deep copies, cache-lock
        ping-pong between assume (scheduler thread) and finish_binding
        (binder pool), one executor submission + bind POST + event write
        per pod. The reference's answer to the same costs is 8 parallel
        binder goroutines (scheduler.go:540); under a GIL the equivalent
        lever is one binder task carrying the whole batch."""
        # shallow clone (pod + spec): only spec.nodeName diverges; the
        # informer's confirm replaces the cache entry with its own object
        # moments later. Deep-copying 4k pods through serde per batch was
        # ~10% of the measured window.
        assumed_list: List[v1.Pod] = []
        for info, node in bound:
            assumed = copy.copy(info.pod)
            assumed.spec = copy.copy(info.pod.spec)
            assumed.spec.node_name = node
            assumed_list.append(assumed)
        with tracing.span("assume", "assume", n=len(assumed_list)):
            ok = self.cache.assume_pods(assumed_list)
        batch_items: List[Tuple] = []  # (assumed, node, state, info)
        # one check per harvest, not per pod: with no Reserve and no
        # Permit plugins registered (the common profile), the entire
        # _reserve_and_permit call is a guaranteed "bind" — skip the
        # per-pod framework dispatch. CycleState is still minted per pod
        # (PreBind/PostBind read it in the binding cycle).
        fwk = self.framework
        plugins_engaged = fwk is not None and (
            fwk.reserve_plugins or fwk.permit_plugins)
        with tracing.span("reserve-permit", "reserve-permit",
                          n=len(assumed_list)):
            for (info, node), assumed, assumed_ok in zip(
                    bound, assumed_list, ok):
                if not assumed_ok:
                    # already in cache (informer raced us): the device
                    # carry keeps this placement, the cache never takes
                    # it — void overlapping shadow audits
                    self._dropped_decisions += 1
                    continue
                state = CycleState()
                if not plugins_engaged or self._reserve_and_permit(
                        state, assumed, node, info) == "bind":
                    batch_items.append((assumed, node, state, info))
        if batch_items:
            with self._inflight_lock:
                self._inflight += 1
            try:
                self._binders.submit(self._bind_batch, batch_items)
            except RuntimeError:
                # pool shut down (stop() raced a lagging completion):
                # bind inline — we're already off the scheduler thread,
                # and stranding the batch assumed-in-cache is worse
                self._bind_batch(batch_items)

    def _reserve_and_permit(
        self, state: CycleState, assumed: v1.Pod, node_name: str, info
    ) -> str:
        """Shared Reserve+Permit sequence for an already-assumed pod
        (scheduler.go:508,:520). Returns "bind" when the caller should
        proceed to the binding cycle; "handled" when the pod was aborted
        or parked on a WAIT thread here."""
        fwk = self.framework
        if fwk is None:
            return "bind"
        # RunReservePluginsReserve (scheduler.go:508)
        st = fwk.run_reserve_plugins_reserve(state, assumed, node_name)
        if st is not None and not st.is_success():
            fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._abort_binding(assumed, f"Reserve: {st.message()}")
            return "handled"
        # RunPermitPlugins (scheduler.go:520); WAIT parks the pod and the
        # binding thread blocks in wait_on_permit
        st = fwk.run_permit_plugins(state, assumed, node_name)
        if st is not None and not st.is_success() and st.code != Code.WAIT:
            fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
            self._abort_binding(assumed, f"Permit: {st.message()}")
            return "handled"
        if st is not None and st.code == Code.WAIT:
            # WAIT-parked pods must NOT occupy the bounded binder pool: a
            # gang larger than the pool would deadlock (every worker
            # blocked in wait_on_permit, the unblocking pod queued behind
            # them). The reference runs one goroutine per binding cycle
            # (scheduler.go:540); a thread per parked pod at gang scale
            # (thousands parked at once) thrashes the GIL, so parked pods
            # register a resolution listener and ONE drainer thread
            # releases them through the batched binding cycle.
            self._park_waiting(assumed, node_name, state, info)
            return "handled"
        return "bind"

    # -- permit drainer: WAIT pods without a thread each -------------------

    def _park_waiting(
        self, assumed: v1.Pod, node_name: str, state: CycleState, info
    ) -> None:
        with self._inflight_lock:
            self._inflight += 1
        key = v1.pod_key(assumed)
        wp = self.framework.get_waiting_pod(key)
        if wp is None:
            # resolved before we could park (plugin allowed within
            # run_permit_plugins' return): plain binding cycle
            try:
                self._binders.submit(self._bind, assumed, node_name, state, info)
            except RuntimeError:  # pool shut down (stop() race)
                with self._inflight_lock:
                    self._inflight -= 1
                self._retry_failed_bind(assumed)
            return
        with self._permit_lock:
            self._permit_parked[key] = (assumed, node_name, state, info, wp)
            if self._permit_thread is None:
                self._permit_thread = threading.Thread(
                    target=self._permit_drain_loop,
                    name="permit-drainer", daemon=True,
                )
                self._permit_thread.start()
        wp.add_listener(lambda k=key: self._permit_release(k))

    def _permit_release(self, key: str) -> None:
        with self._permit_lock:
            item = self._permit_parked.pop(key, None)
            if item is not None:
                self._permit_released.append(item)
        self._permit_wake.set()

    def _permit_drain_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._permit_drain_once()
            except Exception:  # the drainer must outlive plugin bugs:
                # its death would strand every parked pod forever
                traceback.print_exc()

    def _permit_drain_once(self) -> None:
        # wake on releases, or in time for the nearest permit deadline
        with self._permit_lock:
            parked = list(self._permit_parked.values())
        now = _time.monotonic()
        next_deadline = min(
            (wp.deadline for _, _, _, _, wp in parked), default=now + 0.5
        )
        self._permit_wake.wait(timeout=max(0.02, min(next_deadline - now, 0.5)))
        self._permit_wake.clear()
        now = _time.monotonic()
        for _, _, _, _, wp in parked:
            # deadline is immutable: the lock-free check skips the cv
            # acquisition for the (vast) non-expired majority
            if now >= wp.deadline:
                wp.timeout_if_due(now)  # fires the release listener
        try:
            self._gang_deadlock_tick(now)
        except Exception:  # noqa: BLE001 — the breaker observes; a bug
            # in it must not kill the drainer
            traceback.print_exc()
        with self._permit_lock:
            released, self._permit_released = self._permit_released, []
        if not released:
            return
        items: List[Tuple] = []
        aborted: List[Tuple[v1.Pod, str]] = []
        fwk = self.framework
        for assumed, node_name, state, info, _wp in released:
            try:
                # resolved already — returns instantly and unparks the pod
                st = fwk.wait_on_permit(assumed)
                if st is not None and not st.is_success():
                    fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
                    aborted.append((assumed, f"Permit: {st.message()}"))
                    with self._inflight_lock:
                        self._inflight -= 1
                    continue
            except Exception:
                # release the inflight hold and requeue rather than
                # stranding the assumed pod
                traceback.print_exc()
                with self._inflight_lock:
                    self._inflight -= 1
                try:
                    self._retry_failed_bind(assumed)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
                continue
            items.append((assumed, node_name, state, info))
        if aborted:
            # a gang rollback rejects the whole wave into ONE drain pass:
            # abort it as one batch (single cache lock, one carry-delta
            # batch to the device session), each member requeued exactly
            # once — its WaitingPod resolved exactly once to get here
            try:
                self._abort_binding_batch(aborted)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        if items:
            # hand the whole release wave to the batched binding cycle;
            # swap the per-pod inflight holds for the batch's single one
            with self._inflight_lock:
                self._inflight -= len(items) - 1
            try:
                self._binders.submit(self._bind_batch, items)
            except RuntimeError:
                # pool already shut down (stop() race): release the wave
                # instead of stranding it assumed-in-cache
                with self._inflight_lock:
                    self._inflight -= 1
                for assumed, _, _, _ in items:
                    try:
                        self._retry_failed_bind(assumed)
                    except Exception:  # noqa: BLE001
                        traceback.print_exc()

    def _bind_batch(self, items: List[Tuple]) -> None:
        """Binding cycle for a whole batch in one worker: PreBind per pod,
        bulk bind application, single-lock finish_binding, batched metrics,
        async events. `unsettled` tracks pods whose outcome is not yet
        decided: an unexpected exception must forget+requeue them, or the
        assumed pods would phantom-occupy node resources forever
        (cleanup_expired_assumed_pods only expires pods whose binding
        FINISHED — an assumed pod that never reaches finish_binding has
        no expiry)."""
        unsettled = {id(assumed): assumed for assumed, _, _, _ in items}
        bind_t0 = _time.monotonic()
        bind_sp = tracing.span("bind", "bind", n=len(items))
        bind_sp.__enter__()
        try:
            fwk = self.framework
            ready: List[Tuple] = []
            for assumed, node, state, info in items:
                if fwk is not None:
                    st = fwk.run_pre_bind_plugins(state, assumed, node)
                    if st is not None and not st.is_success():
                        fwk.run_reserve_plugins_unreserve(state, assumed, node)
                        unsettled.pop(id(assumed), None)
                        self._abort_binding(assumed, f"PreBind: {st.message()}")
                        continue
                ready.append((assumed, node, state, info))
            if not ready:
                return
            outcomes = self.client.pods.bind_many(
                [(a.metadata.namespace, a.metadata.name, node)
                 for a, node, _, _ in ready],
                fence=self._fence,
            )
            now = _time.monotonic()
            done: List[Tuple] = []
            for (assumed, node, state, info), err in zip(ready, outcomes):
                unsettled.pop(id(assumed), None)
                if isinstance(err, FenceExpired):
                    # our lease epoch is dead: the new leader owns this
                    # pod now. Forget the assumed state but do NOT
                    # requeue — requeuing here is how a deposed leader
                    # double-schedules (the successor's reconcile has
                    # already relisted it).
                    self.cache.forget_pod(assumed)
                elif err is not None:
                    self._retry_failed_bind(assumed)
                else:
                    done.append((assumed, node, state, info))
            if not done:
                return
            self.cache.finish_binding_many([a for a, _, _, _ in done])
            metrics.schedule_attempts.inc(
                len(done), result=metrics.SCHEDULED, profile=self.profile_name
            )
            for assumed, node, state, info in done:
                # one pod's PostBind/event failure must not skip the
                # rest of the batch's hooks (all of `done` is already
                # bound — there is nothing left to unwind)
                try:
                    self._observe_bound(info, now)
                    self.recorder.event(
                        assumed, "Normal", "Scheduled",
                        f"Successfully assigned {assumed.metadata.namespace}/"
                        f"{assumed.metadata.name} to {node}",
                    )
                    if fwk is not None:
                        fwk.run_post_bind_plugins(state, assumed, node)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
        except FenceExpired:
            # whole-call fence rejection (a frontend that raises instead
            # of collecting per-binding outcomes): forget, never requeue
            for assumed in unsettled.values():
                self.cache.forget_pod(assumed)
        except Exception:
            traceback.print_exc()
            for assumed in unsettled.values():
                try:
                    self._retry_failed_bind(assumed)
                except Exception:  # noqa: BLE001 — keep releasing the rest
                    traceback.print_exc()
        finally:
            bind_sp.__exit__(None, None, None)
            metrics.attempt_duration.observe(
                _time.monotonic() - bind_t0, stage="bind")
            with self._inflight_lock:
                self._inflight -= 1

    def _retry_failed_bind(self, assumed: v1.Pod) -> None:
        """Bind POST failed: forget and requeue UNASSIGNED (keeping the
        failed nodeName would pin every retry to that node via the
        NodeName filter)."""
        self.cache.forget_pod(assumed)
        retry = serde.from_dict(v1.Pod, serde.to_dict(assumed))
        retry.spec.node_name = ""
        self.queue.add(retry)

    def _observe_bound(self, info, now: float) -> None:
        """Per-pod scheduling-latency metrics at bind-sent time."""
        if info is None:
            return
        e2e = now - info.initial_attempt_timestamp
        attempt = now - (info.pop_timestamp or info.initial_attempt_timestamp)
        metrics.pod_scheduling_duration.observe(e2e, attempts=str(info.attempts))
        metrics.scheduling_attempt_duration.observe(attempt)
        # kube-style SLO histograms (scheduler_perf SLIs): e2e from the
        # FIRST attempt stamp, attempt from the LAST queue pop, queue
        # wait as the difference — all from stamps that already exist.
        metrics.e2e_duration.observe(e2e)
        metrics.attempt_duration.observe(attempt, stage="attempt")
        metrics.queue_wait.observe(max(0.0, e2e - attempt))
        self.latency_samples.append((e2e, attempt, info.attempts))
        self.bind_timestamps.append(now)

    def _schedule_one_oracle(self, info) -> None:
        pod = info.pod
        cycle = self.queue.scheduling_cycle
        if self._skip(pod):
            return
        self.snapshot = self.cache.update_snapshot(self.snapshot)
        state = CycleState()
        try:
            result = self.algorithm.schedule(
                state, self.framework, pod, self.snapshot, nominator=self.nominator
            )
        except FitError as fe:
            self._record_failure(info, cycle, fe.filtered_nodes_statuses, state)
            return
        self._assume_and_bind(pod, result.suggested_host, state, info=info)

    # -- failure path: preemption then unschedulable queue -----------------

    def _list_pdbs(self) -> List[v1.PodDisruptionBudget]:
        try:
            items, _ = self.client.resource("poddisruptionbudgets").list()
            return items
        except Exception:
            return []

    def _record_failure(
        self,
        info,
        cycle: int,
        statuses: Optional[Dict[str, object]] = None,
        state: Optional[CycleState] = None,
    ) -> None:
        """scheduler.go:427 failure branch: RunPostFilterPlugins (preemption)
        then park in the unschedulable queue with nominatedNodeName set so
        the next attempt lands on the freed node."""
        pod = info.pod
        metrics.schedule_attempts.inc(
            result=metrics.UNSCHEDULABLE, profile=self.profile_name
        )
        self.recorder.event(
            pod, "Warning", "FailedScheduling",
            f"0/{self.cache.node_count()} nodes are available",
        )
        if statuses:
            try:
                self._try_preempt(pod, statuses, state)
            except Exception:
                traceback.print_exc()
        self.queue.add_unschedulable_if_not_present(info, cycle)

    def _try_preempt(self, pod: v1.Pod, statuses, state: Optional[CycleState]) -> None:
        self.snapshot = self.cache.update_snapshot(self.snapshot)
        if state is None:
            # TPU path: the kernel bypassed the oracle PreFilter, but the
            # preemption dry-run's AddPod/RemovePod extensions read its
            # CycleState — run it here (framework.go:426)
            state = CycleState()
            st = self.framework.run_pre_filter_plugins(state, pod)
            if st is not None and not st.is_success():
                return
        metrics.preemption_attempts.inc()
        metrics.preemption_planner.inc(path="oracle")
        result, status = self.framework.run_post_filter_plugins(state, pod, statuses)
        if result is None or status is None or not status.is_success():
            return
        node_name = result.nominated_node_name
        metrics.preemption_victims.observe(len(result.victims))
        self.recorder.event(
            pod, "Normal", "Preempted",
            f"preempted {len(result.victims)} pod(s) on node {node_name}",
        )
        # PrepareCandidate (default_preemption.go:690): patch nomination,
        # evict victims, clear lower-priority nominations on that node
        self.nominator.add_nominated_pod(pod, node_name)
        try:
            fresh = self.client.pods.get(pod.metadata.name, pod.metadata.namespace)
            fresh.status.nominated_node_name = node_name
            self.client.pods.update_status(fresh, fence=self._fence)
        except APIError:
            pass
        for victim in result.victims:
            try:
                self.client.pods.delete(
                    victim.metadata.name, victim.metadata.namespace,
                    fence=self._fence,
                )
            except APIError:
                pass
        for lower in get_lower_priority_nominated_pods(self.nominator, pod, node_name):
            self.nominator.delete_nominated_pod_if_exists(lower)

    # -- assume + binding cycle (scheduler.go:359,:540) --------------------

    def _assume_and_bind(
        self,
        pod: v1.Pod,
        node_name: str,
        state: Optional[CycleState] = None,
        info=None,
    ) -> None:
        # copy before assume (scheduler.go:445 pod.DeepCopy): the queue and
        # informer cache must not see the assumed nodeName. Shallow pod+spec
        # copy suffices — only spec.nodeName diverges and nothing mutates
        # the shared tail objects (the copy discipline informers enforce).
        assumed = copy.copy(pod)
        assumed.spec = copy.copy(pod.spec)
        assumed.spec.node_name = node_name
        try:
            self.cache.assume_pod(assumed)
        except ValueError:
            return  # already in cache (informer raced us)
        state = state if state is not None else CycleState()
        if self._reserve_and_permit(state, assumed, node_name, info) != "bind":
            return
        with self._inflight_lock:
            self._inflight += 1
        self._binders.submit(self._bind, assumed, node_name, state, info)

    def _abort_binding(self, assumed: v1.Pod, reason: str) -> None:
        """Reserve/Permit/PreBind failure: forget the assumed pod and retry
        it unassigned (scheduler.go:516 failure branches)."""
        self.cache.forget_pod(assumed)
        self.recorder.event(assumed, "Warning", "FailedScheduling", reason)
        retry = serde.from_dict(v1.Pod, serde.to_dict(assumed))
        retry.spec.node_name = ""
        self.queue.add(retry)

    def _abort_binding_batch(self, items: List[Tuple[v1.Pod, str]]) -> None:
        """_abort_binding for a whole rollback wave (a rejected gang):
        one batched cache forget — the device session absorbs the
        wave's released capacity as one carry-delta batch — then each
        member requeues unassigned, exactly once."""
        self.cache.forget_pods([assumed for assumed, _ in items])
        for assumed, reason in items:
            self.recorder.event(
                assumed, "Warning", "FailedScheduling", reason)
            retry = serde.from_dict(v1.Pod, serde.to_dict(assumed))
            retry.spec.node_name = ""
            # backoff re-entry, not active: the wave's released capacity
            # must be claimable by OTHER pods (a rival gang's stalled
            # member) before these members re-drive, or a deadlock
            # back-off re-forms the same stall it just broke
            self.queue.requeue_with_backoff(retry)

    # -- gang transaction seams --------------------------------------------

    def _gang_plugin(self):
        """The Coscheduling permit plugin instance, when the profile
        enables it (None otherwise) — the scheduler-side rollback paths
        (deletion, deadlock, device fault, demotion, reconcile) all
        route whole-gang rejections through its wave gates."""
        fwk = self.framework
        if fwk is None:
            return None
        for pl in getattr(fwk, "permit_plugins", ()):
            if getattr(pl, "name", "") == "Coscheduling":
                return pl
        return None

    def _gang_deadlock_tick(self, now: float) -> None:
        """Host-side gang deadlock breaker, ticked from the permit
        drainer: two or more gangs each camping on partial capacity the
        others need make no membership progress — after
        KTPU_GANG_DEADLOCK_TICKS consecutive stalled observations (at
        least KTPU_GANG_DEADLOCK_INTERVAL apart) the YOUNGEST stalled
        gang (latest first park) is backed off whole, freeing its
        reserved capacity for the elders. Bounded and hysteretic: one
        gang per trigger, never the same gang twice in a row, never
        with fewer than two stalled gangs, and a gang whose membership
        moved resets its own counter. A stalled gang that is jointly
        INFEASIBLE on the current cluster (the batched positive-delta
        what-if says its remaining members can never co-place) is
        preferred as the back-off victim — it can never complete, so
        backing off a feasible younger gang instead would be waste."""
        gang = self._gang_plugin()
        if gang is None:
            return
        interval = knobs.get_float("KTPU_GANG_DEADLOCK_INTERVAL")
        if now - self._gang_tick_last < (interval or 0.0):
            return
        self._gang_tick_last = now
        gates = [g for g in gang.waiting_gangs() if not g.failed]
        if len(gates) < 2:
            self._gang_stall = {}
            return
        ticks = max(1, knobs.get_int("KTPU_GANG_DEADLOCK_TICKS") or 1)
        stalled = []
        nxt: Dict[Tuple[str, str], Tuple] = {}
        for g in gates:
            sig = frozenset(g.members())
            prev_sig, count = self._gang_stall.get(
                (g.namespace, g.group), (None, 0))
            count = count + 1 if sig == prev_sig else 1
            nxt[(g.namespace, g.group)] = (sig, count)
            if count >= ticks:
                stalled.append(g)
        self._gang_stall = nxt
        if len(stalled) < 2:
            return
        stalled.sort(key=lambda g: g.first_park or 0.0, reverse=True)
        infeasible = [
            g for g in stalled if self._gang_feasible(g) is False
        ]
        ordered = infeasible + [g for g in stalled if g not in infeasible]
        victim = ordered[0]
        if (victim.namespace, victim.group) == self._gang_last_backoff \
                and len(ordered) > 1:
            victim = ordered[1]
        self._gang_last_backoff = (victim.namespace, victim.group)
        self._gang_stall.pop((victim.namespace, victim.group), None)
        gang.reject_gang(
            victim.namespace, victim.group, "deadlock",
            message=f"gang {victim.group!r} backed off by the deadlock "
                    f"breaker ({len(stalled)} gangs mutually stalled)",
        )

    def _gang_feasible(self, gate) -> Optional[bool]:
        """Joint co-placement feasibility for a waiting gang: can its
        REMAINING members (beyond the ones already reserved) co-place
        on the current cluster at all? Scored as one batched
        positive-delta what-if launch on a scratch carry
        (ops/whatif.py gang_fits): per-node multiplicity of the member
        template, summed and compared against the need. None = unknown
        (whatif off, no parked member to take the template from, or
        the launch faulted) — callers must treat unknown as feasible."""
        tpu = self.tpu
        fn = getattr(tpu, "gang_feasible", None)
        if tpu is None or fn is None or not tpu.whatif_enabled():
            return None
        member_keys = gate.members()
        with self._permit_lock:
            probe = next(
                (self._permit_parked[k][0] for k in member_keys
                 if k in self._permit_parked),
                None,
            )
        if probe is None:
            return None
        gang = self._gang_plugin()
        reserved = 0
        if gang is not None:
            reserved = gang._reserved_members(gate.group, gate.namespace)
        remaining = gate.min_available - reserved
        if remaining <= 0:
            return True
        return fn(probe, remaining)

    def _bind(
        self, assumed: v1.Pod, node_name: str, state: CycleState, info=None
    ) -> None:
        try:
            fwk = self.framework
            if fwk is not None:
                # WaitOnPermit (framework.go:1015) then PreBind (volume
                # binding API writes happen here, scheduler.go:540)
                st = fwk.wait_on_permit(assumed)
                if st is not None and not st.is_success():
                    fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
                    self._abort_binding(assumed, f"Permit: {st.message()}")
                    return
                st = fwk.run_pre_bind_plugins(state, assumed, node_name)
                if st is not None and not st.is_success():
                    fwk.run_reserve_plugins_unreserve(state, assumed, node_name)
                    self._abort_binding(assumed, f"PreBind: {st.message()}")
                    return
            self.client.pods.bind(
                assumed.metadata.namespace, assumed.metadata.name, node_name,
                fence=self._fence,
            )
            self.cache.finish_binding(assumed)
            metrics.schedule_attempts.inc(
                result=metrics.SCHEDULED, profile=self.profile_name
            )
            self._observe_bound(info, _time.monotonic())
            self.recorder.event(
                assumed, "Normal", "Scheduled",
                f"Successfully assigned {assumed.metadata.namespace}/"
                f"{assumed.metadata.name} to {node_name}",
            )
            if self.framework is not None:
                self.framework.run_post_bind_plugins(state, assumed, node_name)
        except FenceExpired:
            # deposed mid-bind: forget the assumed pod, do NOT requeue —
            # the successor relisted it at promotion (before FenceExpired
            # — a subclass of APIError — the clause below would have
            # requeued it into a double-schedule)
            self.cache.forget_pod(assumed)
        except APIError:
            self._retry_failed_bind(assumed)
        except Exception:
            traceback.print_exc()
            self.cache.forget_pod(assumed)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    # -- introspection -----------------------------------------------------

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Test helper: queue drained AND no batch/bind in flight."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._inflight_lock:
                inflight = self._inflight
            with self._completion_cv:
                completions = len(self._completions)
            if (
                inflight == 0
                and completions == 0  # pipelined tail batches
                and not self.queue.pending_pods()
            ):
                return True
            time.sleep(0.05)
        return False
