"""kubernetes_tpu — a TPU-native cluster orchestration framework.

A from-scratch rebuild of the capabilities of Kubernetes (reference:
choury/kubernetes ~v1.21) designed TPU-first: the scheduler's Filter/Score
hot path (reference: pkg/scheduler/framework/runtime/framework.go:723
RunScorePlugins, a 16-goroutine per-node loop) is reformulated as a dense
pod x node constraint-mask + score matrix evaluated in a single XLA
dispatch, sharded over a jax.sharding.Mesh.

Layout (mirrors SURVEY.md section 7 build plan):
  api/        typed API objects, resource.Quantity math, label selectors
  store/      revisioned ordered KV + watch (the etcd equivalent)
  client/     informer-style caches, workqueues
  scheduler/  queue, assume-cache, scheduling framework + plugins (CPU oracle)
  models/     dense array encoding of cluster state for the TPU kernel
  ops/        JAX/XLA kernels: feasibility masks, score matrices, selection
  parallel/   device mesh, sharded dispatch, collectives
  controllers/ control loops (replicaset, node lifecycle, ...)
  utils/      serde, backoff, misc
"""

__version__ = "0.1.0"
