"""Test/benchmark fixtures: pod/node builders and synthetic cluster
generators (reference: pkg/scheduler/testing/wrappers.go,
test/integration/scheduler_perf/config/performance-config.yaml)."""

from .synth import make_node, make_pod, synth_cluster, synth_pending_pods  # noqa: F401
