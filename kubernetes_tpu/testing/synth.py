"""Synthetic cluster + workload generators.

Shapes follow the reference's scheduler_perf harness: nodes of 110 pods /
4 CPU / 32Gi (reference: test/integration/scheduler_perf/
scheduler_test.go:56-60 makeBasePod and node template), zone-labelled for
topology-spread workloads (config/performance-config.yaml), pods stamped
from a small set of templates so encoding caches amortize exactly as the
harness's template-stamped pods do.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..api import types as v1


def make_node(
    name: str,
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[v1.Taint]] = None,
    unschedulable: bool = False,
    images: Optional[List[v1.ContainerImage]] = None,
    extended: Optional[Dict[str, str]] = None,
) -> v1.Node:
    alloc = {"cpu": cpu, "memory": memory, "pods": str(pods)}
    if extended:
        alloc.update(extended)
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, labels=dict(labels or {})),
        spec=v1.NodeSpec(unschedulable=unschedulable, taints=taints),
        status=v1.NodeStatus(capacity=dict(alloc), allocatable=alloc, images=images),
    )


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: Optional[str] = None,
    memory: Optional[str] = None,
    node_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    priority: Optional[int] = None,
    affinity: Optional[v1.Affinity] = None,
    constraints: Optional[List[v1.TopologySpreadConstraint]] = None,
    image: str = "registry.example/app:v1",
    extended: Optional[Dict[str, str]] = None,
) -> v1.Pod:
    requests: Dict[str, str] = {}
    if cpu is not None:
        requests["cpu"] = cpu
    if memory is not None:
        requests["memory"] = memory
    if extended:
        requests.update(extended)
    return v1.Pod(
        metadata=v1.ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec=v1.PodSpec(
            containers=[
                v1.Container(
                    name="c0",
                    image=image,
                    resources=v1.ResourceRequirements(requests=requests or None),
                )
            ],
            node_name=node_name,
            priority=priority,
            affinity=affinity,
            topology_spread_constraints=constraints,
        ),
    )


def synth_cluster(
    n_nodes: int,
    n_zones: int = 3,
    pods_per_node: int = 0,
    seed: int = 0,
) -> tuple:
    """Nodes with hostname/zone/region topology labels plus pods_per_node
    running pods stamped from one template (the scheduler_perf initPods
    pattern). Returns (nodes, pods)."""
    rng = random.Random(seed)
    nodes: List[v1.Node] = []
    for i in range(n_nodes):
        name = f"node-{i}"
        labels = {
            v1.LABEL_HOSTNAME: name,
            v1.LABEL_ZONE: f"zone-{i % n_zones}",
            v1.LABEL_REGION: f"region-{i % n_zones % 2}",
        }
        nodes.append(make_node(name, labels=labels))
    pods: List[v1.Pod] = []
    for i in range(n_nodes * pods_per_node):
        node = f"node-{rng.randrange(n_nodes)}"
        pods.append(
            make_pod(
                f"init-pod-{i}",
                cpu="10m",
                memory="16Mi",
                node_name=node,
                labels={"app": f"init-{i % 8}"},
            )
        )
    return nodes, pods


def synth_pending_pods(
    n_pods: int,
    n_templates: int = 4,
    cpu: str = "100m",
    memory: str = "128Mi",
    spread: bool = False,
) -> List[v1.Pod]:
    """Pending pods stamped from n_templates distinct specs (labels differ
    per template; names differ per pod). With spread=True each template
    carries a zone topology-spread constraint (the PodTopologySpread
    benchmark shape: performance-config.yaml SchedulingPodTopologySpread)."""
    pods: List[v1.Pod] = []
    for i in range(n_pods):
        t = i % n_templates
        labels = {"app": f"tmpl-{t}"}
        constraints = None
        if spread:
            constraints = [
                v1.TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=v1.LABEL_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=v1.LabelSelector(match_labels=dict(labels)),
                )
            ]
        pods.append(
            make_pod(
                f"pending-{i}",
                cpu=cpu,
                memory=memory,
                labels=labels,
                constraints=constraints,
            )
        )
    return pods
