"""Invariant monitors for endurance runs (scripts/soak.py, tests).

An endurance soak is only as strong as what it asserts, and asserting by
poking scheduler internals couples the harness to implementation detail
that a production operator cannot see. These monitors read the SAME
surface operations would: the Prometheus text of /metricsz (process
self-telemetry included — utils/selfstats.py) sampled over the run. The
suite samples on a cadence, each invariant folds the sample stream, and
`finish()` returns every violation; `bundle()` writes the triage
artifacts (flight-recorder ring dump + first/last metrics snapshots +
the violation report) for a failed run.

Invariants shipped (the soak wires all of them):

  CounterFlat       a counter must not move (zero shadow drift, zero
                    expired assumes)
  CounterMoved      a counter must move by at least min_delta (the
                    drill's disruption really exercised its path —
                    leader transitions under failover chaos)
  GaugeBaseline     a gauge must RETURN to its starting band by the end
                    (queue depth after each chaos wave, watcher count)
  BoundedGrowth     first-window vs last-window growth of a gauge stays
                    under an absolute and/or fractional bound (RSS, open
                    fds, thread count — the leak detectors)
  GaugeCeiling      a gauge never exceeds a ceiling at any sample (no
                    assumed pod outliving its TTL)
  HistogramP99Flat  windowed p99 from cumulative bucket deltas: the
                    last-third p99 must stay within a ratio of the
                    first-third p99 (stage latency flatness — the
                    "does it degrade over hours" question)
  Callback          escape hatch: any zero-argument callable returning
                    violation strings at finish (BindIntegrityChecker
                    wiring, convergence checks)
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

Reading = Dict[str, float]


def parse_metrics(text: str) -> Reading:
    """Prometheus text -> {series: value}. Series keys keep their label
    string verbatim (`name{a="b"} 1.0` -> key `name{a="b"}`)."""
    out: Reading = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            out[series] = float(value)
        except ValueError:
            continue
    return out


def series_name(series: str) -> str:
    return series.split("{", 1)[0]


def total(reading: Reading, name: str) -> float:
    """Sum a metric across its label sets (histogram _bucket series are
    cumulative — address them explicitly, not through this)."""
    return sum(v for k, v in reading.items() if series_name(k) == name)


def bucket_counts(reading: Reading, name: str) -> Dict[float, float]:
    """Cumulative bucket counts of `name` summed across non-le labels:
    {le_upper_bound: cumulative_count} (+Inf included as inf)."""
    out: Dict[float, float] = {}
    prefix = f"{name}_bucket{{"
    for k, v in reading.items():
        if not k.startswith(prefix):
            continue
        le = ""
        for part in k[len(prefix):-1].split(","):
            if part.startswith("le="):
                le = part[4:-1]
        bound = float("inf") if le == "+Inf" else float(le)
        out[bound] = out.get(bound, 0.0) + v
    return out


def window_p99(a: Reading, b: Reading, name: str) -> float:
    """p99 (bucket upper bound) of the observations that landed BETWEEN
    two samples, from cumulative bucket deltas — a windowed percentile
    out of plain Prometheus text, no internal sample buffer needed."""
    ca, cb = bucket_counts(a, name), bucket_counts(b, name)
    deltas: List[Tuple[float, float]] = sorted(
        (le, cb.get(le, 0.0) - ca.get(le, 0.0)) for le in cb
    )
    if not deltas:
        return 0.0
    n = deltas[-1][1]  # +Inf bucket is cumulative total
    if n <= 0:
        return 0.0
    target = 0.99 * n
    for le, cum in deltas:
        if cum >= target:
            return le
    return deltas[-1][0]


class Invariant:
    name = "invariant"

    def on_sample(self, t: float, reading: Reading) -> None:  # noqa: B027
        pass

    def check(self, samples: Sequence[Tuple[float, Reading]]) -> List[str]:
        return []


class CounterFlat(Invariant):
    """A counter that must not move over the run (e.g. zero drift)."""

    def __init__(self, metric: str, allow: float = 0.0, label: str = ""):
        self.metric = metric
        self.allow = allow
        self.name = label or f"flat:{metric}"

    def check(self, samples):
        if len(samples) < 2:
            return []
        delta = total(samples[-1][1], self.metric) - total(
            samples[0][1], self.metric)
        if delta > self.allow:
            return [f"{self.name}: {self.metric} moved by {delta:g} "
                    f"(allowed {self.allow:g})"]
        return []


class CounterMoved(Invariant):
    """The inverse of CounterFlat: a counter that MUST move over the run
    by at least `min_delta` — proof that a drill actually exercised the
    path it claims to (e.g. scheduler_leader_transitions_total under a
    failover mix, scheduler_fencing_rejections_total after a stale-token
    replay). A chaos run whose injection silently no-opped passes every
    convergence check; this is the one that fails it."""

    def __init__(self, metric: str, min_delta: float = 1.0,
                 label: str = ""):
        self.metric = metric
        self.min_delta = min_delta
        self.name = label or f"moved:{metric}"

    def check(self, samples):
        if len(samples) < 2:
            return []
        delta = total(samples[-1][1], self.metric) - total(
            samples[0][1], self.metric)
        if delta < self.min_delta:
            return [f"{self.name}: {self.metric} moved by {delta:g} "
                    f"(expected >= {self.min_delta:g} — the disruption "
                    f"never exercised this path)"]
        return []


class GaugeBaseline(Invariant):
    """A gauge that must RETURN to its starting band by the last sample
    (churn may spike it mid-run; staying high at the end is the leak)."""

    def __init__(self, metric: str, slack: float, label: str = ""):
        self.metric = metric
        self.slack = slack
        self.name = label or f"baseline:{metric}"

    def check(self, samples):
        if len(samples) < 2:
            return []
        base = total(samples[0][1], self.metric)
        final = total(samples[-1][1], self.metric)
        if final > base + self.slack:
            return [f"{self.name}: {self.metric} ended at {final:g}, "
                    f"baseline {base:g} + slack {self.slack:g}"]
        return []


class BoundedGrowth(Invariant):
    """Leak detector: median of the last third vs median of the first
    third must stay under max_abs and/or max_frac growth."""

    def __init__(self, metric: str, max_abs: Optional[float] = None,
                 max_frac: Optional[float] = None, label: str = ""):
        self.metric = metric
        self.max_abs = max_abs
        self.max_frac = max_frac
        self.name = label or f"growth:{metric}"

    @staticmethod
    def _median(vals: List[float]) -> float:
        vals = sorted(vals)
        return vals[len(vals) // 2] if vals else 0.0

    def check(self, samples):
        if len(samples) < 6:
            return []
        third = max(1, len(samples) // 3)
        first = self._median(
            [total(r, self.metric) for _, r in samples[:third]])
        last = self._median(
            [total(r, self.metric) for _, r in samples[-third:]])
        growth = last - first
        out = []
        if self.max_abs is not None and growth > self.max_abs:
            out.append(f"{self.name}: {self.metric} grew {growth:g} "
                       f"({first:g} -> {last:g}), max_abs {self.max_abs:g}")
        if (self.max_frac is not None and first > 0
                and growth / first > self.max_frac):
            out.append(f"{self.name}: {self.metric} grew "
                       f"{growth / first:.1%} ({first:g} -> {last:g}), "
                       f"max_frac {self.max_frac:.0%}")
        return out


class GaugeCeiling(Invariant):
    """A gauge that must never exceed `ceiling` at ANY sample."""

    def __init__(self, metric: str, ceiling: float, label: str = ""):
        self.metric = metric
        self.ceiling = ceiling
        self.name = label or f"ceiling:{metric}"
        self.worst = 0.0
        self.breaches = 0

    def on_sample(self, t, reading):
        v = total(reading, self.metric)
        self.worst = max(self.worst, v)
        if v > self.ceiling:
            self.breaches += 1

    def check(self, samples):
        if self.breaches:
            return [f"{self.name}: {self.metric} exceeded {self.ceiling:g} "
                    f"at {self.breaches} samples (worst {self.worst:g})"]
        return []


class HistogramP99Flat(Invariant):
    """First-third vs last-third windowed p99 of a histogram: the
    last-third p99 must stay within `ratio` of the first-third p99
    (ignoring windows under `floor` seconds — bucket quantization noise).
    THE sustained-degradation detector: a slow leak in any per-batch cost
    shows up here long before anything crashes."""

    def __init__(self, metric: str, ratio: float = 5.0,
                 floor: float = 0.01, label: str = ""):
        self.metric = metric
        self.ratio = ratio
        self.floor = floor
        self.name = label or f"p99flat:{metric}"
        self.first_p99 = 0.0
        self.last_p99 = 0.0

    def check(self, samples):
        if len(samples) < 6:
            return []
        third = max(1, len(samples) // 3)
        self.first_p99 = window_p99(
            samples[0][1], samples[third][1], self.metric)
        self.last_p99 = window_p99(
            samples[-third - 1][1], samples[-1][1], self.metric)
        if (self.first_p99 >= self.floor or self.last_p99 >= self.floor) \
                and self.last_p99 > self.ratio * max(self.first_p99,
                                                     self.floor):
            return [f"{self.name}: {self.metric} windowed p99 degraded "
                    f"{self.first_p99:g}s -> {self.last_p99:g}s "
                    f"(> {self.ratio:g}x)"]
        return []


class Callback(Invariant):
    """Any zero-arg callable returning violation strings at finish."""

    def __init__(self, name: str, fn: Callable[[], List[str]]):
        self.name = name
        self._fn = fn

    def check(self, samples):
        return list(self._fn())


class InvariantSuite:
    """Sample /metricsz on a cadence, fold every invariant, report.

    `scrape` defaults to the in-process configz.metricsz_body (the same
    text the HTTP /metricsz route serves); pass a callable that GETs a
    real endpoint to monitor a remote process."""

    def __init__(self, invariants: Sequence[Invariant],
                 scrape: Optional[Callable[[], str]] = None):
        if scrape is None:
            from ..utils import configz

            scrape = configz.metricsz_body
        self._scrape = scrape
        self.invariants = list(invariants)
        self.samples: List[Tuple[float, Reading]] = []
        self.violations: List[str] = []

    def sample(self) -> Reading:
        reading = parse_metrics(self._scrape())
        t = time.monotonic()
        self.samples.append((t, reading))
        for inv in self.invariants:
            try:
                inv.on_sample(t, reading)
            except Exception as e:  # noqa: BLE001 — a broken monitor is
                # itself a violation, not a harness crash
                self.violations.append(f"{inv.name}: monitor error {e!r}")
        return reading

    def finish(self) -> List[str]:
        """Final sample + every invariant's verdict; returns ALL
        violations (also kept on self.violations)."""
        self.sample()
        for inv in self.invariants:
            try:
                self.violations.extend(inv.check(self.samples))
            except Exception as e:  # noqa: BLE001
                self.violations.append(f"{inv.name}: check error {e!r}")
        return self.violations

    def bundle(self, out_dir: str, reason: str = "invariant-violation",
               extra: Optional[dict] = None) -> str:
        """Write the triage bundle for a failed run: the flight-recorder
        ring (if tracing is on), the first and last metrics snapshots,
        and report.json (violations + invariant summaries). Returns the
        bundle directory."""
        from ..utils import tracing

        os.makedirs(out_dir, exist_ok=True)
        trace_path = os.path.join(out_dir, "trace.json")
        if tracing.RECORDER.snapshot():
            tracing.dump(reason, path=trace_path)
        for tag, idx in (("first", 0), ("last", -1)):
            if self.samples:
                with open(os.path.join(out_dir, f"metrics_{tag}.json"),
                          "w", encoding="utf-8") as f:
                    json.dump(self.samples[idx][1], f, indent=1,
                              sort_keys=True)
        report = {
            "reason": reason,
            "violations": self.violations,
            "n_samples": len(self.samples),
            "invariants": [inv.name for inv in self.invariants],
        }
        if extra:
            report.update(extra)
        with open(os.path.join(out_dir, "report.json"), "w",
                  encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        return out_dir
