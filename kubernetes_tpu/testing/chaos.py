"""Chaosmonkey: periodic fault injection against a running cluster.

Reference: test/e2e/chaosmonkey/chaosmonkey.go:48 — a chaosmonkey Do()s
disruptions while registered tests run; the reboot/disruptive e2e suites
use it to prove the control plane re-converges. Here the disruptions are
the ones a hollow cluster can suffer: kubelet kill (node death), kubelet
restart (recovery), and random pod deletion (workload churn). Each
disruption is recorded so tests can assert recovery against the actual
injection history.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass
class Disruption:
    kind: str  # kill-kubelet | restart-kubelet | delete-pod
    target: str
    at: float = field(default_factory=time.time)


class ChaosMonkey:
    def __init__(
        self,
        cluster,  # kubernetes_tpu.cluster.Cluster (needs .hollow/.client)
        period: float = 1.0,
        rng: Optional[random.Random] = None,
        disruptions: Optional[List[str]] = None,
    ):
        self.cluster = cluster
        self.period = period
        self.rng = rng or random.Random(0)
        self.kinds = disruptions or ["kill-kubelet", "restart-kubelet", "delete-pod"]
        self.history: List[Disruption] = []
        self._dead: List = []  # kubelets killed and not yet restarted
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.do_one()
            except Exception:  # noqa: BLE001 — chaos must not crash the test
                pass

    # -- disruptions --------------------------------------------------------

    def do_one(self) -> Optional[Disruption]:
        kind = self.rng.choice(self.kinds)
        fn = {
            "kill-kubelet": self._kill_kubelet,
            "restart-kubelet": self._restart_kubelet,
            "delete-pod": self._delete_pod,
        }[kind]
        d = fn()
        if d is not None:
            self.history.append(d)
        return d

    def _kill_kubelet(self) -> Optional[Disruption]:
        hollow = self.cluster.hollow
        if hollow is None:
            return None
        alive = [kl for kl in hollow.kubelets if kl not in self._dead]
        if len(alive) <= 1:
            return None  # always leave one node standing
        victim = self.rng.choice(alive)
        victim.stop()
        self._dead.append(victim)
        return Disruption("kill-kubelet", victim.config.node_name)

    def _restart_kubelet(self) -> Optional[Disruption]:
        if not self._dead:
            return None
        kl = self._dead.pop(self.rng.randrange(len(self._dead)))
        # a restarted kubelet is a FRESH process over the same node name
        # and runtime (kubelet restart reconciles from CRI via PLEG)
        from ..kubelet.kubelet import Kubelet

        fresh = Kubelet(
            self.cluster.hollow.client,
            self.cluster.hollow.factory,
            config=kl.config,
            runtime=kl.runtime,
        )
        idx = self.cluster.hollow.kubelets.index(kl)
        self.cluster.hollow.kubelets[idx] = fresh
        fresh.run()
        return Disruption("restart-kubelet", kl.config.node_name)

    def _delete_pod(self) -> Optional[Disruption]:
        pods, _ = self.cluster.client.pods.list(namespace="default")
        candidates = [p for p in pods if p.metadata.deletion_timestamp is None]
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        self.cluster.client.pods.delete(
            victim.metadata.name, victim.metadata.namespace
        )
        return Disruption(
            "delete-pod", f"{victim.metadata.namespace}/{victim.metadata.name}"
        )

    # -- assertions ---------------------------------------------------------

    def restart_all_dead(self) -> None:
        while self._dead:
            self._restart_kubelet()
