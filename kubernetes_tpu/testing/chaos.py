"""Chaosmonkey: periodic fault injection against a running cluster.

Reference: test/e2e/chaosmonkey/chaosmonkey.go:48 — a chaosmonkey Do()s
disruptions while registered tests run; the reboot/disruptive e2e suites
use it to prove the control plane re-converges. Here the disruptions are
the ones a hollow cluster can suffer: kubelet kill (node death), kubelet
restart (recovery), random pod deletion (workload churn), and — on
clusters wired for it — control-plane crashes: `crash-apiserver` drops
the durable store to its on-disk state mid-churn (SIGKILL-equivalent;
every acknowledged write survives, every live watch dies and reflectors
re-list) and `crash-controller` kills one supervised controller loop so
the supervisor must restart it with backoff. The crash kinds are opt-in
via `disruptions=` (they no-op on clusters without a DurableKVStore /
Supervisor). Each disruption is recorded so tests can assert recovery
against the actual injection history.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass
class Disruption:
    kind: str  # kill-kubelet | restart-kubelet | delete-pod | crash-*
    target: str
    at: float = field(default_factory=time.time)


#: the control-plane crash kinds (opt-in: pass via `disruptions=`)
CRASH_KINDS = ("crash-apiserver", "crash-controller")

#: the device/scheduler fault kinds (opt-in): `wedge-device` arms one
#: dispatch-level fault (raise / NaN harvest / wedged wait) on the TPU
#: backend's FaultInjector; `crash-scheduler` kills one pipeline worker
#: thread (scheduling loop or completion worker); `overload` makes the
#: host transiently SLOW — a completion-worker stall wave or a synthetic
#: event burst — so the overload monitor's shed→restore cycle gets
#: exercised (the endurance soak's signature disruption). All no-op on
#: clusters without a TPU-backed scheduler.
FAULT_KINDS = ("wedge-device", "crash-scheduler", "overload")

#: the scheduler-failover kinds (opt-in): `partition-scheduler` cuts the
#: current leader off from the store — its lease renews fail, the
#: self-fence margin demotes it, and a standby adopts the lease while
#: the zombie's straggler writes bounce off the fencing precondition;
#: `failover-scheduler` is the graceful form — the leader abdicates
#: (vacates the lease + cools down) so a warm standby wins
#: deterministically. Both no-op on clusters without leader election.
FAILOVER_KINDS = ("partition-scheduler", "failover-scheduler")

#: the gang-scheduling kinds (opt-in): `kill-gang-member` deletes one
#: member of a live gang — the Coscheduling rollback protocol must
#: unwind the whole waiting wave (never a prefix) and, once a
#: replacement lands, re-complete the gang; `gang-burst` submits a
#: fresh burst of gang pods so admission waves keep forming mid-chaos.
#: Both no-op on clusters without gang pods / without a default
#: namespace to burst into.
GANG_KINDS = ("kill-gang-member", "gang-burst")


class ChaosMonkey:
    def __init__(
        self,
        cluster,  # kubernetes_tpu.cluster.Cluster (needs .hollow/.client)
        period: float = 1.0,
        rng: Optional[random.Random] = None,
        disruptions: Optional[List[str]] = None,
    ):
        self.cluster = cluster
        self.period = period
        self.rng = rng or random.Random(0)
        self.kinds = disruptions or ["kill-kubelet", "restart-kubelet", "delete-pod"]
        self.history: List[Disruption] = []
        self._dead: List = []  # kubelets killed and not yet restarted
        self._crashed_controllers: List[str] = []  # awaiting supervisor
        self._partitioned: List = []  # electors cut off from the store
        self._burst_seq = 0  # gang-burst group-name sequence
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.do_one()
            except Exception:  # noqa: BLE001 — chaos must not crash the test
                pass

    # -- disruptions --------------------------------------------------------

    def do_one(self, kind: Optional[str] = None) -> Optional[Disruption]:
        kind = kind or self.rng.choice(self.kinds)
        fn = {
            "kill-kubelet": self._kill_kubelet,
            "restart-kubelet": self._restart_kubelet,
            "delete-pod": self._delete_pod,
            "crash-apiserver": self._crash_apiserver,
            "crash-controller": self._crash_controller,
            "wedge-device": self._wedge_device,
            "crash-scheduler": self._crash_scheduler,
            "overload": self._overload,
            "partition-scheduler": self._partition_scheduler,
            "failover-scheduler": self._failover_scheduler,
            "kill-gang-member": self._kill_gang_member,
            "gang-burst": self._gang_burst,
        }[kind]
        d = fn()
        if d is not None:
            self.history.append(d)
        return d

    def _kill_kubelet(self) -> Optional[Disruption]:
        hollow = self.cluster.hollow
        if hollow is None:
            return None
        alive = [kl for kl in hollow.kubelets if kl not in self._dead]
        if len(alive) <= 1:
            return None  # always leave one node standing
        victim = self.rng.choice(alive)
        victim.stop()
        self._dead.append(victim)
        return Disruption("kill-kubelet", victim.config.node_name)

    def _restart_kubelet(self) -> Optional[Disruption]:
        if not self._dead:
            return None
        kl = self._dead.pop(self.rng.randrange(len(self._dead)))
        # a restarted kubelet is a FRESH process over the same node name
        # and runtime (kubelet restart reconciles from CRI via PLEG)
        from ..kubelet.kubelet import Kubelet

        fresh = Kubelet(
            self.cluster.hollow.client,
            self.cluster.hollow.factory,
            config=kl.config,
            runtime=kl.runtime,
        )
        idx = self.cluster.hollow.kubelets.index(kl)
        self.cluster.hollow.kubelets[idx] = fresh
        fresh.run()
        return Disruption("restart-kubelet", kl.config.node_name)

    def _delete_pod(self) -> Optional[Disruption]:
        pods, _ = self.cluster.client.pods.list(namespace="default")
        candidates = [p for p in pods if p.metadata.deletion_timestamp is None]
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        self.cluster.client.pods.delete(
            victim.metadata.name, victim.metadata.namespace
        )
        return Disruption(
            "delete-pod", f"{victim.metadata.namespace}/{victim.metadata.name}"
        )

    def _crash_apiserver(self) -> Optional[Disruption]:
        """SIGKILL-equivalent on the control plane's store: drop to disk
        state mid-churn (sometimes with a torn final record) and recover.
        Acknowledged writes survive; live watches die and every reflector
        re-lists. No-op unless the cluster runs a DurableKVStore."""
        store = getattr(getattr(self.cluster, "api", None), "store", None)
        if store is None or not hasattr(store, "crash"):
            return None
        store.crash(torn=bool(self.rng.getrandbits(1)))
        return Disruption("crash-apiserver", "apiserver")

    def _crash_controller(self) -> Optional[Disruption]:
        """Kill one supervised controller loop; the supervisor must
        restart it with capped backoff while the rest keep running.
        No-op unless the controller manager runs a Supervisor."""
        sup = getattr(getattr(self.cluster, "kcm", None), "supervisor", None)
        if sup is None:
            return None
        candidates = [n for n in sup.names() if sup.running(n)]
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        sup.crash(victim)
        self._crashed_controllers.append(victim)
        return Disruption("crash-controller", victim)

    def _fault_injector(self):
        """The scheduler's FaultInjector, installing one on first use.
        None when the cluster has no TPU-backed scheduler (the fault
        kinds then no-op, like the crash kinds on non-durable stores)."""
        sched = getattr(self.cluster, "scheduler", None)
        if sched is None or getattr(sched, "tpu", None) is None:
            return None
        inj = getattr(sched, "faults", None)
        if inj is None:
            from .faults import FaultInjector

            inj = FaultInjector()
            sched.install_fault_injector(inj)
        return inj

    def _wedge_device(self) -> Optional[Disruption]:
        """One device-level fault on the next dispatch: an XLA launch
        raise, a garbage (NaN/saturated) harvest payload, or a wedged
        wait that only the dispatch watchdog ends. The backend must
        detect it, retry with a rebuilt session, and keep every pod
        (fault-parity: same bound set as a clean run)."""
        inj = self._fault_injector()
        if inj is None:
            return None
        kind = self.rng.choice(("raise-dispatch", "nan-harvest", "wedge-wait"))
        inj.arm(kind, shots=1)
        return Disruption("wedge-device", kind)

    def _crash_scheduler(self) -> Optional[Disruption]:
        """Kill one scheduling-pipeline worker thread (the scheduling
        loop or the completion worker); the in-process supervision must
        drain the in-flight FIFO back to the queue and restart it."""
        inj = self._fault_injector()
        if inj is None:
            return None
        kind = self.rng.choice(("kill-scheduler", "kill-completion"))
        inj.arm(kind, shots=1)
        return Disruption("crash-scheduler", kind)

    def _overload(self) -> Optional[Disruption]:
        """Make the host transiently SLOW (not dead): either arm a wave
        of completion-worker stalls — the FIFO ages, the overload
        monitor must shed optional work and restore once the wave passes
        — or fire a synthetic event burst (no-op annotation bumps on a
        slab of pods) that floods every informer/watcher with MODIFIED
        events, exercising queue depth and the wire's slow-consumer
        path. Placements must be untouched either way."""
        inj = self._fault_injector()
        if inj is None:
            return None
        if self.rng.random() < 0.7:
            # a wave of stalled batches, long enough to out-dwell the
            # monitor's shed threshold
            inj.arm("stall-completion", shots=6)
            return Disruption("overload", "stall-completion")
        pods, _ = self.cluster.client.pods.list(namespace="default")
        victims = [p for p in pods if p.metadata.deletion_timestamp is None]
        self.rng.shuffle(victims)
        burst = 0
        for p in victims[:50]:
            ann = dict(p.metadata.annotations or {})
            ann["chaos/overload-burst"] = str(time.time())
            p.metadata.annotations = ann
            try:
                self.cluster.client.pods.update(p)
                burst += 1
            except Exception:  # noqa: BLE001 — racing deletes are fine
                pass
        return Disruption("overload", f"event-burst:{burst}")

    def _kill_gang_member(self) -> Optional[Disruption]:
        """Delete one member of a live gang (waiting or bound — the rng
        doesn't care, and neither may the protocol): a waiting member's
        deletion must roll the WHOLE wave back so no sibling camps on
        capacity; a bound member's deletion leaves its siblings bound
        (still a legal all-bound-minus-departed state) and the owner's
        replacement re-completes the gang off the reserved index. Either
        way the gang may never sit torn — the GangIntegrityChecker
        holds the line. No-op when no gang pods exist."""
        from ..scheduler.plugins.coscheduling import pod_group

        pods, _ = self.cluster.client.pods.list(namespace="default")
        candidates = []
        for p in pods:
            if p.metadata.deletion_timestamp is not None:
                continue
            group, min_available = pod_group(p)
            if group and min_available > 1:
                candidates.append(p)
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        self.cluster.client.pods.delete(
            victim.metadata.name, victim.metadata.namespace
        )
        return Disruption(
            "kill-gang-member",
            f"{victim.metadata.namespace}/{victim.metadata.name}",
        )

    #: pods per injected gang-burst gang (drills override per shape)
    gang_burst_size = 4
    #: cpu request per burst member — small enough that a burst gang is
    #: placeable on a drill-sized cluster, large enough to contend
    gang_burst_cpu = "10m"

    def _gang_burst(self) -> Optional[Disruption]:
        """Submit one fresh gang (gang_burst_size pods sharing a new
        group, min-available == size) so admission waves keep forming
        mid-chaos — gang identity rides annotations, exactly like the
        perf harness, so the burst never perturbs template hoisting."""
        from ..api import types as v1
        from ..scheduler.plugins.coscheduling import (
            GROUP_LABEL,
            MIN_AVAILABLE_LABEL,
        )

        seq = self._burst_seq
        self._burst_seq += 1
        group = f"chaos-gang-{seq}"
        k = self.gang_burst_size
        for i in range(k):
            pod = v1.Pod(
                metadata=v1.ObjectMeta(
                    name=f"{group}-{i}",
                    namespace="default",
                    annotations={
                        GROUP_LABEL: group,
                        MIN_AVAILABLE_LABEL: str(k),
                    },
                ),
                spec=v1.PodSpec(containers=[v1.Container(
                    name="c", image="img:1",
                    resources=v1.ResourceRequirements(
                        requests={"cpu": self.gang_burst_cpu}),
                )]),
            )
            try:
                self.cluster.client.pods.create(pod)
            except Exception:  # noqa: BLE001 — name races with a prior burst
                return None
        return Disruption("gang-burst", f"{group} x{k}")

    def _electing_schedulers(self) -> List:
        """Every scheduler instance with leader election armed; supports
        both the multi-scheduler cluster (`.schedulers`) and a bare
        single-scheduler one."""
        scheds = getattr(self.cluster, "schedulers", None)
        if not scheds:
            sole = getattr(self.cluster, "scheduler", None)
            scheds = [sole] if sole is not None else []
        return [s for s in scheds if getattr(s, "elector", None) is not None]

    def _leader(self):
        for s in self._electing_schedulers():
            if s.elector.is_leader.is_set():
                return s
        return None

    def _partition_scheduler(self) -> Optional[Disruption]:
        """Netsplit the current leader from the store: heal any previous
        partition first (both instances partitioned means nobody can
        lead), then cut the leader off — its renews fail, the self-fence
        margin demotes it strictly before a standby's adoption window
        opens, and any straggler write it still has in flight carries a
        dead epoch the apiserver rejects (FenceExpired). No-op without
        at least two electing schedulers."""
        if len(self._electing_schedulers()) < 2:
            return None
        while self._partitioned:
            self._partitioned.pop().partitioned = False
        leader = self._leader()
        if leader is None:
            return None
        leader.elector.partitioned = True
        self._partitioned.append(leader.elector)
        return Disruption("partition-scheduler", leader.elector.cfg.identity)

    def _failover_scheduler(self) -> Optional[Disruption]:
        """Graceful leader handoff: the active instance abdicates —
        vacates the lease record and sits out the next race — so a warm
        standby adopts (epoch bump, reconcile, resume) while the old
        leader demotes through the same pause-and-drain path a crash
        would use. No-op without at least two electing schedulers."""
        if len(self._electing_schedulers()) < 2:
            return None
        leader = self._leader()
        if leader is None:
            return None
        # sit out long enough that the standby reliably wins the race
        leader.elector.abdicate(cooldown=2.0 * leader.elector.cfg.lease_duration)
        return Disruption("failover-scheduler", leader.elector.cfg.identity)

    # -- assertions ---------------------------------------------------------

    def restart_all_dead(self, timeout: float = 30.0) -> None:
        """End the experiment with every component back: kubelets
        restarted (fresh process over the same node), crashed controller
        loops re-running under their supervisor, the apiserver store
        healthy (crash() recovers in place, so it already is), and any
        still-armed overload stall wave disarmed so the monitor's
        restore path can run."""
        sched = getattr(self.cluster, "scheduler", None)
        inj = getattr(sched, "faults", None) if sched is not None else None
        if inj is not None:
            inj.disarm("stall-completion")
        while self._dead:
            self._restart_kubelet()
        sup = getattr(getattr(self.cluster, "kcm", None), "supervisor", None)
        while self._crashed_controllers:
            name = self._crashed_controllers.pop()
            if sup is not None and not sup.wait_running(name, timeout):
                # a recovery barrier that shrugs is worse than none: the
                # test would proceed green with a controller still down
                raise RuntimeError(
                    f"controller {name} not restarted within {timeout}s "
                    f"(restarts={sup.restart_count(name)})"
                )
        # heal scheduler netsplits and wait for a leader to re-emerge —
        # the same no-shrug rule: converging with no active scheduler
        # would pass every per-pod check on a cluster that schedules
        # nothing ever again
        while self._partitioned:
            self._partitioned.pop().partitioned = False
        if self._electing_schedulers():
            deadline = time.time() + timeout
            while time.time() < deadline:
                if self._leader() is not None:
                    return
                time.sleep(0.05)
            raise RuntimeError(
                f"no scheduler re-acquired the leader lease within {timeout}s"
            )
