"""Deterministic fault seam for the TPU scheduling pipeline.

The FaultInjector is the drill hook the device-fault-tolerance subsystem
is tested through (the reference proves re-convergence with chaosmonkey-
driven disruptive e2e suites; a wedged XLA wait or a NaN harvest needs
the same treatment but cannot be produced by killing kubelets). The
scheduler and TPU backend hold an OPTIONAL `faults` attribute and call
the hooks below at the natural fault points; production code never
imports this module — the seam is duck-typed, `None` means no injection.

Kinds:

  raise-dispatch   the next device dispatch raises (XLA launch error)
  raise-whatif     the next preemption what-if launch raises — the
                   planner must fall one rung (device -> fast/oracle)
                   with no victim double-claim and no live-session
                   invalidation (the PR-7 drill)
  nan-harvest      the next harvested payload is corrupted (NaN floats /
                   saturated ints) BEFORE decode — must be caught by the
                   backend's finite/in-range validation guard
  wedge-wait       device waits report not-ready until the dispatch
                   watchdog fires (hung collective / preempted chip)
  kill-scheduler   the scheduling loop thread dies at its next iteration
  kill-completion  the completion worker dies before its next batch
  stall-completion the completion worker sleeps `stall_delay` seconds
                   before its next batch — a transient SLOW host (GC
                   pause, noisy neighbor, audit tax), not a dead one.
                   The overload monitor must see the FIFO age climb,
                   shed optional work, and restore once shots run out
                   (the ChaosMonkey "overload" disruption's engine)

Faults are armed with a shot count (`-1` = until disarm) and optionally a
`min_rung` (scheduler/degradation.py rung constants): a pallas-only
Mosaic bug is modeled as `min_rung=RUNG_PALLAS` — dispatches and probes
at or above that rung fault, lower rungs run clean, which is exactly the
shape the degradation ladder must survive. `injected` counts every fired
fault per kind; tests assert recovery against it (the ground-truth role
plan.injected played for the HTTP fault plan).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

KINDS = (
    "raise-dispatch",
    "raise-whatif",
    "nan-harvest",
    "wedge-wait",
    "kill-scheduler",
    "kill-completion",
    "stall-completion",
)


class InjectedFault(RuntimeError):
    """Raised by on_dispatch when raise-dispatch is armed; the backend
    treats it like any other device-path exception."""


class _Armed:
    __slots__ = ("shots", "min_rung")

    def __init__(self, shots: int, min_rung: Optional[int]):
        self.shots = shots
        self.min_rung = min_rung


class FaultInjector:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, _Armed] = {}
        self.injected: Dict[str, int] = {}
        # per-batch sleep while stall-completion is armed (seconds)
        self.stall_delay = 0.25

    # -- arming ------------------------------------------------------------

    def arm(self, kind: str, shots: int = 1,
            min_rung: Optional[int] = None) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if min_rung is not None and kind == "wedge-wait":
            # a wedged wait has no dispatch-rung context (wedge_active()
            # is polled from inside the wait loop), so a rung-filtered
            # wedge would wedge every rung but never consume its shot —
            # a permanent outage masquerading as a transient fault
            raise ValueError("wedge-wait does not support min_rung")
        with self._lock:
            self._armed[kind] = _Armed(shots, min_rung)

    def disarm(self, kind: Optional[str] = None) -> None:
        with self._lock:
            if kind is None:
                self._armed.clear()
            else:
                self._armed.pop(kind, None)

    def armed(self, kind: str) -> bool:
        with self._lock:
            return kind in self._armed

    def _take(self, kind: str, rung: Optional[int] = None) -> bool:
        """Consume one shot of `kind` if armed (and the rung filter
        passes); counts the injection."""
        with self._lock:
            a = self._armed.get(kind)
            if a is None:
                return False
            if a.min_rung is not None and (rung is None or rung < a.min_rung):
                return False
            if a.shots > 0:
                a.shots -= 1
                if a.shots == 0:
                    del self._armed[kind]
            self.injected[kind] = self.injected.get(kind, 0) + 1
            return True

    # -- hooks (called by tpu_backend / scheduler) -------------------------

    def on_dispatch(self, rung: Optional[int] = None,
                    probe: bool = False) -> None:
        """Called right before every device dispatch (and every ladder
        probe, with the rung the probe vouches for)."""
        if self._take("raise-dispatch", rung):
            raise InjectedFault(
                f"injected dispatch failure (probe={probe}, rung={rung})"
            )

    def on_whatif(self) -> None:
        """Called right before every preemption what-if launch
        (tpu_backend.check_whatif_fault)."""
        if self._take("raise-whatif"):
            raise InjectedFault("injected what-if launch failure")

    def corrupt_harvest(self, ys, rung: Optional[int] = None):
        """Possibly corrupt one harvested payload: float leaves -> NaN,
        int leaves -> dtype max (out of any node-index range). Returns a
        corrupted COPY; the original device arrays are untouched."""
        if not self._take("nan-harvest", rung):
            return ys
        if not isinstance(ys, dict):
            return ys
        bad = dict(ys)
        for k, v in ys.items():
            if np.ndim(v) == 0 and not hasattr(v, "dtype"):
                continue  # host scalars ("n", "_b_real") steer decode
            try:
                a = np.asarray(v)
            except Exception:  # noqa: BLE001 — leave non-arrays alone
                continue
            if a.dtype.kind == "f":
                bad[k] = np.full_like(a, np.nan)
            elif a.dtype.kind in "iu":
                bad[k] = np.full_like(a, np.iinfo(a.dtype).max)
        return bad

    def wedge_active(self) -> bool:
        """True while wedge-wait is armed: device waits must report
        not-ready (the watchdog, not this hook, ends the wedge). Does not
        consume a shot — one shot covers one full wedged wait."""
        with self._lock:
            return "wedge-wait" in self._armed

    def consume_wedge(self) -> None:
        """The wedged wait hit its watchdog: the shot fired; release it
        so the retry path finds a responsive device. (arm() guarantees
        wedge-wait carries no rung filter, so _take consumes cleanly.)"""
        self._take("wedge-wait")

    def take_kill(self, worker: str) -> bool:
        """worker = "scheduler" | "completion"; True means the caller
        must die now (it raises scheduler.WorkerKilled)."""
        return self._take(f"kill-{worker}")

    def on_completion(self) -> None:
        """Called at the top of every batch completion. While
        stall-completion is armed the worker sleeps stall_delay per
        batch (one shot = one stalled batch) — the synthetic form of a
        host that is ALIVE but too slow, which is what the overload
        monitor sheds against."""
        if self._take("stall-completion"):
            import time

            time.sleep(self.stall_delay)


class GangIntegrityChecker:
    """Gang atomicity monitor for fault drills: a gang is always
    all-bound, all-waiting, or all-rolled-back — never TORN (some live
    members holding a binding while sibling members sit unbound) for
    longer than `grace` seconds. Transient partials
    are legal and expected: a committed wave binds as one batch but the
    apiserver echoes its bindings one watch event at a time, and a
    killed member's ReplicaSet replacement takes a moment to reserve and
    re-complete the gang (bound siblings stay in the Coscheduling
    reserved index, so the replacement counts them and the gang heals).
    The grace window absorbs both; a gang that STAYS partial past it is
    exactly the torn state the permit/rollback protocol exists to
    prevent. Attach to any pods informer; read `violations` after the
    drill and assert `partial_gangs()` is empty once converged."""

    def __init__(self, grace: float = 15.0):
        self.grace = grace
        self._lock = threading.Lock()
        # (namespace, group) -> {pod key: bound?} over LIVE members
        # (deleting/deleted members left the gang — they are the
        # rolled-back third of the invariant, not a partial state)
        self._members: Dict[str, Dict[str, bool]] = {}
        self._min_avail: Dict[str, int] = {}
        self._partial_since: Dict[str, float] = {}
        self._flagged: set = set()
        self.violations = []

    def attach(self, pods_informer) -> "GangIntegrityChecker":
        from ..client.informer import EventHandler

        pods_informer.add_event_handler(EventHandler(
            on_add=self._on_add,
            on_update=self._on_update,
            on_delete=self._on_delete,
        ))
        return self

    @staticmethod
    def _gang_of(pod):
        from ..scheduler.plugins.coscheduling import pod_group

        group, min_available = pod_group(pod)
        if not group or min_available <= 1:
            return None, 0
        return (pod.metadata.namespace, group), min_available

    def _on_add(self, pod) -> None:
        self._observe(pod)

    def _on_update(self, old, new) -> None:
        self._observe(new)

    def _on_delete(self, pod) -> None:
        gk, _ = self._gang_of(pod)
        if gk is None:
            return
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            members = self._members.get(gk)
            if members is not None:
                members.pop(key, None)
                if not members:
                    self._members.pop(gk, None)
                    self._min_avail.pop(gk, None)
            self._scan_locked()

    def _observe(self, pod) -> None:
        gk, min_available = self._gang_of(pod)
        if gk is None:
            return
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        deleting = pod.metadata.deletion_timestamp is not None
        with self._lock:
            if deleting:
                members = self._members.get(gk)
                if members is not None:
                    members.pop(key, None)
            else:
                self._members.setdefault(gk, {})[key] = bool(
                    pod.spec.node_name)
                self._min_avail[gk] = min_available
            self._scan_locked()

    def _scan_locked(self, now: Optional[float] = None) -> None:
        import time

        now = time.monotonic() if now is None else now
        partial = self._partial_locked()
        for gk in list(self._partial_since):
            if gk not in partial:
                del self._partial_since[gk]
                self._flagged.discard(gk)  # episode over; re-flaggable
        for gk, (bound, live, need) in partial.items():
            since = self._partial_since.setdefault(gk, now)
            if now - since > self.grace and gk not in self._flagged:
                self._flagged.add(gk)
                self.violations.append(
                    f"{gk[0]}/{gk[1]}: partial gang for "
                    f"{now - since:.1f}s ({bound}/{need} bound, "
                    f"{live} live members)"
                )

    def _partial_locked(self) -> Dict:
        # torn = some live members bound while others are not: the state
        # the all-or-nothing permit protocol must never leave standing.
        # A gang whose bound membership merely SHRANK below min-available
        # (an external delete with no owner to replace the member) is
        # all-bound-though-shrunk, not torn — the scheduler admitted it
        # atomically and Kubernetes semantics keep bound pods bound.
        out = {}
        for gk, members in self._members.items():
            need = self._min_avail.get(gk, 0)
            if need <= 1 or not members:
                continue
            bound = sum(1 for b in members.values() if b)
            if 0 < bound < len(members):
                out[gk] = (bound, len(members), need)
        return out

    def partial_gangs(self) -> Dict:
        """Current partial gangs: {(ns, group): (bound, live, need)} —
        must be empty once the cluster has converged (the drill's final
        zero-partial-gangs gate, grace-independent)."""
        with self._lock:
            self._scan_locked()
            return dict(self._partial_locked())


class BindIntegrityChecker:
    """Double-bind detector for fault drills: a pod whose spec.nodeName
    moves from one non-empty node to a DIFFERENT non-empty node was bound
    twice — the invariant the fault-tolerant pipeline must never break
    (the apiserver's binding endpoint Conflict-rejects the second bind,
    so a violation surfacing here means a pod object was re-created or
    rebound around that guard). Attach to any pods informer; read
    `violations` after the drill."""

    def __init__(self):
        self._lock = threading.Lock()
        self.violations = []

    def attach(self, pods_informer) -> "BindIntegrityChecker":
        from ..client.informer import EventHandler

        pods_informer.add_event_handler(
            EventHandler(on_update=self._on_update))
        return self

    def _on_update(self, old, new) -> None:
        o = old.spec.node_name
        n = new.spec.node_name
        if o and n and o != n:
            with self._lock:
                self.violations.append(
                    f"{new.metadata.namespace}/{new.metadata.name}: "
                    f"rebound {o} -> {n}"
                )
