"""Deterministic fault seam for the TPU scheduling pipeline.

The FaultInjector is the drill hook the device-fault-tolerance subsystem
is tested through (the reference proves re-convergence with chaosmonkey-
driven disruptive e2e suites; a wedged XLA wait or a NaN harvest needs
the same treatment but cannot be produced by killing kubelets). The
scheduler and TPU backend hold an OPTIONAL `faults` attribute and call
the hooks below at the natural fault points; production code never
imports this module — the seam is duck-typed, `None` means no injection.

Kinds:

  raise-dispatch   the next device dispatch raises (XLA launch error)
  raise-whatif     the next preemption what-if launch raises — the
                   planner must fall one rung (device -> fast/oracle)
                   with no victim double-claim and no live-session
                   invalidation (the PR-7 drill)
  nan-harvest      the next harvested payload is corrupted (NaN floats /
                   saturated ints) BEFORE decode — must be caught by the
                   backend's finite/in-range validation guard
  wedge-wait       device waits report not-ready until the dispatch
                   watchdog fires (hung collective / preempted chip)
  kill-scheduler   the scheduling loop thread dies at its next iteration
  kill-completion  the completion worker dies before its next batch
  stall-completion the completion worker sleeps `stall_delay` seconds
                   before its next batch — a transient SLOW host (GC
                   pause, noisy neighbor, audit tax), not a dead one.
                   The overload monitor must see the FIFO age climb,
                   shed optional work, and restore once shots run out
                   (the ChaosMonkey "overload" disruption's engine)

Faults are armed with a shot count (`-1` = until disarm) and optionally a
`min_rung` (scheduler/degradation.py rung constants): a pallas-only
Mosaic bug is modeled as `min_rung=RUNG_PALLAS` — dispatches and probes
at or above that rung fault, lower rungs run clean, which is exactly the
shape the degradation ladder must survive. `injected` counts every fired
fault per kind; tests assert recovery against it (the ground-truth role
plan.injected played for the HTTP fault plan).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

KINDS = (
    "raise-dispatch",
    "raise-whatif",
    "nan-harvest",
    "wedge-wait",
    "kill-scheduler",
    "kill-completion",
    "stall-completion",
)


class InjectedFault(RuntimeError):
    """Raised by on_dispatch when raise-dispatch is armed; the backend
    treats it like any other device-path exception."""


class _Armed:
    __slots__ = ("shots", "min_rung")

    def __init__(self, shots: int, min_rung: Optional[int]):
        self.shots = shots
        self.min_rung = min_rung


class FaultInjector:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, _Armed] = {}
        self.injected: Dict[str, int] = {}
        # per-batch sleep while stall-completion is armed (seconds)
        self.stall_delay = 0.25

    # -- arming ------------------------------------------------------------

    def arm(self, kind: str, shots: int = 1,
            min_rung: Optional[int] = None) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if min_rung is not None and kind == "wedge-wait":
            # a wedged wait has no dispatch-rung context (wedge_active()
            # is polled from inside the wait loop), so a rung-filtered
            # wedge would wedge every rung but never consume its shot —
            # a permanent outage masquerading as a transient fault
            raise ValueError("wedge-wait does not support min_rung")
        with self._lock:
            self._armed[kind] = _Armed(shots, min_rung)

    def disarm(self, kind: Optional[str] = None) -> None:
        with self._lock:
            if kind is None:
                self._armed.clear()
            else:
                self._armed.pop(kind, None)

    def armed(self, kind: str) -> bool:
        with self._lock:
            return kind in self._armed

    def _take(self, kind: str, rung: Optional[int] = None) -> bool:
        """Consume one shot of `kind` if armed (and the rung filter
        passes); counts the injection."""
        with self._lock:
            a = self._armed.get(kind)
            if a is None:
                return False
            if a.min_rung is not None and (rung is None or rung < a.min_rung):
                return False
            if a.shots > 0:
                a.shots -= 1
                if a.shots == 0:
                    del self._armed[kind]
            self.injected[kind] = self.injected.get(kind, 0) + 1
            return True

    # -- hooks (called by tpu_backend / scheduler) -------------------------

    def on_dispatch(self, rung: Optional[int] = None,
                    probe: bool = False) -> None:
        """Called right before every device dispatch (and every ladder
        probe, with the rung the probe vouches for)."""
        if self._take("raise-dispatch", rung):
            raise InjectedFault(
                f"injected dispatch failure (probe={probe}, rung={rung})"
            )

    def on_whatif(self) -> None:
        """Called right before every preemption what-if launch
        (tpu_backend.check_whatif_fault)."""
        if self._take("raise-whatif"):
            raise InjectedFault("injected what-if launch failure")

    def corrupt_harvest(self, ys, rung: Optional[int] = None):
        """Possibly corrupt one harvested payload: float leaves -> NaN,
        int leaves -> dtype max (out of any node-index range). Returns a
        corrupted COPY; the original device arrays are untouched."""
        if not self._take("nan-harvest", rung):
            return ys
        if not isinstance(ys, dict):
            return ys
        bad = dict(ys)
        for k, v in ys.items():
            if np.ndim(v) == 0 and not hasattr(v, "dtype"):
                continue  # host scalars ("n", "_b_real") steer decode
            try:
                a = np.asarray(v)
            except Exception:  # noqa: BLE001 — leave non-arrays alone
                continue
            if a.dtype.kind == "f":
                bad[k] = np.full_like(a, np.nan)
            elif a.dtype.kind in "iu":
                bad[k] = np.full_like(a, np.iinfo(a.dtype).max)
        return bad

    def wedge_active(self) -> bool:
        """True while wedge-wait is armed: device waits must report
        not-ready (the watchdog, not this hook, ends the wedge). Does not
        consume a shot — one shot covers one full wedged wait."""
        with self._lock:
            return "wedge-wait" in self._armed

    def consume_wedge(self) -> None:
        """The wedged wait hit its watchdog: the shot fired; release it
        so the retry path finds a responsive device. (arm() guarantees
        wedge-wait carries no rung filter, so _take consumes cleanly.)"""
        self._take("wedge-wait")

    def take_kill(self, worker: str) -> bool:
        """worker = "scheduler" | "completion"; True means the caller
        must die now (it raises scheduler.WorkerKilled)."""
        return self._take(f"kill-{worker}")

    def on_completion(self) -> None:
        """Called at the top of every batch completion. While
        stall-completion is armed the worker sleeps stall_delay per
        batch (one shot = one stalled batch) — the synthetic form of a
        host that is ALIVE but too slow, which is what the overload
        monitor sheds against."""
        if self._take("stall-completion"):
            import time

            time.sleep(self.stall_delay)


class BindIntegrityChecker:
    """Double-bind detector for fault drills: a pod whose spec.nodeName
    moves from one non-empty node to a DIFFERENT non-empty node was bound
    twice — the invariant the fault-tolerant pipeline must never break
    (the apiserver's binding endpoint Conflict-rejects the second bind,
    so a violation surfacing here means a pod object was re-created or
    rebound around that guard). Attach to any pods informer; read
    `violations` after the drill."""

    def __init__(self):
        self._lock = threading.Lock()
        self.violations = []

    def attach(self, pods_informer) -> "BindIntegrityChecker":
        from ..client.informer import EventHandler

        pods_informer.add_event_handler(
            EventHandler(on_update=self._on_update))
        return self

    def _on_update(self, old, new) -> None:
        o = old.spec.node_name
        n = new.spec.node_name
        if o and n and o != n:
            with self._lock:
                self.violations.append(
                    f"{new.metadata.namespace}/{new.metadata.name}: "
                    f"rebound {o} -> {n}"
                )
