"""Dynamic lock-order sentinel: the runtime twin of analysis/lock_order.

The static checker proves the *written* with-nesting is cycle-free;
this sentinel asserts the *observed* acquisition order is, under real
chaos/endurance concurrency. Install patches ``threading.Lock`` /
``threading.RLock`` so every lock created afterwards is a tracked
wrapper: each acquire records (held -> acquired) edges on a per-thread
held stack, labelled by the lock's creation site. At teardown
``assert_cycle_free()`` DFS-checks the edge graph; a cycle means two
threads can take the same pair of locks in opposite orders — a
deadlock that plain soak timing may never hit.

Overhead is one dict update per acquire — negligible next to the soak
itself. Use::

    with lock_order_sentinel() as s:
        ...  # construct Cluster, run chaos
    # exiting uninstalls, then asserts the observed graph is acyclic

Locks created BEFORE install() are untracked (module-level locks from
import time); the chaos suites build their Cluster inside the sentinel
so everything that matters is covered.

``threading.Condition`` on a tracked lock stays correct either way: a
Lock-backed wrapper has no ``_release_save``/``_acquire_restore``/
``_is_owned`` (delegation raises AttributeError), so Condition falls
back to plain ``acquire``/``release`` through the wrapper and wait()
keeps the held stack balanced; an RLock-backed wrapper delegates those
three to the real RLock, whose ownership semantics Condition needs
(the fallback ``_is_owned`` probe mis-answers on re-entrant locks).
During an RLock wait() the label stays on the waiter's stack — the
thread is blocked, so no false edges can be recorded from it.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from typing import Dict, List, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class _TrackedLock:
    """Delegating wrapper around a real Lock/RLock with order tracking."""

    __slots__ = ("_ktpu_inner", "_ktpu_label", "_ktpu_sentinel")

    def __init__(self, inner, label: str, sentinel: "LockOrderSentinel"):
        object.__setattr__(self, "_ktpu_inner", inner)
        object.__setattr__(self, "_ktpu_label", label)
        object.__setattr__(self, "_ktpu_sentinel", sentinel)

    def acquire(self, *args, **kwargs):
        got = self._ktpu_inner.acquire(*args, **kwargs)
        if got:
            self._ktpu_sentinel._note_acquire(self._ktpu_label)
        return got

    def release(self):
        self._ktpu_sentinel._note_release(self._ktpu_label)
        self._ktpu_inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self):
        return self._ktpu_inner.locked()

    def __getattr__(self, name):
        return getattr(self._ktpu_inner, name)

    def __repr__(self):
        return f"<TrackedLock {self._ktpu_label} of {self._ktpu_inner!r}>"


class LockOrderSentinel:
    """Records the global lock-acquisition-order graph while installed."""

    def __init__(self):
        # (held_label, acquired_label) -> example thread name
        self.edges: Dict[Tuple[str, str], str] = {}
        self._held = threading.local()
        self._mu = _REAL_LOCK()
        self._installed = False

    # -- tracking ----------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def _note_acquire(self, label: str) -> None:
        stack = self._stack()
        new_edges = [(h, label) for h in stack if h != label]
        stack.append(label)
        if new_edges:
            tname = threading.current_thread().name
            with self._mu:
                for e in new_edges:
                    self.edges.setdefault(e, tname)

    def _note_release(self, label: str) -> None:
        stack = self._stack()
        # locks are not always released LIFO: drop the last occurrence
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == label:
                del stack[i]
                return

    # -- install / uninstall ----------------------------------------

    def _creation_label(self) -> str:
        frame = sys._getframe(2)
        fn = frame.f_code.co_filename
        for marker in ("kubernetes_tpu", "tests"):
            idx = fn.find(marker)
            if idx >= 0:
                fn = fn[idx:]
                break
        return f"{fn}:{frame.f_lineno}"

    def install(self) -> None:
        assert not self._installed, "sentinel already installed"
        sentinel = self

        def make_lock():
            return _TrackedLock(_REAL_LOCK(), sentinel._creation_label(),
                                sentinel)

        def make_rlock():
            return _TrackedLock(_REAL_RLOCK(), sentinel._creation_label(),
                                sentinel)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock = _REAL_LOCK
            threading.RLock = _REAL_RLOCK
            self._installed = False

    # -- verdict -----------------------------------------------------

    def find_cycle(self) -> List[str]:
        """One observed acquisition cycle as a label list, or []."""
        graph: Dict[str, set] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}

        def dfs(node, stack):
            color[node] = GRAY
            stack.append(node)
            for nxt in sorted(graph[node]):
                if color[nxt] == GRAY:
                    return stack[stack.index(nxt):]
                if color[nxt] == WHITE:
                    cyc = dfs(nxt, stack)
                    if cyc:
                        return cyc
            color[node] = BLACK
            stack.pop()
            return None

        for start in sorted(graph):
            if color[start] == WHITE:
                cyc = dfs(start, [])
                if cyc:
                    return cyc
        return []

    def assert_cycle_free(self) -> None:
        cyc = self.find_cycle()
        if cyc:
            detail = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                thread = self.edges.get((a, b), "?")
                detail.append(f"  {a} -> {b}  (thread {thread})")
            raise AssertionError(
                "lock-order cycle observed at runtime:\n" +
                "\n".join(detail))


@contextlib.contextmanager
def lock_order_sentinel():
    """Install the sentinel, yield it, uninstall, assert acyclic."""
    s = LockOrderSentinel()
    s.install()
    try:
        yield s
    finally:
        s.uninstall()
    s.assert_cycle_free()
