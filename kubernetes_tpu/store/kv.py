"""Revisioned, ordered, watchable in-process KV store — the etcd equivalent.

The reference keeps all cluster state in etcd, reached only through the
apiserver's storage.Interface (reference: staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go:143 Create, :286 GuaranteedUpdate, :816 Watch).
This module reproduces the semantics that layer relies on:

  * a single monotonically-increasing int64 revision over ALL keys (the
    etcd store revision; object resourceVersion = mod revision);
  * conditional writes — create-if-absent, update/delete guarded by the
    expected mod revision (the transactional compare etcd3 store.go uses);
  * prefix range reads returning (values, store revision);
  * watches from a historical revision: replay from the event log, then
    live delivery; asking for a compacted revision raises Compacted — the
    equivalent of etcd's "410 Gone" that forces a client re-list
    (client-go reflector.go ListAndWatch re-list path).

Values are opaque Python objects; callers must treat returned values as
immutable (the apiserver layer stores serialized dicts and deep-copies at
its own boundary).
"""

from __future__ import annotations

import bisect
import os
import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import wal

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class StoreError(Exception):
    pass


class KeyExists(StoreError):
    pass


class KeyNotFound(StoreError):
    pass


class Conflict(StoreError):
    """Mod-revision precondition failed (optimistic concurrency)."""


class Compacted(StoreError):
    """Requested watch revision predates the retained event log (410 Gone)."""


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    key: str
    value: Any  # current value (ADDED/MODIFIED) or last value (DELETED)
    revision: int


@dataclass(frozen=True)
class KeyValue:
    key: str
    value: Any
    create_revision: int
    mod_revision: int


class Watch:
    """One watch stream: iterate for events; stop() ends the stream."""

    _SENTINEL = object()

    def __init__(self, store: "KVStore", prefix: str):
        self._store = store
        self._prefix = prefix
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False
        # a stopped watch is a DEAD stream: reflectors poll this to know
        # they must re-list+re-watch (the informer's restart-surviving
        # path after an apiserver crash kills every live watch)
        self.closed = False

    def _deliver(self, ev: Event) -> None:
        if not self._stopped and ev.key.startswith(self._prefix):
            self._q.put(ev)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self.closed = True
            self._store._remove_watch(self)
            self._q.put(self._SENTINEL)

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._q.get()
            if ev is self._SENTINEL:
                return
            yield ev

    def poll(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event or None on timeout/stop."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return None if ev is self._SENTINEL else ev


class KVStore:
    #: conditional writes accept a `precondition` callable (checked
    #: atomically under the store lock) — the capability the fencing
    #: layer probes before trusting guaranteed_update to be race-free
    supports_precondition = True

    #: bumped by crashing store facades (DurableKVStore) each time the
    #: live state is rebuilt; a plain in-memory store never restarts.
    #: Consumers (the HTTP fan-out's frame memo) fold it into cache keys
    #: so a (key, revision, type) triple re-minted by a rollback can
    #: never alias a stale cached frame.
    incarnation = 0

    def __init__(self, history_limit: int = 100_000):
        self._lock = threading.RLock()
        self._data: Dict[str, KeyValue] = {}
        self._keys: List[str] = []  # sorted for range reads
        self._rev = 0
        self._history: deque = deque()  # Events, oldest first
        self._history_limit = history_limit
        self._compacted_rev = 0  # events <= this are gone
        self._watches: List[Watch] = []

    # -- reads -------------------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rev

    @property
    def compacted_revision(self) -> int:
        """Events at or below this revision are gone (watch floor)."""
        with self._lock:
            return self._compacted_rev

    def get(self, key: str) -> KeyValue:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            return kv

    def list(self, prefix: str) -> Tuple[List[KeyValue], int]:
        """All KVs under prefix (key-ordered) + the store revision, the
        consistent LIST the reflector's initial sync needs."""
        with self._lock:
            lo = bisect.bisect_left(self._keys, prefix)
            out = []
            for i in range(lo, len(self._keys)):
                k = self._keys[i]
                if not k.startswith(prefix):
                    break
                out.append(self._data[k])
            return out, self._rev

    # -- writes ------------------------------------------------------------

    def create(self, key: str, value: Any) -> int:
        with self._lock:
            if key in self._data:
                raise KeyExists(key)
            self._rev += 1
            kv = KeyValue(key, value, self._rev, self._rev)
            self._data[key] = kv
            bisect.insort(self._keys, key)
            self._emit(Event(ADDED, key, value, self._rev))
            return self._rev

    def update(
        self,
        key: str,
        value: Any,
        expected_mod_revision: Optional[int] = None,
        precondition=None,
    ) -> int:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            if expected_mod_revision is not None and kv.mod_revision != expected_mod_revision:
                raise Conflict(
                    f"{key}: mod_revision {kv.mod_revision} != expected {expected_mod_revision}"
                )
            if precondition is not None:
                # under the store RLock (re-entrant: the callable may read
                # OTHER keys — the fencing check reads the leader lease) so
                # check + commit are one atomic step
                precondition()
            self._rev += 1
            self._data[key] = KeyValue(key, value, kv.create_revision, self._rev)
            self._emit(Event(MODIFIED, key, value, self._rev))
            return self._rev

    def delete(
        self,
        key: str,
        expected_mod_revision: Optional[int] = None,
        precondition=None,
    ) -> int:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            if expected_mod_revision is not None and kv.mod_revision != expected_mod_revision:
                raise Conflict(
                    f"{key}: mod_revision {kv.mod_revision} != expected {expected_mod_revision}"
                )
            if precondition is not None:
                precondition()
            self._rev += 1
            del self._data[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
            self._emit(Event(DELETED, key, kv.value, self._rev))
            return self._rev

    def guaranteed_update(self, key: str, fn, max_retries: int = 16,
                          precondition=None) -> int:
        return guaranteed_update(self, key, fn, max_retries, precondition)

    # -- watch -------------------------------------------------------------

    def watch(self, prefix: str = "", since_revision: Optional[int] = None) -> Watch:
        """Events with revision > since_revision under prefix. since=None
        means 'from now' (live-only); any int — INCLUDING 0, the revision
        of an empty store — replays history after that revision, so a
        lister that saw revision 0 has no list->watch event gap. Raises
        Compacted if the backlog was trimmed past the requested
        revision."""
        with self._lock:
            w = Watch(self, prefix)
            if since_revision is not None:
                if since_revision < self._compacted_rev:
                    raise Compacted(
                        f"revision {since_revision} compacted (floor {self._compacted_rev})"
                    )
                for ev in self._history:
                    if ev.revision > since_revision:
                        w._deliver(ev)
            self._watches.append(w)
            return w

    def history_since(
        self, prefix: str = "", since_revision: int = 0,
    ) -> List[Event]:
        """Retained events with revision > since_revision under prefix —
        the watch() replay as a value, for fan-out hubs that attach a
        late watcher to an already-running shared stream: replay the gap
        under the store lock, then ride the shared live feed with no
        missed or duplicated event. Raises Compacted exactly as watch()
        would."""
        with self._lock:
            if since_revision < self._compacted_rev:
                raise Compacted(
                    f"revision {since_revision} compacted (floor {self._compacted_rev})"
                )
            return [
                ev for ev in self._history
                if ev.revision > since_revision and ev.key.startswith(prefix)
            ]

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            try:
                self._watches.remove(w)
            except ValueError:
                pass

    def _emit(self, ev: Event) -> None:
        self._history.append(ev)
        while len(self._history) > self._history_limit:
            dropped = self._history.popleft()
            self._compacted_rev = dropped.revision
        for w in self._watches:
            w._deliver(ev)

    def compact(self, revision: int) -> None:
        """Drop history up to revision (etcd compaction)."""
        with self._lock:
            while self._history and self._history[0].revision <= revision:
                dropped = self._history.popleft()
                self._compacted_rev = dropped.revision


_EVENT_OPS = {ADDED: wal.OP_CREATE, MODIFIED: wal.OP_UPDATE, DELETED: wal.OP_DELETE}
_OP_EVENTS = {v: k for k, v in _EVENT_OPS.items()}


class DurableKVStore:
    """KVStore + append-only WAL + periodic snapshots — etcd's durability
    contract for the control plane (reference: etcd server/storage/wal +
    snap behind the apiserver's storage.Interface).

    Every mutation is framed into <path>/wal.log (store/wal.py) before it
    is acknowledged; every `snapshot_every` records the full state is
    written to <path>/snapshot.db and the WAL is rewritten down to the
    records that rebuild the retained event history. Construction (and
    the `recover` alias) replays snapshot+WAL back to the exact
    (rev, compacted_rev, data, history) the acknowledged writes produced:
    replay is idempotent — records at or below the snapshot revision only
    contribute history, records below the compaction floor contribute
    nothing — and a torn final record is discarded as the crash's own
    half-write, then truncated so appends resume at a record boundary.

    Values must be JSON-serializable (they are: the apiserver stores
    serde dicts). fsync=True acknowledges only durable writes — the
    crash drill's "zero lost acknowledged writes" assert rides on it;
    fsync=False trades the unsynced tail for write latency, exactly the
    etcd `--unsafe-no-fsync` posture.
    """

    supports_precondition = True

    def __init__(
        self,
        path: str,
        history_limit: int = 100_000,
        snapshot_every: int = 4096,
        fsync: bool = True,
    ):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._wal_path = os.path.join(path, "wal.log")
        self._snap_path = os.path.join(path, "snapshot.db")
        self._history_limit = history_limit
        self._snapshot_every = snapshot_every
        self._fsync = fsync
        # one writer lock over apply+log keeps WAL order == revision order
        self._dlock = threading.RLock()
        self._records_since_snapshot = 0
        self.incarnation = 0
        self._inner = self._rebuild()
        self._writer = wal.WALWriter(self._wal_path, fsync=fsync)

    @classmethod
    def recover(cls, path: str, **kw) -> "DurableKVStore":
        """Rebuild a store from its directory — what a restarted apiserver
        does. Recovery IS construction; the alias names the intent."""
        return cls(path, **kw)

    # -- recovery ----------------------------------------------------------

    def _rebuild(self) -> KVStore:
        inner = KVStore(history_limit=self._history_limit)
        snap = wal.read_snapshot(self._snap_path)
        if snap is not None:
            items, rev, compacted = snap
            with inner._lock:
                for key, value, create_rev, mod_rev in items:
                    inner._data[key] = KeyValue(key, value, create_rev, mod_rev)
                inner._keys = sorted(inner._data)
                inner._rev = rev
                inner._compacted_rev = compacted
        records, valid_end = wal.read_wal(self._wal_path)
        with inner._lock:
            for rec in records:
                self._replay(inner, rec)
            while len(inner._history) > self._history_limit:
                dropped = inner._history.popleft()
                inner._compacted_rev = dropped.revision
        # drop the torn tail so the next append starts a clean record
        wal.truncate(self._wal_path, valid_end)
        return inner

    @staticmethod
    def _replay(inner: KVStore, rec: "wal.Record") -> None:
        """Apply one WAL record; caller holds inner._lock. State applies
        only past the snapshot revision; history applies only past the
        compaction floor — together that makes replay idempotent."""
        if rec.op == wal.OP_COMPACT:
            DurableKVStore._apply_floor(inner, rec.compacted_rev)
            return
        if rec.rev > inner._rev:
            if rec.op == wal.OP_CREATE:
                inner._data[rec.key] = KeyValue(rec.key, rec.value, rec.rev, rec.rev)
                bisect.insort(inner._keys, rec.key)
            elif rec.op == wal.OP_UPDATE:
                prev = inner._data.get(rec.key)
                create_rev = prev.create_revision if prev is not None else rec.rev
                inner._data[rec.key] = KeyValue(rec.key, rec.value, create_rev, rec.rev)
            else:  # OP_DELETE
                if rec.key in inner._data:
                    del inner._data[rec.key]
                    i = bisect.bisect_left(inner._keys, rec.key)
                    del inner._keys[i]
            inner._rev = rec.rev
        if rec.rev > inner._compacted_rev:
            inner._history.append(
                Event(_OP_EVENTS[rec.op], rec.key, rec.value, rec.rev)
            )
        DurableKVStore._apply_floor(inner, rec.compacted_rev)

    @staticmethod
    def _apply_floor(inner: KVStore, floor: int) -> None:
        while inner._history and inner._history[0].revision <= floor:
            inner._history.popleft()
        if floor > inner._compacted_rev:
            inner._compacted_rev = floor

    # -- reads: delegate to the live in-memory store -----------------------

    @property
    def revision(self) -> int:
        return self._inner.revision

    @property
    def compacted_revision(self) -> int:
        return self._inner.compacted_revision

    def get(self, key: str) -> KeyValue:
        return self._inner.get(key)

    def list(self, prefix: str) -> Tuple[List[KeyValue], int]:
        return self._inner.list(prefix)

    def watch(self, prefix: str = "", since_revision: Optional[int] = None) -> Watch:
        # under _dlock: a watch racing crash() must not register on the
        # inner store being discarded — it would never be stopped/closed
        # and its reflector would poll a silent stream forever instead of
        # re-listing
        with self._dlock:
            return self._inner.watch(prefix, since_revision)

    def history_since(
        self, prefix: str = "", since_revision: int = 0,
    ) -> List[Event]:
        with self._dlock:
            return self._inner.history_since(prefix, since_revision)

    # -- writes: apply, then log before acknowledging ----------------------

    def create(self, key: str, value: Any) -> int:
        with self._dlock:
            rev = self._inner.create(key, value)
            self._log(wal.OP_CREATE, key, value, rev)
            return rev

    def update(
        self,
        key: str,
        value: Any,
        expected_mod_revision: Optional[int] = None,
        precondition=None,
    ) -> int:
        with self._dlock:
            rev = self._inner.update(key, value, expected_mod_revision,
                                     precondition=precondition)
            self._log(wal.OP_UPDATE, key, value, rev)
            return rev

    def delete(
        self,
        key: str,
        expected_mod_revision: Optional[int] = None,
        precondition=None,
    ) -> int:
        with self._dlock:
            # the DELETED event (and its WAL record) carries the last value
            prev = self._inner.get(key)
            rev = self._inner.delete(key, expected_mod_revision,
                                     precondition=precondition)
            self._log(wal.OP_DELETE, key, prev.value, rev)
            return rev

    def guaranteed_update(self, key: str, fn, max_retries: int = 16,
                          precondition=None) -> int:
        return guaranteed_update(self, key, fn, max_retries, precondition)

    def compact(self, revision: int) -> None:
        with self._dlock:
            self._inner.compact(revision)
            self._log(wal.OP_COMPACT, "", None, self._inner.revision)

    def _log(self, op: int, key: str, value: Any, rev: int) -> None:
        self._writer.append(
            wal.Record(op, key, value, rev, self._inner.compacted_revision)
        )
        self._records_since_snapshot += 1
        if self._records_since_snapshot >= self._snapshot_every:
            self._snapshot_locked()

    # -- snapshot / lifecycle ----------------------------------------------

    def snapshot(self) -> None:
        """Force a snapshot + WAL rotation now (tests / operator hook)."""
        with self._dlock:
            self._snapshot_locked()

    def _snapshot_locked(self) -> None:
        inner = self._inner
        with inner._lock:
            items = [
                (kvv.key, kvv.value, kvv.create_revision, kvv.mod_revision)
                for kvv in (inner._data[k] for k in inner._keys)
            ]
            rev = inner._rev
            compacted = inner._compacted_rev
            history = list(inner._history)
        wal.write_snapshot(self._snap_path, items, rev, compacted)
        # the retained WAL is exactly the records that rebuild the retained
        # history (floor, rev]; state at `rev` now lives in the snapshot
        self._writer.close()
        wal.rewrite(self._wal_path, [
            wal.Record(_EVENT_OPS[ev.type], ev.key, ev.value, ev.revision, compacted)
            for ev in history
        ])
        self._writer = wal.WALWriter(self._wal_path, fsync=self._fsync)
        self._records_since_snapshot = 0

    def sync(self) -> None:
        """Advance the durability watermark to everything written."""
        with self._dlock:
            self._writer.sync()

    def close(self) -> None:
        with self._dlock:
            self._writer.close()

    def crash(self, torn: bool = False) -> None:
        """SIGKILL-equivalent crash + restart as one atomic step: drop the
        in-memory state to what is durable on disk, then recover in place.
        Acknowledged-but-unsynced records (fsync=False) are lost exactly
        as a power cut would lose them; torn=True additionally leaves a
        half-written record at the tail (the write the crash caught
        mid-append), which recovery must discard. Every live watch dies
        marked `closed`, so reflectors re-list against the recovered
        revision — the restart-surviving watch contract."""
        with self._dlock:
            old = self._inner
            self._writer.crash(torn=torn)
            self._inner = self._rebuild()
            self._writer = wal.WALWriter(self._wal_path, fsync=self._fsync)
            self._records_since_snapshot = 0
            # the rebuilt store can re-mint (key, revision) pairs the old
            # incarnation already emitted (fsync=False rollback); anyone
            # caching per-revision artifacts must treat this as an epoch
            self.incarnation += 1
        with old._lock:
            watches = list(old._watches)
        for w in watches:
            w.stop()


def guaranteed_update(store, key: str, fn, max_retries: int = 16,
                      precondition=None) -> int:
    """Read-modify-write with conflict retry (etcd3 store.go:286
    GuaranteedUpdate's optimistic loop). fn(value) -> new value. Shared by
    every store backend so retry semantics can't diverge.

    `precondition` (zero-arg, raises to veto) is evaluated atomically with
    the commit on stores that support it (`supports_precondition`); on
    plain dict-backed stores it degrades to check-then-write — adequate
    for the fencing layer because a stale fence can only get MORE stale.
    """
    if precondition is not None and not getattr(
            store, "supports_precondition", False):
        for _ in range(max_retries):
            kv = store.get(key)
            new_value = fn(kv.value)
            precondition()
            try:
                return store.update(key, new_value, expected_mod_revision=kv.mod_revision)
            except Conflict:
                continue
        raise Conflict(f"{key}: too many conflicts in guaranteed_update")
    for _ in range(max_retries):
        kv = store.get(key)
        new_value = fn(kv.value)
        try:
            if precondition is not None:
                return store.update(key, new_value,
                                    expected_mod_revision=kv.mod_revision,
                                    precondition=precondition)
            return store.update(key, new_value, expected_mod_revision=kv.mod_revision)
        except Conflict:
            continue
    raise Conflict(f"{key}: too many conflicts in guaranteed_update")
