"""Revisioned, ordered, watchable in-process KV store — the etcd equivalent.

The reference keeps all cluster state in etcd, reached only through the
apiserver's storage.Interface (reference: staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go:143 Create, :286 GuaranteedUpdate, :816 Watch).
This module reproduces the semantics that layer relies on:

  * a single monotonically-increasing int64 revision over ALL keys (the
    etcd store revision; object resourceVersion = mod revision);
  * conditional writes — create-if-absent, update/delete guarded by the
    expected mod revision (the transactional compare etcd3 store.go uses);
  * prefix range reads returning (values, store revision);
  * watches from a historical revision: replay from the event log, then
    live delivery; asking for a compacted revision raises Compacted — the
    equivalent of etcd's "410 Gone" that forces a client re-list
    (client-go reflector.go ListAndWatch re-list path).

Values are opaque Python objects; callers must treat returned values as
immutable (the apiserver layer stores serialized dicts and deep-copies at
its own boundary).
"""

from __future__ import annotations

import bisect
import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class StoreError(Exception):
    pass


class KeyExists(StoreError):
    pass


class KeyNotFound(StoreError):
    pass


class Conflict(StoreError):
    """Mod-revision precondition failed (optimistic concurrency)."""


class Compacted(StoreError):
    """Requested watch revision predates the retained event log (410 Gone)."""


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    key: str
    value: Any  # current value (ADDED/MODIFIED) or last value (DELETED)
    revision: int


@dataclass(frozen=True)
class KeyValue:
    key: str
    value: Any
    create_revision: int
    mod_revision: int


class Watch:
    """One watch stream: iterate for events; stop() ends the stream."""

    _SENTINEL = object()

    def __init__(self, store: "KVStore", prefix: str):
        self._store = store
        self._prefix = prefix
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False

    def _deliver(self, ev: Event) -> None:
        if not self._stopped and ev.key.startswith(self._prefix):
            self._q.put(ev)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._store._remove_watch(self)
            self._q.put(self._SENTINEL)

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._q.get()
            if ev is self._SENTINEL:
                return
            yield ev

    def poll(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event or None on timeout/stop."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return None if ev is self._SENTINEL else ev


class KVStore:
    def __init__(self, history_limit: int = 100_000):
        self._lock = threading.RLock()
        self._data: Dict[str, KeyValue] = {}
        self._keys: List[str] = []  # sorted for range reads
        self._rev = 0
        self._history: deque = deque()  # Events, oldest first
        self._history_limit = history_limit
        self._compacted_rev = 0  # events <= this are gone
        self._watches: List[Watch] = []

    # -- reads -------------------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rev

    def get(self, key: str) -> KeyValue:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            return kv

    def list(self, prefix: str) -> Tuple[List[KeyValue], int]:
        """All KVs under prefix (key-ordered) + the store revision, the
        consistent LIST the reflector's initial sync needs."""
        with self._lock:
            lo = bisect.bisect_left(self._keys, prefix)
            out = []
            for i in range(lo, len(self._keys)):
                k = self._keys[i]
                if not k.startswith(prefix):
                    break
                out.append(self._data[k])
            return out, self._rev

    # -- writes ------------------------------------------------------------

    def create(self, key: str, value: Any) -> int:
        with self._lock:
            if key in self._data:
                raise KeyExists(key)
            self._rev += 1
            kv = KeyValue(key, value, self._rev, self._rev)
            self._data[key] = kv
            bisect.insort(self._keys, key)
            self._emit(Event(ADDED, key, value, self._rev))
            return self._rev

    def update(self, key: str, value: Any, expected_mod_revision: Optional[int] = None) -> int:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            if expected_mod_revision is not None and kv.mod_revision != expected_mod_revision:
                raise Conflict(
                    f"{key}: mod_revision {kv.mod_revision} != expected {expected_mod_revision}"
                )
            self._rev += 1
            self._data[key] = KeyValue(key, value, kv.create_revision, self._rev)
            self._emit(Event(MODIFIED, key, value, self._rev))
            return self._rev

    def delete(self, key: str, expected_mod_revision: Optional[int] = None) -> int:
        with self._lock:
            kv = self._data.get(key)
            if kv is None:
                raise KeyNotFound(key)
            if expected_mod_revision is not None and kv.mod_revision != expected_mod_revision:
                raise Conflict(
                    f"{key}: mod_revision {kv.mod_revision} != expected {expected_mod_revision}"
                )
            self._rev += 1
            del self._data[key]
            i = bisect.bisect_left(self._keys, key)
            del self._keys[i]
            self._emit(Event(DELETED, key, kv.value, self._rev))
            return self._rev

    def guaranteed_update(self, key: str, fn, max_retries: int = 16) -> int:
        return guaranteed_update(self, key, fn, max_retries)

    # -- watch -------------------------------------------------------------

    def watch(self, prefix: str = "", since_revision: Optional[int] = None) -> Watch:
        """Events with revision > since_revision under prefix. since=None
        means 'from now' (live-only); any int — INCLUDING 0, the revision
        of an empty store — replays history after that revision, so a
        lister that saw revision 0 has no list->watch event gap. Raises
        Compacted if the backlog was trimmed past the requested
        revision."""
        with self._lock:
            w = Watch(self, prefix)
            if since_revision is not None:
                if since_revision < self._compacted_rev:
                    raise Compacted(
                        f"revision {since_revision} compacted (floor {self._compacted_rev})"
                    )
                for ev in self._history:
                    if ev.revision > since_revision:
                        w._deliver(ev)
            self._watches.append(w)
            return w

    def _remove_watch(self, w: Watch) -> None:
        with self._lock:
            try:
                self._watches.remove(w)
            except ValueError:
                pass

    def _emit(self, ev: Event) -> None:
        self._history.append(ev)
        while len(self._history) > self._history_limit:
            dropped = self._history.popleft()
            self._compacted_rev = dropped.revision
        for w in self._watches:
            w._deliver(ev)

    def compact(self, revision: int) -> None:
        """Drop history up to revision (etcd compaction)."""
        with self._lock:
            while self._history and self._history[0].revision <= revision:
                dropped = self._history.popleft()
                self._compacted_rev = dropped.revision


def guaranteed_update(store, key: str, fn, max_retries: int = 16) -> int:
    """Read-modify-write with conflict retry (etcd3 store.go:286
    GuaranteedUpdate's optimistic loop). fn(value) -> new value. Shared by
    every store backend so retry semantics can't diverge."""
    for _ in range(max_retries):
        kv = store.get(key)
        new_value = fn(kv.value)
        try:
            return store.update(key, new_value, expected_mod_revision=kv.mod_revision)
        except Conflict:
            continue
    raise Conflict(f"{key}: too many conflicts in guaranteed_update")
