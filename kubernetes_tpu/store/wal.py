"""Write-ahead-log + snapshot framing for the durable KV store.

The reference's store is etcd, whose durability contract is exactly this
pair: an fsync'd append-only WAL of raft entries plus periodic snapshots
that bound replay (etcd server/storage/wal, snap). The on-disk grammar
here deliberately mirrors native/kvstore.cpp's wire framing (watch-poll
event buffers and kv_list result buffers) so the two backends can share
tooling:

  WAL record   frame   = len:u32 | crc32:u32 | payload
               payload = op:u8 | klen:u32 | key | vlen:u32 | value_json
                         | rev:i64 | compacted_rev:i64
  Snapshot     header  = magic 'KVSN' | version:u32 | rev:i64
                         | compacted_rev:i64 | count:u32
               entry   = klen:u32 | key | vlen:u32 | value_json
                         | create_rev:i64 | mod_rev:i64   (kv_list framing)
               trailer = crc32:u32 over header+entries

All integers little-endian. A torn final WAL record (short frame, short
payload, or CRC mismatch) terminates replay cleanly: it is the
half-written record of the crash itself, never an acknowledged write —
acknowledgements happen only after the fsync that made the record whole.
Snapshots are written tmp-then-rename, so the snapshot file is never
torn; a crash between snapshot and WAL rotation leaves stale WAL records
behind, which replay skips idempotently.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Iterator, List, NamedTuple, Optional, Tuple

OP_CREATE = 0  # matches the native event type ids (kvstore.cpp)
OP_UPDATE = 1
OP_DELETE = 2
OP_COMPACT = 3
# wire-only op: the HTTP watch path frames its keep-alive ticks in the
# same record grammar so a binary stream is records all the way down.
# Never valid on disk — WAL replay knows only ops 0..3.
OP_HEARTBEAT = 4

_FRAME = struct.Struct("<II")  # payload length, crc32(payload)
_U32 = struct.Struct("<I")
_TAIL = struct.Struct("<qq")  # rev, compacted_rev
_SNAP_HEAD = struct.Struct("<qqI")  # rev, compacted_rev, entry count
_ENTRY_REVS = struct.Struct("<qq")  # create_rev, mod_rev
_SNAP_MAGIC = b"KVSN"
_SNAP_VERSION = 1


class WALError(Exception):
    """Unrecoverable on-disk corruption (NOT a torn tail, which is normal)."""


class Record(NamedTuple):
    op: int
    key: str
    value: Any  # CREATE/UPDATE: new value; DELETE: last value; COMPACT: None
    rev: int
    compacted_rev: int  # the store's compaction floor AFTER this op


def encode_record(rec: Record) -> bytes:
    key = rec.key.encode()
    val = json.dumps(rec.value).encode()
    payload = b"".join((
        bytes((rec.op,)),
        _U32.pack(len(key)), key,
        _U32.pack(len(val)), val,
        _TAIL.pack(rec.rev, rec.compacted_rev),
    ))
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def _decode_payload(payload: bytes) -> Record:
    op = payload[0]
    klen = _U32.unpack_from(payload, 1)[0]
    key = payload[5:5 + klen].decode()
    off = 5 + klen
    vlen = _U32.unpack_from(payload, off)[0]
    value = json.loads(payload[off + 4:off + 4 + vlen])
    rev, compacted_rev = _TAIL.unpack_from(payload, off + 4 + vlen)
    return Record(op, key, value, rev, compacted_rev)


def iter_records(buf: bytes) -> Iterator[Tuple[Record, int]]:
    """(record, end_offset) pairs; stops silently at a torn/corrupt tail."""
    off = 0
    n = len(buf)
    while n - off >= _FRAME.size:
        plen, crc = _FRAME.unpack_from(buf, off)
        start = off + _FRAME.size
        if start + plen > n:
            return  # torn tail: frame promised more bytes than exist
        payload = buf[start:start + plen]
        if zlib.crc32(payload) != crc:
            return  # torn tail: record half-written when the crash hit
        try:
            rec = _decode_payload(payload)
        except (IndexError, struct.error, ValueError, UnicodeDecodeError):
            return
        off = start + plen
        yield rec, off


def read_wal(path: str) -> Tuple[List[Record], int]:
    """All intact records + the byte offset where the intact prefix ends
    (the truncation point that drops a torn tail)."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except FileNotFoundError:
        return [], 0
    records: List[Record] = []
    end = 0
    for rec, off in iter_records(buf):
        records.append(rec)
        end = off
    return records, end


def truncate(path: str, offset: int) -> None:
    """Drop everything past offset (the torn tail) so appends resume at a
    record boundary."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size <= offset:
        return
    with open(path, "r+b") as f:
        f.truncate(offset)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    # the rename itself must be durable, or a crash can resurrect the
    # replaced file (the classic create-rename-fsync dance)
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def rewrite(path: str, records: List[Record]) -> None:
    """Atomically replace the WAL with exactly `records` (rotation)."""
    _atomic_write(path, b"".join(encode_record(r) for r in records))


def snapshot_header(count: int, rev: int, compacted_rev: int) -> bytes:
    return (_SNAP_MAGIC + _U32.pack(_SNAP_VERSION)
            + _SNAP_HEAD.pack(rev, compacted_rev, count))


def encode_snapshot_entry(
    key: str, value: Any, create_rev: int, mod_rev: int,
) -> bytes:
    """One kv_list-framed entry — the unit the HTTP binary list streams."""
    k = key.encode()
    val = json.dumps(value).encode()
    return (_U32.pack(len(k)) + k + _U32.pack(len(val)) + val
            + _ENTRY_REVS.pack(create_rev, mod_rev))


def encode_snapshot(
    items: List[Tuple[str, Any, int, int]],  # (key, value, create_rev, mod_rev)
    rev: int,
    compacted_rev: int,
) -> bytes:
    body = bytearray()
    body += snapshot_header(len(items), rev, compacted_rev)
    for key, value, create_rev, mod_rev in items:
        body += encode_snapshot_entry(key, value, create_rev, mod_rev)
    body += _U32.pack(zlib.crc32(bytes(body)))
    return bytes(body)


def write_snapshot(
    path: str,
    items: List[Tuple[str, Any, int, int]],  # (key, value, create_rev, mod_rev)
    rev: int,
    compacted_rev: int,
) -> None:
    _atomic_write(path, encode_snapshot(items, rev, compacted_rev))


def decode_snapshot(
    buf: bytes, label: str = "<buf>",
) -> Tuple[List[Tuple[str, Any, int, int]], int, int]:
    """-> (items, rev, compacted_rev). Raises WALError on corruption."""
    head_len = len(_SNAP_MAGIC) + _U32.size + _SNAP_HEAD.size
    if len(buf) < head_len + _U32.size or buf[:4] != _SNAP_MAGIC:
        raise WALError(f"snapshot {label}: bad magic/size")
    if zlib.crc32(buf[:-4]) != _U32.unpack_from(buf, len(buf) - 4)[0]:
        raise WALError(f"snapshot {label}: checksum mismatch")
    version = _U32.unpack_from(buf, 4)[0]
    if version != _SNAP_VERSION:
        raise WALError(f"snapshot {label}: unknown version {version}")
    rev, compacted_rev, count = _SNAP_HEAD.unpack_from(buf, 8)
    off = head_len
    items: List[Tuple[str, Any, int, int]] = []
    try:
        for _ in range(count):
            klen = _U32.unpack_from(buf, off)[0]
            key = buf[off + 4:off + 4 + klen].decode()
            off += 4 + klen
            vlen = _U32.unpack_from(buf, off)[0]
            value = json.loads(buf[off + 4:off + 4 + vlen])
            off += 4 + vlen
            create_rev, mod_rev = _ENTRY_REVS.unpack_from(buf, off)
            off += _ENTRY_REVS.size
            items.append((key, value, create_rev, mod_rev))
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise WALError(f"snapshot {label}: truncated entries: {e}")
    return items, rev, compacted_rev


def read_snapshot(
    path: str,
) -> Optional[Tuple[List[Tuple[str, Any, int, int]], int, int]]:
    """-> (items, rev, compacted_rev), or None when no snapshot exists.
    Raises WALError on corruption: snapshots are written atomically, so a
    bad one is disk damage, not a crash artifact."""
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except FileNotFoundError:
        return None
    return decode_snapshot(buf, label=path)


class WALWriter:
    """Append-only record log with explicit durability tracking.

    `durable_offset` is the byte count known to be on the platter: with
    fsync=True it tracks every append (each acknowledged write is
    durable, etcd's contract); with fsync=False it only advances on
    sync(), and crash() discards the in-between — exactly what a power
    cut does to the OS page cache."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self._do_fsync = fsync
        self._f = open(path, "ab")
        # pre-existing bytes either were fsynced by their writer or
        # survived a real crash: both count as durable
        self.durable_offset = self._f.tell()

    def append(self, rec: Record) -> None:
        self._f.write(encode_record(rec))
        self._f.flush()
        if self._do_fsync:
            os.fsync(self._f.fileno())
            self.durable_offset = self._f.tell()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self.durable_offset = self._f.tell()

    def close(self, sync: bool = True) -> None:
        if self._f.closed:
            return
        if sync:
            self.sync()
        self._f.close()

    def crash(self, torn: bool = False) -> None:
        """SIGKILL-equivalent: abandon the handle, drop every byte past
        the last fsync, and (optionally) leave a half-written record at
        the tail — the write the crash caught mid-append."""
        durable = self.durable_offset
        try:
            self._f.close()  # without flush-ordering guarantees; see below
        except OSError:
            pass
        # close() flushed Python's buffer into the page cache, but a real
        # crash loses the page cache too: model it by truncating to the
        # fsync watermark
        truncate(self.path, durable)
        if torn:
            junk = encode_record(
                Record(OP_CREATE, "__torn__", {"torn": True}, 1 << 60, 0)
            )
            with open(self.path, "ab") as f:
                f.write(junk[: len(junk) // 2])
