"""ctypes binding for the native (C++) KV store — the etcd-equivalent.

Reference: the reference's store is etcd, a native process beside the
apiserver (SURVEY.md §2.4.2; staging/src/k8s.io/apiserver/pkg/storage/
etcd3). `NativeKVStore` is drop-in for store.kv.KVStore (same methods,
exceptions, and Watch surface — tests/test_store.py runs the same
suite over both), backed by native/kvstore.cpp:

  * values cross the boundary as JSON bytes, so callers can never alias
    stored state (the copy discipline the apiserver depends on);
  * watch polls block inside the shared library with the GIL released —
    N informers polling do not serialize the interpreter;
  * the library is built on demand with g++ (native/Makefile) — no
    pip/pybind11 (the environment bans installs; ctypes is stdlib).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import threading
from typing import Any, Iterator, List, Optional, Tuple

from .kv import (
    ADDED,
    DELETED,
    MODIFIED,
    Compacted,
    Conflict,
    Event,
    KeyExists,
    KeyNotFound,
    KeyValue,
)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libkvstore.so")
_EVENT_TYPES = {0: ADDED, 1: MODIFIED, 2: DELETED}

_lib = None
_lib_lock = threading.Lock()


def _build_library() -> None:
    subprocess.run(
        ["make", "-s", "build/libkvstore.so"],
        cwd=os.path.abspath(_NATIVE_DIR),
        check=True,
        capture_output=True,
    )


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the shared library; cached."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _build_library()
        lib = ctypes.CDLL(os.path.abspath(_LIB_PATH))
        lib.kv_new.restype = ctypes.c_void_p
        lib.kv_new.argtypes = [ctypes.c_int64]
        lib.kv_free.argtypes = [ctypes.c_void_p]
        lib.kv_buf_free.argtypes = [ctypes.c_void_p]
        lib.kv_create.restype = ctypes.c_int64
        lib.kv_create.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.kv_update.restype = ctypes.c_int64
        lib.kv_update.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.kv_delete.restype = ctypes.c_int64
        lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.kv_get.restype = ctypes.c_void_p
        lib.kv_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.kv_list.restype = ctypes.c_void_p
        lib.kv_list.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.kv_rev.restype = ctypes.c_int64
        lib.kv_rev.argtypes = [ctypes.c_void_p]
        lib.kv_compacted_rev.restype = ctypes.c_int64
        lib.kv_compacted_rev.argtypes = [ctypes.c_void_p]
        lib.kv_compact.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kv_watch_new.restype = ctypes.c_int64
        lib.kv_watch_new.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.kv_watch_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kv_watch_poll.restype = ctypes.c_void_p
        lib.kv_watch_poll.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
        return lib


def _take_buf(lib, ptr: int, length: int) -> bytes:
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.kv_buf_free(ptr)


class NativeWatch:
    """Watch stream over a native watch id; poll blocks GIL-free."""

    def __init__(self, store: "NativeKVStore", wid: int):
        self._store = store
        self._wid = wid
        self._stopped = threading.Event()

    @property
    def closed(self) -> bool:
        """Dead-stream marker (kv.Watch.closed parity): reflectors
        re-list when the stream they poll has been stopped."""
        return self._stopped.is_set()

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self._store._lib.kv_watch_free(self._store._h, self._wid)

    def poll(self, timeout: Optional[float] = None) -> Optional[Event]:
        # timeout=None blocks until an event or stop() (kv.Watch.poll
        # semantics); the native wait wakes on stop via the store CV, so
        # loop in bounded chunks rather than waiting forever in C
        while True:
            if self._stopped.is_set():
                return None
            ms = 3_600_000 if timeout is None else int(timeout * 1000)
            out_len = ctypes.c_int64()
            ptr = self._store._lib.kv_watch_poll(
                self._store._h, self._wid, ms, ctypes.byref(out_len)
            )
            if ptr:
                break
            if timeout is not None:
                return None
        buf = _take_buf(self._store._lib, ptr, out_len.value)
        etype = buf[0]
        klen = struct.unpack_from("<I", buf, 1)[0]
        key = buf[5 : 5 + klen].decode()
        off = 5 + klen
        vlen = struct.unpack_from("<I", buf, off)[0]
        value = json.loads(buf[off + 4 : off + 4 + vlen]) if vlen else None
        rev = struct.unpack_from("<q", buf, off + 4 + vlen)[0]
        return Event(_EVENT_TYPES[etype], key, value, rev)

    def __iter__(self) -> Iterator[Event]:
        while not self._stopped.is_set():
            ev = self.poll(timeout=0.2)
            if ev is not None:
                yield ev


class NativeKVStore:
    """Drop-in KVStore over the C++ library (same API surface)."""

    #: the C side cannot evaluate a Python precondition inside its write
    #: lock; callers get check-then-write (see kv.guaranteed_update) —
    #: adequate for fencing (a stale fence only gets MORE stale) but not
    #: atomic, so the capability flag stays honest
    supports_precondition = False

    def __init__(self, history_limit: int = 100_000):
        self._lib = load_library()
        self._h = self._lib.kv_new(history_limit)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.kv_free(self._h)
                self._h = None
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    # -- reads -------------------------------------------------------------

    @property
    def revision(self) -> int:
        return self._lib.kv_rev(self._h)

    @property
    def compacted_revision(self) -> int:
        return self._lib.kv_compacted_rev(self._h)

    def get(self, key: str) -> KeyValue:
        out_len = ctypes.c_int64()
        create_rev = ctypes.c_int64()
        mod_rev = ctypes.c_int64()
        ptr = self._lib.kv_get(
            self._h, key.encode(), ctypes.byref(out_len),
            ctypes.byref(create_rev), ctypes.byref(mod_rev),
        )
        if not ptr:
            raise KeyNotFound(key)
        value = json.loads(_take_buf(self._lib, ptr, out_len.value))
        return KeyValue(key, value, create_rev.value, mod_rev.value)

    def list(self, prefix: str) -> Tuple[List[KeyValue], int]:
        out_len = ctypes.c_int64()
        ptr = self._lib.kv_list(self._h, prefix.encode(), ctypes.byref(out_len))
        buf = _take_buf(self._lib, ptr, out_len.value)
        n = struct.unpack_from("<I", buf, 0)[0]
        off = 4
        items: List[KeyValue] = []
        for _ in range(n):
            klen = struct.unpack_from("<I", buf, off)[0]
            key = buf[off + 4 : off + 4 + klen].decode()
            off += 4 + klen
            vlen = struct.unpack_from("<I", buf, off)[0]
            value = json.loads(buf[off + 4 : off + 4 + vlen])
            off += 4 + vlen
            create_rev, mod_rev = struct.unpack_from("<qq", buf, off)
            off += 16
            items.append(KeyValue(key, value, create_rev, mod_rev))
        rev = struct.unpack_from("<q", buf, off)[0]
        return items, rev

    # -- writes ------------------------------------------------------------

    def create(self, key: str, value: Any) -> int:
        data = json.dumps(value).encode()
        rev = self._lib.kv_create(self._h, key.encode(), data, len(data))
        if rev == -1:
            raise KeyExists(key)
        return rev

    def update(
        self, key: str, value: Any, expected_mod_revision: Optional[int] = None,
        precondition=None,
    ) -> int:
        if precondition is not None:
            precondition()
        data = json.dumps(value).encode()
        expected = -1 if expected_mod_revision is None else expected_mod_revision
        rev = self._lib.kv_update(self._h, key.encode(), data, len(data), expected)
        if rev == -1:
            raise KeyNotFound(key)
        if rev == -2:
            raise Conflict(
                f"{key}: mod_revision != expected {expected_mod_revision}"
            )
        return rev

    def delete(self, key: str, expected_mod_revision: Optional[int] = None,
               precondition=None) -> int:
        if precondition is not None:
            precondition()
        expected = -1 if expected_mod_revision is None else expected_mod_revision
        rev = self._lib.kv_delete(self._h, key.encode(), expected)
        if rev == -1:
            raise KeyNotFound(key)
        if rev == -2:
            raise Conflict(
                f"{key}: mod_revision != expected {expected_mod_revision}"
            )
        return rev

    def guaranteed_update(self, key: str, fn, max_retries: int = 16,
                          precondition=None) -> int:
        from .kv import guaranteed_update

        return guaranteed_update(self, key, fn, max_retries, precondition)

    def compact(self, revision: int) -> None:
        """Drop history up to revision (etcd compaction)."""
        self._lib.kv_compact(self._h, revision)

    # -- watch -------------------------------------------------------------

    def watch(
        self, prefix: str = "", since_revision: Optional[int] = None
    ) -> NativeWatch:
        # None = live-only (kv.py semantics); the C side uses -1 for that.
        # 0 replays from the beginning (empty-store list revision).
        since = -1 if since_revision is None else since_revision
        wid = self._lib.kv_watch_new(self._h, prefix.encode(), since)
        if wid == -2:
            raise Compacted(
                f"revision {since_revision} compacted "
                f"(floor {self._lib.kv_compacted_rev(self._h)})"
            )
        return NativeWatch(self, wid)
