"""kubeadm equivalent: phased init, worker/control-plane join, bootstrap
tokens, and certificate lifecycle.

Reference: cmd/kubeadm — init runs an ordered phase list (app/cmd/phases/
init: preflight, certs, kubeconfig, control-plane, upload-config,
mark-control-plane, bootstrap-token, addon), each independently
invocable (`kubeadm init phase <name>`) and skippable (--skip-phases);
join (app/cmd/join.go) discovers the cluster with a bootstrap token
(abcdef.16-hex format, stored as a Secret in kube-system per
bootstrap.kubernetes.io/token) and brings up a kubelet;
`kubeadm certs check-expiration` / `renew` manage the PKI.

The in-proc trust model: this build's "certificates" are signed identity
records (HMAC over cn/org/expiry with the cluster CA key) whose tokens
register with the SecureAPIServer's authenticator — the same
issue/verify/expire/renew lifecycle without an X.509 stack, which no
in-proc boundary would check anyway.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .api import types as v1

TOKEN_SECRET_PREFIX = "bootstrap-token-"
TOKEN_ID_LEN = 6
TOKEN_SECRET_LEN = 16
DEFAULT_CERT_TTL = 365 * 24 * 3600.0  # kubeadm's 1-year component certs
DEFAULT_TOKEN_TTL = 24 * 3600.0  # bootstrap tokens default to 24h
CONTROL_PLANE_LABEL = "node-role.kubernetes.io/control-plane"
CONTROL_PLANE_TAINT = "node-role.kubernetes.io/master"


def generate_bootstrap_token() -> str:
    """abcdef.0123456789abcdef (bootstraputil.GenerateBootstrapToken)."""
    alphabet = "abcdefghijklmnopqrstuvwxyz0123456789"
    tid = "".join(secrets.choice(alphabet) for _ in range(TOKEN_ID_LEN))
    tsec = "".join(secrets.choice(alphabet) for _ in range(TOKEN_SECRET_LEN))
    return f"{tid}.{tsec}"


@dataclass
class Certificate:
    """A signed identity record (the X.509-shaped subset kubeadm manages:
    CommonName/Organization map to user/groups, NotAfter to expiry)."""

    common_name: str
    organizations: List[str]
    not_after: float
    signature: str = ""
    token: str = ""  # the bearer credential registered for this identity


class CertificateAuthority:
    """Issue/verify/renew identity records (kubeadm's pkiutil + renewal
    manager, app/phases/certs)."""

    def __init__(self, key: Optional[bytes] = None):
        self.key = key or secrets.token_bytes(32)
        self._lock = threading.Lock()
        self.issued: Dict[str, Certificate] = {}  # name -> cert

    def _sign(self, cn: str, orgs: List[str], not_after: float) -> str:
        msg = f"{cn}|{','.join(sorted(orgs))}|{not_after:.3f}".encode()
        return hmac.new(self.key, msg, hashlib.sha256).hexdigest()

    def public_bundle(self) -> str:
        """The distributable CA identity (the ca.crt bundle analog):
        clients pin this fingerprint to verify they're talking to the
        cluster this CA anchors (root-ca-cert-publisher payload;
        discovery's --discovery-token-ca-cert-hash)."""
        return "sha256:" + hashlib.sha256(self.key).hexdigest()

    def issue(self, name: str, common_name: str, organizations: List[str],
              ttl: float = DEFAULT_CERT_TTL) -> Certificate:
        not_after = time.time() + ttl
        cert = Certificate(
            common_name=common_name,
            organizations=list(organizations),
            not_after=not_after,
            signature=self._sign(common_name, organizations, not_after),
            token=f"cert-{secrets.token_hex(16)}",
        )
        with self._lock:
            self.issued[name] = cert
        return cert

    def verify(self, cert: Certificate) -> bool:
        if time.time() >= cert.not_after:
            return False
        want = self._sign(cert.common_name, cert.organizations, cert.not_after)
        return hmac.compare_digest(want, cert.signature)

    def check_expiration(self, within: float = 0.0) -> Dict[str, float]:
        """name -> seconds until expiry (kubeadm certs check-expiration);
        only entries expiring within `within` seconds when given."""
        now = time.time()
        with self._lock:
            out = {n: c.not_after - now for n, c in self.issued.items()}
        if within:
            out = {n: left for n, left in out.items() if left <= within}
        return out

    def renew(self, name: str, ttl: float = DEFAULT_CERT_TTL) -> Certificate:
        """kubeadm certs renew <name>: re-issue with a fresh expiry (same
        identity, same bearer token so live components keep working)."""
        with self._lock:
            old = self.issued[name]
        cert = Certificate(
            common_name=old.common_name,
            organizations=list(old.organizations),
            not_after=time.time() + ttl,
            token=old.token,
        )
        cert.signature = self._sign(
            cert.common_name, cert.organizations, cert.not_after
        )
        with self._lock:
            self.issued[name] = cert
        return cert


@dataclass
class Phase:
    name: str
    run: Callable[["InitContext"], None]


@dataclass
class InitContext:
    """What phases read/write (kubeadm's workflow.RunData analog)."""

    secure: object  # apiserver.auth.SecureAPIServer
    cluster_name: str = "kubernetes"
    node_name: str = "control-plane-0"
    ca: CertificateAuthority = field(default_factory=CertificateAuthority)
    bootstrap_token: str = ""
    admin_token: str = ""
    results: Dict[str, bool] = field(default_factory=dict)


# -- the init phases (same order as app/cmd/phases/init) --------------------


def _apply(api, resource: str, obj) -> None:
    """Create-or-replace: phases are individually re-runnable (`kubeadm
    init phase <name>` twice must succeed idempotently, as the
    reference's phases do)."""
    try:
        api.create(resource, obj)
    except Exception:
        try:
            live = api.get(resource, obj.metadata.name,
                           obj.metadata.namespace)
            obj.metadata.resource_version = live.metadata.resource_version
            api.update(resource, obj)
        except Exception:
            raise


def _phase_preflight(ctx: InitContext) -> None:
    # environment checks: store reachable, clean registry prefix
    ctx.secure.api.list("namespaces")


def _phase_certs(ctx: InitContext) -> None:
    """Issue the control-plane PKI: CA-signed identities for admin,
    apiserver, controller-manager, scheduler, kubelet client."""
    for name, cn, orgs in (
        ("admin", "kubernetes-admin", ["system:masters"]),
        ("controller-manager", "system:kube-controller-manager", []),
        ("scheduler", "system:kube-scheduler", []),
        (f"kubelet-{ctx.node_name}", f"system:node:{ctx.node_name}",
         ["system:nodes"]),
    ):
        cert = ctx.ca.issue(name, cn, orgs)
        ctx.secure.authenticator.add_token(cert.token, cn, orgs)
    ctx.admin_token = ctx.ca.issued["admin"].token


def _phase_kubeconfig(ctx: InitContext) -> None:
    """Admin/component kubeconfigs: a ConfigMap holding the cluster
    coordinates + identity references (files in the reference)."""
    _apply(ctx.secure.api, "configmaps", v1.ConfigMap(
        metadata=v1.ObjectMeta(name="kubeconfig-admin", namespace="kube-system"),
        data={"cluster": ctx.cluster_name, "user": "kubernetes-admin"},
    ))


def _phase_upload_config(ctx: InitContext) -> None:
    """kubeadm-config ConfigMap (uploadconfig phase) — what joining nodes
    read to discover cluster settings."""
    _apply(ctx.secure.api, "configmaps", v1.ConfigMap(
        metadata=v1.ObjectMeta(name="kubeadm-config", namespace="kube-system"),
        data={"clusterName": ctx.cluster_name},
    ))


def _phase_mark_control_plane(ctx: InitContext) -> None:
    """Label + taint the control-plane node (markcontrolplane phase)."""
    api = ctx.secure.api
    try:
        node = api.get("nodes", ctx.node_name)
    except Exception:  # noqa: BLE001 — no node object yet: create a stub
        node = v1.Node(metadata=v1.ObjectMeta(name=ctx.node_name))
        node = api.create("nodes", node)
    node.metadata.labels = dict(node.metadata.labels or {})
    node.metadata.labels[CONTROL_PLANE_LABEL] = ""
    taints = list(node.spec.taints or [])
    if not any(t.key == CONTROL_PLANE_TAINT for t in taints):
        # idempotent: phases are individually re-runnable (kubeadm init
        # phase mark-control-plane twice must not stack taints)
        taints.append(
            v1.Taint(key=CONTROL_PLANE_TAINT, value="", effect="NoSchedule")
        )
    node.spec.taints = taints
    api.update("nodes", node)


def _phase_bootstrap_token(ctx: InitContext) -> None:
    """Create the join token as a kube-system Secret
    (bootstraptoken phase; bootstrap.kubernetes.io/token type)."""
    token = ctx.bootstrap_token or generate_bootstrap_token()
    tid, tsec = token.split(".", 1)
    _apply(ctx.secure.api, "secrets", v1.Secret(
        metadata=v1.ObjectMeta(
            name=f"{TOKEN_SECRET_PREFIX}{tid}", namespace="kube-system"),
        type="bootstrap.kubernetes.io/token",
        data={
            "token-id": tid,
            "token-secret": tsec,
            "expiration": str(time.time() + DEFAULT_TOKEN_TTL),
            "usage-bootstrap-authentication": "true",
            "usage-bootstrap-signing": "true",
        },
    ))
    # cluster-info in kube-public (bootstraptoken/clusterinfo phase):
    # the anonymous discovery document joiners read; the bootstrapsigner
    # controller maintains its jws-kubeconfig-<tokenID> signatures
    _apply(ctx.secure.api, "configmaps", v1.ConfigMap(
        metadata=v1.ObjectMeta(name="cluster-info", namespace="kube-public"),
        data={
            "kubeconfig": (
                f"cluster={ctx.cluster_name};"
                f"ca={ctx.ca.public_bundle()}"
            ),
        },
    ))
    ctx.bootstrap_token = token


INIT_PHASES: List[Phase] = [
    Phase("preflight", _phase_preflight),
    Phase("certs", _phase_certs),
    Phase("kubeconfig", _phase_kubeconfig),
    Phase("upload-config", _phase_upload_config),
    Phase("mark-control-plane", _phase_mark_control_plane),
    Phase("bootstrap-token", _phase_bootstrap_token),
]


def init(secure, node_name: str = "control-plane-0",
         skip_phases: Optional[List[str]] = None,
         only_phase: str = "") -> InitContext:
    """kubeadm init: run the phase list in order. `only_phase` runs a
    single phase (kubeadm init phase <name>); `skip_phases` mirrors
    --skip-phases."""
    ctx = InitContext(secure=secure, node_name=node_name)
    skip = set(skip_phases or ())
    for phase in INIT_PHASES:
        if only_phase and phase.name != only_phase:
            continue
        if phase.name in skip:
            ctx.results[phase.name] = False
            continue
        phase.run(ctx)
        ctx.results[phase.name] = True
    return ctx


# -- join -------------------------------------------------------------------


class InvalidToken(Exception):
    pass


def _validate_token(api, token: str) -> None:
    """Token discovery/validation (app/discovery/token): the secret must
    exist, match, allow authentication, and not be expired."""
    try:
        tid, tsec = token.split(".", 1)
    except ValueError:
        raise InvalidToken(f"malformed bootstrap token {token!r}")
    try:
        secret = api.get("secrets", f"{TOKEN_SECRET_PREFIX}{tid}", "kube-system")
    except Exception:
        raise InvalidToken(f"unknown bootstrap token id {tid!r}")
    data = secret.data or {}
    if data.get("token-secret") != tsec:
        raise InvalidToken("bootstrap token secret mismatch")
    if data.get("usage-bootstrap-authentication") != "true":
        raise InvalidToken("token not usable for authentication")
    if float(data.get("expiration", "0")) < time.time():
        raise InvalidToken("bootstrap token expired")


def join(ctx: InitContext, node_name: str,
         control_plane: bool = False, token: str = "",
         via_csr: bool = False, csr_timeout: float = 30.0) -> Certificate:
    """kubeadm join: validate the bootstrap token, obtain the node's
    kubelet identity (TLS bootstrap analog), and for --control-plane
    joins mark the node and mint component identities too.

    via_csr=True runs the real TLS-bootstrap shape: create a
    CertificateSigningRequest as the bootstrap identity and wait for the
    csrapproving + csrsigning controllers to approve and issue it
    (kubelet/certificate/bootstrap; requires those controllers running
    against the same apiserver with ctx.ca)."""
    api = ctx.secure.api
    _validate_token(api, token or ctx.bootstrap_token)
    if via_csr:
        cert = _join_via_csr(ctx, node_name, token or ctx.bootstrap_token,
                             csr_timeout)
    else:
        cert = ctx.ca.issue(
            f"kubelet-{node_name}", f"system:node:{node_name}",
            ["system:nodes"]
        )
    ctx.secure.authenticator.add_token(
        cert.token, cert.common_name, cert.organizations
    )
    if control_plane:
        sub = InitContext(
            secure=ctx.secure, node_name=node_name, ca=ctx.ca,
            cluster_name=ctx.cluster_name,
        )
        _phase_mark_control_plane(sub)
    return cert


def _join_via_csr(ctx: InitContext, node_name: str, token: str,
                  timeout: float) -> Certificate:
    import json as _json

    from .api import certificates as certsapi

    api = ctx.secure.api
    tid = token.split(".", 1)[0]
    name = f"node-csr-{node_name}"
    csr = certsapi.CertificateSigningRequest(
        metadata=certsapi.ObjectMeta(name=name),
        spec=certsapi.CertificateSigningRequestSpec(
            request=certsapi.encode_request(
                f"system:node:{node_name}", ["system:nodes"]
            ),
            signer_name=certsapi.SIGNER_KUBE_APISERVER_CLIENT_KUBELET,
            usages=["client auth"],
            username=f"system:bootstrap:{tid}",
            groups=["system:bootstrappers"],
        ),
    )
    try:
        api.create("certificatesigningrequests", csr)
    except Exception:  # noqa: BLE001 — re-join: an existing CSR may be ours
        pass
    deadline = time.time() + timeout
    while time.time() < deadline:
        cur = api.get("certificatesigningrequests", name)
        # never adopt a pre-existing CSR for a DIFFERENT identity: an
        # attacker could pre-create node-csr-<victim> and harvest the
        # issued credential (the signer writes the bearer token into
        # status.certificate)
        if cur.spec.request != csr.spec.request:
            raise InvalidToken(
                f"existing CSR {name!r} requests a different identity; "
                "refusing to adopt it"
            )
        if cur.status.certificate:
            rec = _json.loads(cur.status.certificate)
            if rec.get("commonName") != f"system:node:{node_name}":
                raise InvalidToken(
                    f"CSR {name!r} was issued for "
                    f"{rec.get('commonName')!r}, not this node"
                )
            return Certificate(
                common_name=rec["commonName"],
                organizations=list(rec["organizations"]),
                not_after=float(rec["notAfter"]),
                signature=rec.get("signature", ""),
                token=rec.get("token", ""),
            )
        time.sleep(0.05)
    raise TimeoutError(
        f"CSR {name!r} was not approved+signed within {timeout}s "
        "(are csrapproving/csrsigning running?)"
    )
