"""Multi-chip dispatch of the scheduling kernel over a jax.sharding.Mesh.

The reference parallelizes its filter/score hot loop over 16 goroutines
chunked across nodes (reference: pkg/scheduler/internal/parallelize/
parallelism.go:27,56 Until; used from core/generic_scheduler.go:295 and
framework/runtime/framework.go:736). The TPU equivalent shards the *node
axis* of the dense cluster encoding across chips: every per-node matrix is
split along dim 0 over the mesh's "nodes" axis, per-pod/term/vocab state is
replicated, and the fused kernel (ops/kernel.py) runs under jit with GSPMD
propagating the shardings. Cross-shard reductions the kernel needs —
normalization max/min over all nodes (helper/normalize_score.go:26
DefaultNormalizeScore), topology-pair counts (segment-sums scattered from
the replicated pod table onto node-sharded outputs) — become XLA
collectives over ICI, replacing the reference's shared-memory access.

The final argmax across shards rides the same mechanism: `select` reduces
the node-sharded total-score vector to one (score, index) pair, which XLA
lowers to an all-reduce over the mesh.

This is data parallelism over cluster nodes — the analog of "DP over the
batch" in an ML workload; the pod axis (batching many pending pods per
dispatch) is the second axis, used by the batch/session paths (ops/batch.py,
ops/hoisted.py) and by gang scheduling (scheduler/plugins/coscheduling.py).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.vocab import node_headroom
from ..utils import knobs
from ..ops.kernel import DEFAULT_WEIGHTS, schedule_pod
from .partition import CLUSTER_PARTITION_RULES, NODE_AXIS, shard_tree

__all__ = [
    "NODE_AXIS", "NODE_DIM0_KEYS", "make_mesh", "node_capacity_multiple",
    "node_headroom", "pad_node_axis", "shard_cluster", "replicate_pod",
    "select",
    "ShardedScheduler",
]

# Cluster-dict arrays whose dim 0 is the node axis (ClusterEncoding node
# rows). Everything else — pod rows, term tables, vocab-indexed vectors,
# scalars — is replicated.
NODE_DIM0_KEYS = frozenset(
    {
        "valid", "alloc", "requested", "nz_requested", "pod_count",
        "allowed_pods", "unschedulable", "taints", "ports_triple",
        "ports_pair_any", "ports_pair_wild", "npair", "nkey", "pair_of_key",
        "nnum", "nnum_valid", "img_size", "avoid",
    }
)


def make_mesh(devices=None, n_devices: Optional[int] = None) -> Mesh:
    """1-D device mesh over the node axis.

    With no explicit count, `KTPU_MESH_DEVICES` picks how many local
    devices to span (0/unset = all). On a CPU host, export
    `XLA_FLAGS=--xla_force_host_platform_device_count=8` before jax
    imports to simulate an 8-device mesh (tests/conftest.py forces this
    for tier-1).
    """
    if devices is None:
        if n_devices is None:
            n_devices = knobs.get_int("KTPU_MESH_DEVICES") or None
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_capacity_multiple(mesh: Mesh) -> int:
    return int(mesh.devices.size)


def pad_node_axis(cluster: Dict, multiple: int,
                  headroom: Optional[float] = None) -> Dict:
    """Pad node-axis arrays so dim 0 divides the shard count, with
    growth headroom quantized to shard multiples.

    Padding rows are all-zero: `valid` stays False so padded nodes are
    infeasible, and id columns hit the vocab null sentinel (id 0).
    `headroom` (default `KTPU_NODE_HEADROOM`) over-pads by a fraction of
    the live node count so later node adds stay inside the same padded
    shape — the delta-class envelope at 100k nodes.
    """
    n = cluster["valid"].shape[0]
    h = node_headroom() if headroom is None else max(0.0, headroom)
    want = max(n, int(-(-n * (1.0 + h) // 1)))
    target = -(-want // multiple) * multiple
    if target == n:
        return cluster
    out = dict(cluster)
    for k in NODE_DIM0_KEYS:
        v = cluster[k]
        widths = [(0, target - n)] + [(0, 0)] * (v.ndim - 1)
        out[k] = jnp.pad(v, widths)
    return out


def shard_cluster(cluster: Dict, mesh: Mesh) -> Dict:
    """Place the cluster dict: node rows split over the mesh, rest
    replicated — placements declared by CLUSTER_PARTITION_RULES
    (parallel/partition.py), not per-key wiring."""
    cluster = pad_node_axis(cluster, node_capacity_multiple(mesh))
    return shard_tree(dict(cluster), CLUSTER_PARTITION_RULES, mesh)


def replicate_pod(pod_arrays: Dict, mesh: Mesh) -> Dict:
    """Replicate the pending pod's encoded arrays across the mesh."""
    repl = NamedSharding(mesh, P())
    return {
        k: jax.device_put(np.asarray(v), repl)
        for k, v in pod_arrays.items()
        if not k.startswith("_")
    }


def select(out: Dict) -> Dict:
    """Device-side reduction: best node (max total, lowest index wins ties)
    plus the feasible count. Ties must be broken by reservoir sampling for
    Go parity (core/generic_scheduler.go:152 selectHost) — callers needing
    identical decisions pull `total` back and sample host-side; this
    reduction is the fast path and the cross-shard collective."""
    total = out["total"]
    best_score = jnp.max(total)
    best_idx = jnp.argmax(total)
    return {
        "best_score": best_score,
        "best_idx": best_idx,
        "n_feasible": jnp.sum(out["feasible"].astype(jnp.int32)),
    }


@functools.partial(jax.jit, static_argnames=("weights_key",))
def _kernel_with_select(c, p, weights_key):
    out = schedule_pod(c, p, dict(weights_key))
    out.update(select(out))
    return out


class ShardedScheduler:
    """Holds a mesh and dispatches scheduling cycles over it.

    One instance per process; the jitted kernel is compiled per
    (array-shape-bucket, weights) combination and cached by jax.
    """

    def __init__(self, mesh: Optional[Mesh] = None, weights: Optional[Dict[str, int]] = None):
        self.mesh = mesh or make_mesh()
        self.weights_key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))

    def schedule(self, cluster: Dict, pod_arrays: Dict) -> Dict:
        c = shard_cluster(cluster, self.mesh)
        p = replicate_pod(pod_arrays, self.mesh)
        return _kernel_with_select(c, p, self.weights_key)

    def session(self, cluster: Dict, template_arrays_list, weights=None):
        """Cross-batch hoisted SESSION over the mesh: the same
        HoistedSession object (ops/hoisted.py), built on node-sharded
        cluster arrays. The device-resident carry (utilization + PTS/IPA
        counts + port tables) and every per-step mask/score inherit
        shardings through GSPMD — normalization maxima, count scatters,
        and the per-step argmax lower to collectives over ICI, exactly
        the "full sequence length" design of SURVEY §5 (score ALL nodes,
        reduce across shards). Decisions are bit-identical to the
        single-device session (tests/test_sharded.py session parity,
        __graft_entry__.dryrun_multichip at 512 nodes)."""
        from ..ops import hoisted

        c = shard_cluster(cluster, self.mesh)
        return hoisted.HoistedSession(c, template_arrays_list, weights)

    def schedule_batch_hoisted(self, cluster: Dict, pod_arrays_list):
        """Template-hoisted batched scan over the mesh: node-axis arrays
        sharded, templates/batch rows replicated. The prologue's pod-table
        sweeps run replicated; per-node masks/scores and the in-scan
        normalization max/min and count scatters become GSPMD collectives
        over ICI. Decisions are bit-identical to the single-device scan
        (tests/test_hoisted.py::TestShardedHoisted). Returns
        (decisions, ys) — the same contract as
        ops.hoisted.schedule_batch_hoisted, so callers are swappable."""
        from ..ops import hoisted

        tp, batch_self, xs, templates = hoisted.prepare_batch(pod_arrays_list)
        dyn_ipa = hoisted.templates_have_terms(templates)
        dyn_ports = hoisted.templates_have_ports(templates)
        port_adds = (
            hoisted._port_adds_for(templates, cluster) if dyn_ports else None
        )
        c = shard_cluster(cluster, self.mesh)
        tp = replicate_pod(tp, self.mesh)
        batch_self = replicate_pod(batch_self, self.mesh)
        xs = replicate_pod(xs, self.mesh)
        if port_adds is not None:
            port_adds = tuple(replicate_pod({"a": p}, self.mesh)["a"] for p in port_adds)
        _, ys = hoisted._run(
            c, tp, batch_self, xs, self.weights_key, dyn_ipa, dyn_ports, port_adds
        )
        return [int(v) for v in np.asarray(ys["best"])], ys
