"""Declarative GSPMD partitioning: regex-on-leaf-path -> PartitionSpec.

Before this module, every node-sharded array in the mesh path was
hand-wired: `ops/sharded_scan.py` kept a `_NODE_DIM` placement dict that
had to be edited in lock-step with every new static, and
`parallel/sharded.py` kept a parallel `NODE_DIM0_KEYS` frozenset for the
cluster dict. State added since PR 5 (delta statics, multipod conflict
tables, what-if scratch carries, the explain harvest) each needed a
matching hand edit — at 100k nodes a forgotten entry silently replicates
a [rows, N] array onto every host.

The declarative form is the `match_partition_rules` pattern from large
LM trainers: flatten the pytree with key paths, join each path into a
`/`-separated name, and take the first regex rule that matches. Scalars
short-circuit to replicated. An unmatched leaf is an ERROR, not a
default — new state must name its placement (one line in a rule table)
or construction fails loudly.

Two rule tables live here:

- `CLUSTER_PARTITION_RULES` — the ClusterEncoding device dict: node rows
  (dim 0 = node axis) sharded, pod/term/vocab state replicated.
- `SESSION_PARTITION_RULES` — the sharded session's grouped tree
  (`statics/`, `tables/`, `carry/`, `delta/`, `xs/`): per-node statics
  and carries split along their node axis, score tables and batch rows
  replicated. The specs reproduce the old `_NODE_DIM` placements
  exactly (pinned by tests/test_mesh_partition.py).

`shard_map` moved out of `jax.experimental` upstream; `shard_map_compat`
resolves whichever home this jax has and maps the replication-check
kwarg (`check_vma` on new jax, `check_rep` on 0.4.x) so the sharded
session runs on both.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def tree_path_to_string(path: Tuple, sep: str = "/") -> str:
    """Join a jax key path into a readable `/`-separated name."""
    keys = []
    for key in path:
        if isinstance(key, jax.tree_util.SequenceKey):
            keys.append(str(key.idx))
        elif isinstance(key, jax.tree_util.DictKey):
            keys.append(str(key.key))
        elif isinstance(key, jax.tree_util.GetAttrKey):
            keys.append(str(key.name))
        elif isinstance(key, jax.tree_util.FlattenedIndexKey):
            keys.append(str(key.key))
        else:
            keys.append(str(key))
    return sep.join(keys)


def named_tree_map(f: Callable, tree: Any, *rest, is_leaf=None,
                   sep: str = "/") -> Any:
    """tree_map where `f` receives (path-name, leaf, *rest-leaves)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x, *r: f(tree_path_to_string(path, sep=sep), x, *r),
        tree, *rest, is_leaf=is_leaf)


def match_partition_rules(rules: List[Tuple[str, P]], tree: Any,
                          sep: str = "/") -> Any:
    """PartitionSpec tree for `tree`: first rule whose regex matches the
    leaf's path name wins; 0-d / 1-element leaves are replicated without
    consulting the rules; a leaf no rule covers raises ValueError (new
    state MUST declare its placement)."""

    def get_partition_spec(name, leaf):
        if np.ndim(leaf) == 0 or np.prod(np.shape(leaf)) == 1:
            return P()
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"partition rule not found for leaf: {name}")

    return named_tree_map(get_partition_spec, tree, sep=sep)


def make_shard_and_gather_fns(partition_specs: Any, mesh: Mesh):
    """Per-leaf placement/readback fns for a spec tree.

    shard_fns[leaf](x) puts x on the mesh under its NamedSharding;
    gather_fns[leaf](x) pulls the full (unsharded) value back to host
    numpy. Trees mirror `partition_specs`.
    """

    def make_shard_fn(spec: P):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x):
            return jax.device_put(jnp.asarray(x), sharding)

        return shard_fn

    def make_gather_fn(spec: P):
        def gather_fn(x):
            return jax.device_get(x)

        return gather_fn

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    shard_fns = jax.tree_util.tree_map(make_shard_fn, partition_specs,
                                       is_leaf=is_spec)
    gather_fns = jax.tree_util.tree_map(make_gather_fn, partition_specs,
                                        is_leaf=is_spec)
    return shard_fns, gather_fns


def shard_tree(tree: Any, rules: List[Tuple[str, P]], mesh: Mesh) -> Any:
    """match + place in one call: every leaf of `tree` lands on `mesh`
    under its matched spec."""
    specs = match_partition_rules(rules, tree)
    shard_fns, _ = make_shard_and_gather_fns(specs, mesh)
    return jax.tree_util.tree_map(lambda f, x: f(x), shard_fns, tree)


# ---------------------------------------------------------------------------
# Rule tables
# ---------------------------------------------------------------------------

# ClusterEncoding device-dict: arrays whose dim 0 is the node axis. The
# name list mirrors ClusterEncoding._NODE_ROW_KEYS; everything else
# (pod rows, term tables, vocab-indexed vectors, scalars) replicates.
_CLUSTER_NODE_KEYS = (
    "valid", "alloc", "requested", "nz_requested", "pod_count",
    "allowed_pods", "unschedulable", "taints", "ports_triple",
    "ports_pair_any", "ports_pair_wild", "npair", "nkey", "pair_of_key",
    "nnum", "nnum_valid", "img_size", "avoid",
)

CLUSTER_PARTITION_RULES: List[Tuple[str, P]] = [
    (r"^(%s)$" % "|".join(_CLUSTER_NODE_KEYS), P(NODE_AXIS)),
    (r".*", P()),
]

# ShardedPallasSession grouped tree. Node-axis positions mirror the
# session layouts: carries and most statics are [rows, N]; the stat /
# IPA blocks are template-major [T, rows, N]; onehot is [K, N, VZ].
SESSION_PARTITION_RULES: List[Tuple[str, P]] = [
    # carries: requested/nzpc/cnt_fn/cnt_sn [rows, N]; ucnt [UR, N];
    # kcnt [UR, nsh] keeps one per-shard partial column per device
    (r"^carry/", P(None, NODE_AXIS)),
    # template-major static blocks, node axis last
    (r"^statics/(stat|ipa_stat|anti_static|anti_konn|aff_static)$",
     P(None, None, NODE_AXIS)),
    # zone one-hots [K, N, VZ]
    (r"^statics/onehot$", P(None, NODE_AXIS, None)),
    # replicated zone-validity rows [TCp, VZ] — vocab space, not nodes
    (r"^statics/zvalid_s_rows$", P()),
    # per-node row statics [rows, N]
    (r"^statics/(alloc|regrow_f|zvalid_node_s|konn_f|konn_s|shasall"
     r"|valid_n|prow_f|prow_s|prow_ipa)$", P(None, NODE_AXIS)),
    # delta statics: src factor rows are per-node, perno flags replicate
    (r"^delta/src_rows$", P(None, NODE_AXIS)),
    (r"^delta/", P()),
    # score/meta tables and batch rows replicate
    (r"^tables/", P()),
    (r"^xs/", P()),
]


def session_specs(group: str, tree: Dict) -> Dict:
    """Spec dict for one session group ('statics'/'tables'/'carry'/
    'delta'/'xs') — usable both at placement time (numpy leaves) and
    inside jit for shard_map in/out specs (tracer leaves)."""
    return match_partition_rules(SESSION_PARTITION_RULES,
                                 {group: tree})[group]


# ---------------------------------------------------------------------------
# shard_map compat (jax moved it out of experimental; the replication
# check kwarg was renamed check_rep -> check_vma along the way)
# ---------------------------------------------------------------------------

def shard_map_compat(f, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
