"""Taint / toleration matching.

Reference: staging/src/k8s.io/api/core/v1/toleration.go ToleratesTaint and
pkg/apis/core/v1/helper/helpers.go TolerationsTolerateTaint /
FindMatchingUntoleratedTaint — used by the TaintToleration plugin
(pkg/scheduler/framework/plugins/tainttoleration/taint_toleration.go:55).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from .types import Taint, Toleration

TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


def toleration_tolerates_taint(toleration: Toleration, taint: Taint) -> bool:
    """toleration.go:30 ToleratesTaint."""
    if toleration.effect and toleration.effect != taint.effect:
        return False
    if toleration.key and toleration.key != taint.key:
        return False
    # an empty key with operator Exists matches all keys
    if toleration.operator == TOLERATION_OP_EXISTS:
        return True
    if toleration.operator in ("", TOLERATION_OP_EQUAL):
        return toleration.value == taint.value
    return False


def tolerations_tolerate_taint(
    tolerations: Optional[List[Toleration]], taint: Taint
) -> bool:
    return any(toleration_tolerates_taint(t, taint) for t in tolerations or [])


def find_matching_untolerated_taint(
    taints: Optional[List[Taint]],
    tolerations: Optional[List[Toleration]],
    inclusion_filter: Optional[Callable[[Taint], bool]] = None,
) -> Tuple[Optional[Taint], bool]:
    """helpers.go FindMatchingUntoleratedTaint: first filtered taint not
    tolerated; returns (taint, True) if found."""
    for taint in taints or []:
        if inclusion_filter is not None and not inclusion_filter(taint):
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint, True
    return None, False
