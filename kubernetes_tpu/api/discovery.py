"""discovery.k8s.io EndpointSlice types.

Reference: staging/src/k8s.io/api/discovery/v1beta1/types.go — EndpointSlice
(:33) with AddressType, Endpoints[] (:87 Endpoint: Addresses, Conditions,
Topology/NodeName, TargetRef) and Ports[]; slices are tied to their Service
by the kubernetes.io/service-name label (:169 LabelServiceName). The
endpointslice controller caps endpoints per slice at 100 by default
(pkg/controller/endpointslice/endpointslice_controller.go:61
maxEndpointsPerSlice default).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import ObjectMeta

LABEL_SERVICE_NAME = "kubernetes.io/service-name"
MAX_ENDPOINTS_PER_SLICE = 100


@dataclass
class EndpointConditions:
    ready: bool = True


@dataclass
class Endpoint:
    addresses: List[str] = field(default_factory=list)
    conditions: EndpointConditions = field(default_factory=EndpointConditions)
    node_name: str = ""
    target_ref_name: str = ""  # pod name (flattened ObjectReference)
    target_ref_namespace: str = ""
    topology: Optional[Dict[str, str]] = None


@dataclass
class EndpointSlicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0


@dataclass
class EndpointSlice:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: Optional[List[Endpoint]] = None
    ports: Optional[List[EndpointSlicePort]] = None
    kind: str = "EndpointSlice"
    api_version: str = "discovery.k8s.io/v1beta1"
