"""apps/v1 workload types: ReplicaSet, Deployment, DaemonSet, StatefulSet.

Hand-written equivalents of the reference's apps group structs
(reference: staging/src/k8s.io/api/apps/v1/types.go). Only the fields the
controllers reconcile on are carried; everything round-trips through
utils.serde with camelCase keys like the reference's JSON tags.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import LabelSelector, ObjectMeta, PodTemplateSpec

# ---------------------------------------------------------------------------
# ReplicaSet (reference: apps/v1/types.go ReplicaSet)


@dataclass
class ReplicaSetSpec:
    replicas: Optional[int] = None  # default 1
    min_ready_seconds: int = 0
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    fully_labeled_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)
    kind: str = "ReplicaSet"
    api_version: str = "apps/v1"


# ---------------------------------------------------------------------------
# Deployment (reference: apps/v1/types.go Deployment; RollingUpdate strategy)


@dataclass
class RollingUpdateDeployment:
    max_unavailable: Optional[str] = None  # int or percent string, default 25%
    max_surge: Optional[str] = None  # default 25%


@dataclass
class DeploymentStrategy:
    type: str = "RollingUpdate"  # RollingUpdate | Recreate
    rolling_update: Optional[RollingUpdateDeployment] = None


@dataclass
class DeploymentSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    min_ready_seconds: int = 0
    revision_history_limit: Optional[int] = None
    paused: bool = False


@dataclass
class DeploymentStatus:
    observed_generation: int = 0
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)
    kind: str = "Deployment"
    api_version: str = "apps/v1"


# ---------------------------------------------------------------------------
# DaemonSet (reference: apps/v1/types.go DaemonSet)


@dataclass
class DaemonSetSpec:
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    min_ready_seconds: int = 0


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    number_misscheduled: int = 0
    desired_number_scheduled: int = 0
    number_ready: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)
    kind: str = "DaemonSet"
    api_version: str = "apps/v1"


# ---------------------------------------------------------------------------
# StatefulSet (reference: apps/v1/types.go StatefulSet; ordered identity)


@dataclass
class StatefulSetSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"  # OrderedReady | Parallel


@dataclass
class StatefulSetStatus:
    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)
    kind: str = "StatefulSet"
    api_version: str = "apps/v1"
