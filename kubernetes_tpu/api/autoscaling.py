"""autoscaling/v1 HorizontalPodAutoscaler types.

Reference: staging/src/k8s.io/api/autoscaling/v1/types.go —
HorizontalPodAutoscaler (:33) with ScaleTargetRef, Min/MaxReplicas,
TargetCPUUtilizationPercentage; status CurrentReplicas/DesiredReplicas/
CurrentCPUUtilizationPercentage/LastScaleTime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .types import ObjectMeta


@dataclass
class CrossVersionObjectReference:
    kind: str = ""
    name: str = ""
    api_version: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference
    )
    min_replicas: Optional[int] = None  # default 1
    max_replicas: int = 0
    target_cpu_utilization_percentage: Optional[int] = None  # default 80


@dataclass
class HorizontalPodAutoscalerStatus:
    observed_generation: Optional[int] = None
    last_scale_time: Optional[float] = None
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscaler:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalPodAutoscalerSpec = field(
        default_factory=HorizontalPodAutoscalerSpec
    )
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus
    )
    kind: str = "HorizontalPodAutoscaler"
    api_version: str = "autoscaling/v1"
