"""Label selector semantics.

Reimplements apimachinery label selection exactly as the scheduler consumes
it (reference: staging/src/k8s.io/apimachinery/pkg/labels/selector.go
Requirement.Matches; metav1.LabelSelectorAsSelector in
staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/helpers.go; node selector
term matching in pkg/apis/core/v1/helper/helpers.go MatchNodeSelectorTerms).

Key subtleties preserved:
  - a nil LabelSelector matches NOTHING; an empty one matches EVERYTHING
  - NotIn / DoesNotExist match when the key is absent
  - Gt/Lt parse both sides as integers and fail the match on parse error
  - node selector terms are ORed; expressions within a term are ANDed;
    a term with no expressions and no fields matches nothing
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .types import (
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
)

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


def requirement_matches(
    key: str, operator: str, values: Optional[List[str]], labels: Dict[str, str]
) -> bool:
    """One selector requirement against a label set (selector.go:194 Matches)."""
    values = values or []
    has = key in labels
    if operator == IN:
        return has and labels[key] in values
    if operator == NOT_IN:
        return (not has) or labels[key] not in values
    if operator == EXISTS:
        return has
    if operator == DOES_NOT_EXIST:
        return not has
    if operator in (GT, LT):
        if not has or len(values) != 1:
            return False
        lhs = _parse_int64(labels[key])
        rhs = _parse_int64(values[0])
        if lhs is None or rhs is None:
            return False
        return lhs > rhs if operator == GT else lhs < rhs
    return False


_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


def _parse_int64(s: str) -> Optional[int]:
    """strconv.ParseInt(s, 10, 64) semantics: optional sign + ASCII digits
    only (no whitespace, underscores, or unicode digits), must fit int64."""
    if not s:
        return None
    body = s[1:] if s[0] in "+-" else s
    if not body or not all("0" <= c <= "9" for c in body):
        return None
    v = int(s)
    if v < _INT64_MIN or v > _INT64_MAX:
        return None
    return v


def _split_requirements(s: str):
    """Split on commas not inside `in (...)` value parentheses."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


class Selector:
    """Compiled label selector: a conjunction of requirements.

    Mirrors labels.Selector. Use Selector.from_label_selector for the
    metav1.LabelSelector conversion (nil -> matches nothing).
    """

    __slots__ = ("requirements", "_matches_nothing")

    def __init__(self, requirements, matches_nothing: bool = False):
        self.requirements = requirements  # list of (key, op, values)
        self._matches_nothing = matches_nothing

    @classmethod
    def nothing(cls) -> "Selector":
        return cls([], matches_nothing=True)

    @classmethod
    def everything(cls) -> "Selector":
        return cls([])

    @classmethod
    def from_label_selector(cls, sel: Optional[LabelSelector]) -> "Selector":
        """metav1.LabelSelectorAsSelector (helpers.go:34)."""
        if sel is None:
            return cls.nothing()
        reqs = []
        for k, v in sorted((sel.match_labels or {}).items()):
            reqs.append((k, IN, [v]))
        for expr in sel.match_expressions or []:
            reqs.append((expr.key, expr.operator, list(expr.values or [])))
        return cls(reqs)

    @classmethod
    def from_match_labels(cls, match_labels: Optional[Dict[str, str]]) -> "Selector":
        """labels.SelectorFromSet — nil/empty set matches everything."""
        reqs = [(k, IN, [v]) for k, v in sorted((match_labels or {}).items())]
        return cls(reqs)

    @classmethod
    def parse(cls, selector: str) -> "Selector":
        """labels.Parse (selector.go:852): the string grammar used by
        `kubectl -l` / list options — comma-joined requirements of the
        forms `k=v`, `k==v`, `k!=v`, `k in (a,b)`, `k notin (a,b)`, `k`
        (exists), `!k` (does not exist), `k>n`, `k<n`."""
        import re

        set_req = re.compile(
            r"^(?P<key>\S+)\s+(?P<op>in|notin)\s*\((?P<vals>[^)]*)\)$", re.IGNORECASE
        )
        reqs = []
        for part in _split_requirements(selector):
            part = part.strip()
            if not part:
                continue
            m = set_req.match(part)
            if m:
                # the real lexer tokenizes on '(' so `k in(a,b)` is valid
                op = IN if m.group("op").lower() == "in" else NOT_IN
                values = [v.strip() for v in m.group("vals").split(",") if v.strip()]
                reqs.append((m.group("key"), op, values))
                continue
            for token, op in (("!=", NOT_IN), ("==", IN), ("=", IN), (">", GT), ("<", LT)):
                idx = part.find(token)
                if idx > 0:
                    reqs.append(
                        (part[:idx].strip(), op, [part[idx + len(token):].strip()])
                    )
                    break
            else:
                if part.startswith("!"):
                    reqs.append((part[1:].strip(), DOES_NOT_EXIST, []))
                else:
                    reqs.append((part, EXISTS, []))
        return cls(reqs)

    def matches(self, labels: Optional[Dict[str, str]]) -> bool:
        if self._matches_nothing:
            return False
        labels = labels or {}
        return all(
            requirement_matches(k, op, vals, labels) for (k, op, vals) in self.requirements
        )

    def is_everything(self) -> bool:
        return not self._matches_nothing and not self.requirements


def _node_selector_requirements_match(
    reqs: Optional[List[NodeSelectorRequirement]], labels: Dict[str, str]
) -> bool:
    return all(
        requirement_matches(r.key, r.operator, r.values, labels) for r in reqs or []
    )


def match_node_selector_terms(
    terms: Optional[List[NodeSelectorTerm]],
    node_labels: Dict[str, str],
    node_fields: Dict[str, str],
) -> bool:
    """OR over terms, AND within (helpers.go MatchNodeSelectorTerms).

    Terms with neither expressions nor fields match nothing; an overall
    empty/None term list matches nothing.
    """
    for term in terms or []:
        if not term.match_expressions and not term.match_fields:
            continue
        if not _node_selector_requirements_match(term.match_expressions, node_labels):
            continue
        if not _node_selector_requirements_match(term.match_fields, node_fields):
            continue
        return True
    return False


def node_fields(node: Node) -> Dict[str, str]:
    return {"metadata.name": node.metadata.name}


def pod_matches_node_selector_and_affinity(pod: Pod, node: Node) -> bool:
    """PodMatchesNodeSelectorAndAffinityTerms
    (reference: pkg/scheduler/framework/plugins/helper/node_affinity.go:27).

    nodeSelector (all labels must be present) AND required node affinity.
    """
    labels = node.metadata.labels or {}
    if pod.spec.node_selector:
        for k, v in pod.spec.node_selector.items():
            if labels.get(k) != v:
                return False
    affinity = pod.spec.affinity
    if (
        affinity is not None
        and affinity.node_affinity is not None
        and affinity.node_affinity.required_during_scheduling_ignored_during_execution
        is not None
    ):
        required: NodeSelector = (
            affinity.node_affinity.required_during_scheduling_ignored_during_execution
        )
        return match_node_selector_terms(
            required.node_selector_terms, labels, node_fields(node)
        )
    return True
