"""resource.Quantity: exact fixed-point resource arithmetic.

Reimplements the semantics of apimachinery's resource.Quantity
(reference: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go)
that the scheduler depends on:

  - parse decimal SI ("100m", "2", "1.5", "2k", "3M"), binary SI
    ("1Ki", "2Gi"), and scientific notation ("12e6")
  - Value()      -> int64, ceil to integer   (quantity.go Value/ScaledValue(0))
  - MilliValue() -> int64, ceil(q * 1000)    (quantity.go MilliValue)

All scheduler math downstream is int64 milli-units (CPU) or bytes (memory),
mirroring framework.Resource (reference: pkg/scheduler/framework/types.go:318).
Exactness matters: binding-decision parity with the reference requires the
same integer values, so parsing uses rational arithmetic, never floats.
"""

from __future__ import annotations

import math
import re
from fractions import Fraction
from functools import lru_cache
from typing import Union

# Binary SI suffixes (quantity.go `BinarySI` format)
_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
# Decimal SI suffixes (`DecimalSI`)
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE])|[eE](?P<exp>[+-]?\d+))?$"
)


def parse_quantity(s: Union[str, int, float, "Quantity"]) -> Fraction:
    """Parse a quantity string to an exact Fraction."""
    if isinstance(s, Quantity):
        return s.rational
    if isinstance(s, int):
        return Fraction(s)
    if isinstance(s, float):
        return Fraction(str(s))
    return _parse_quantity_str(s)


@lru_cache(maxsize=8192)
def _parse_quantity_str(s: str) -> Fraction:
    # Fractions are immutable, so the cached value is safe to share.
    # Workloads repeat a handful of request strings ("100m", "128Mi", ...)
    # across every pod; the uncached Fraction math showed up in scheduler
    # hot-loop profiles (NodeInfo.add_pod -> calculate_resource).
    m = _QUANTITY_RE.match(s.strip())
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    value = Fraction(m.group("num"))
    if m.group("sign") == "-":
        value = -value
    suffix = m.group("suffix")
    exp = m.group("exp")
    if suffix in _BINARY_SUFFIXES:
        value *= _BINARY_SUFFIXES[suffix]
    elif suffix is not None:
        value *= _DECIMAL_SUFFIXES[suffix]
    elif exp is not None:
        value *= Fraction(10) ** int(exp)
    return value


def _ceil_int64(x: Fraction) -> int:
    """Round toward +inf to an integer (quantity.go roundUp semantics)."""
    return math.ceil(x)


class Quantity:
    """Immutable exact quantity. Hashable, comparable by value."""

    __slots__ = ("rational",)

    def __init__(self, value: Union[str, int, float, Fraction, "Quantity"]):
        if isinstance(value, Fraction):
            self.rational = value
        else:
            self.rational = parse_quantity(value)

    def value(self) -> int:
        """Integer value, rounded up (quantity.go Value)."""
        return _ceil_int64(self.rational)

    def milli_value(self) -> int:
        """Value * 1000 rounded up (quantity.go MilliValue)."""
        return _ceil_int64(self.rational * 1000)

    def scaled_value(self, scale: int) -> int:
        """Value / 10**scale, rounded up (quantity.go ScaledValue)."""
        return _ceil_int64(self.rational / Fraction(10) ** scale)

    def is_zero(self) -> bool:
        return self.rational == 0

    def __eq__(self, other) -> bool:
        if isinstance(other, Quantity):
            return self.rational == other.rational
        return NotImplemented

    def __lt__(self, other: "Quantity") -> bool:
        return self.rational < other.rational

    def __le__(self, other: "Quantity") -> bool:
        return self.rational <= other.rational

    def __hash__(self) -> int:
        return hash(self.rational)

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"

    def __str__(self) -> str:
        r = self.rational
        if r.denominator == 1:
            return str(r.numerator)
        milli = r * 1000
        if milli.denominator == 1:
            return f"{milli.numerator}m"
        return f"{float(r):g}"


@lru_cache(maxsize=8192)
def milli_value_of(q: Union[str, int, float, "Quantity"]) -> int:
    """MilliValue of a quantity literal, memoized by the literal.

    The string parse is already cached (_parse_quantity_str), but the
    Fraction multiply + ceil per MilliValue call was not — and it
    dominated calculate_resource in the completion worker's assume
    profile (workloads repeat a handful of request literals across
    every pod). Hashable literals only, which is what serde yields.
    """
    return _ceil_int64(parse_quantity(q) * 1000)


@lru_cache(maxsize=8192)
def value_of(q: Union[str, int, float, "Quantity"]) -> int:
    """Value (ceil to integer) of a quantity literal, memoized."""
    return _ceil_int64(parse_quantity(q))


def cpu_milli(requests: dict, key: str = "cpu") -> int:
    """CPU request in milli-cores from a resource map of quantity strings."""
    q = requests.get(key)
    return milli_value_of(q) if q is not None else 0


def mem_bytes(requests: dict, key: str = "memory") -> int:
    q = requests.get(key)
    return value_of(q) if q is not None else 0
