"""rbac.authorization.k8s.io types.

Reference: staging/src/k8s.io/api/rbac/v1/types.go — PolicyRule (:47),
Role (:106), ClusterRole (:155), RoleBinding (:123), ClusterRoleBinding
(:175), Subject (:77). Wildcards ("*") in verbs/resources/apiGroups
follow rbac/v1 semantics (VerbMatches/ResourceMatches in
plugin/pkg/auth/authorizer/rbac/rbac.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import ObjectMeta

ALL = "*"


@dataclass
class PolicyRule:
    verbs: List[str] = field(default_factory=list)
    api_groups: Optional[List[str]] = None
    resources: Optional[List[str]] = None
    resource_names: Optional[List[str]] = None
    non_resource_urls: Optional[List[str]] = None


@dataclass
class Subject:
    kind: str = ""  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""


@dataclass
class RoleRef:
    kind: str = ""  # Role | ClusterRole
    name: str = ""
    api_group: str = "rbac.authorization.k8s.io"


@dataclass
class Role:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: Optional[List[PolicyRule]] = None
    kind: str = "Role"
    api_version: str = "rbac.authorization.k8s.io/v1"


@dataclass
class AggregationRule:
    """rbac/v1 AggregationRule: label selectors over ClusterRoles whose
    rules the aggregation controller unions into this role (types.go
    AggregationRule; pkg/controller/clusterroleaggregation)."""

    # match-labels dicts (one per selector; the reference uses full
    # LabelSelectors — match_labels is the shape kube ships by default,
    # e.g. rbac.authorization.k8s.io/aggregate-to-admin: "true")
    cluster_role_selectors: Optional[List[Dict[str, str]]] = None


@dataclass
class ClusterRole:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: Optional[List[PolicyRule]] = None
    aggregation_rule: Optional[AggregationRule] = None
    kind: str = "ClusterRole"
    api_version: str = "rbac.authorization.k8s.io/v1"


@dataclass
class RoleBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: Optional[List[Subject]] = None
    role_ref: RoleRef = field(default_factory=RoleRef)
    kind: str = "RoleBinding"
    api_version: str = "rbac.authorization.k8s.io/v1"


@dataclass
class ClusterRoleBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: Optional[List[Subject]] = None
    role_ref: RoleRef = field(default_factory=RoleRef)
    kind: str = "ClusterRoleBinding"
    api_version: str = "rbac.authorization.k8s.io/v1"


@dataclass
class ServiceAccount:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    kind: str = "ServiceAccount"
    api_version: str = "v1"


def rule_matches(
    rule: PolicyRule, verb: str, resource: str, name: str = "", api_group: str = ""
) -> bool:
    """VerbMatches + APIGroupMatches + ResourceMatches + resourceNames
    (rbac.go:76-120). A rule with no apiGroups matches only the core
    group (""), matching the reference's required-field semantics."""
    if not any(v == ALL or v == verb for v in rule.verbs):
        return False
    groups = rule.api_groups if rule.api_groups is not None else [""]
    if not any(g == ALL or g == api_group for g in groups):
        return False
    resources = rule.resources or []
    if not any(r == ALL or r == resource for r in resources):
        return False
    if rule.resource_names:
        return name != "" and name in rule.resource_names
    return True
