from .quantity import Quantity, parse_quantity  # noqa: F401
from . import types  # noqa: F401
