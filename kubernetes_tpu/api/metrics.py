"""metrics.k8s.io types + the metrics-server equivalent.

Reference: staging/src/k8s.io/metrics/pkg/apis/metrics/v1beta1/types.go —
NodeMetrics (:27), PodMetrics (:62) with per-container usage; served by
metrics-server through the aggregator and consumed by HPA and
`kubectl top`. Here the types are ordinary resources and MetricsServer
is the scraper loop: it derives usage from an injectable per-pod usage
function (hollow clusters synthesize usage from requests) and writes
nodemetrics/podmetrics objects each period.
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils.metrics import Counter, Gauge, legacy_registry
from .quantity import Quantity
from .types import ObjectMeta

# -- controller-plane supervision metrics (controllers/manager.Supervisor) --
# Served through the same process-wide registry every component's /metrics
# handler exposes; the supervisor sets them on every crash/restart so a
# flapping loop is visible without log archaeology.

controller_restarts_total = legacy_registry.register(Counter(
    "controller_restarts_total",
    "Controller loops restarted by the supervisor after a crash.",
    ("controller",),
))
controller_healthy = legacy_registry.register(Gauge(
    "controller_healthy",
    "1 while the controller loop runs; 0 while crashed/awaiting restart.",
    ("controller",),
))


@dataclass
class ContainerMetrics:
    name: str = ""
    usage: Optional[Dict[str, str]] = None  # {"cpu": "100m", "memory": "64Mi"}


@dataclass
class NodeMetrics:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    timestamp: Optional[float] = None
    window: float = 10.0
    usage: Optional[Dict[str, str]] = None
    kind: str = "NodeMetrics"
    api_version: str = "metrics.k8s.io/v1beta1"


@dataclass
class PodMetrics:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    timestamp: Optional[float] = None
    window: float = 10.0
    containers: Optional[List[ContainerMetrics]] = None
    kind: str = "PodMetrics"
    api_version: str = "metrics.k8s.io/v1beta1"


def default_usage_fn(pod) -> Dict[str, str]:
    """Hollow-node usage synthesis: usage == requests (the most useful
    deterministic default for tests/benchmarks)."""
    cpu = 0
    mem = 0
    for c in pod.spec.containers or []:
        req = (c.resources.requests or {}) if c.resources else {}
        cpu += Quantity(req.get("cpu", 0)).milli_value()
        mem += Quantity(req.get("memory", 0)).value()
    return {"cpu": f"{cpu}m", "memory": str(mem)}


class MetricsServer:
    """Scrape loop: pods/nodes -> podmetrics/nodemetrics objects."""

    def __init__(
        self,
        clientset,
        usage_fn: Optional[Callable] = None,
        period: float = 10.0,
    ):
        self.client = clientset
        self.usage_fn = usage_fn or default_usage_fn
        self.period = period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def scrape_once(self) -> None:
        now = time.time()
        pods, _ = self.client.pods.list()
        per_node: Dict[str, Dict[str, int]] = {}
        pm_client = self.client.resource("podmetrics")
        for pod in pods:
            if pod.status.phase != "Running" or not pod.spec.node_name:
                continue
            usage = self.usage_fn(pod)
            node_acc = per_node.setdefault(
                pod.spec.node_name, {"cpu": 0, "memory": 0}
            )
            node_acc["cpu"] += Quantity(usage.get("cpu", 0)).milli_value()
            node_acc["memory"] += Quantity(usage.get("memory", 0)).value()
            pm = PodMetrics(
                metadata=ObjectMeta(
                    name=pod.metadata.name, namespace=pod.metadata.namespace
                ),
                timestamp=now,
                containers=[
                    ContainerMetrics(
                        name=(pod.spec.containers or [None])[0].name
                        if pod.spec.containers
                        else "c",
                        usage=usage,
                    )
                ],
            )
            self._upsert(pm_client, pm)
        nm_client = self.client.resource("nodemetrics")
        nodes, _ = self.client.nodes.list()
        for node in nodes:
            acc = per_node.get(node.metadata.name, {"cpu": 0, "memory": 0})
            nm = NodeMetrics(
                metadata=ObjectMeta(name=node.metadata.name),
                timestamp=now,
                usage={"cpu": f"{acc['cpu']}m", "memory": str(acc["memory"])},
            )
            self._upsert(nm_client, nm)
        # drop metrics for pods/nodes that no longer exist
        live = {
            (p.metadata.namespace, p.metadata.name)
            for p in pods
            if p.status.phase == "Running"
        }
        stale, _ = pm_client.list()
        for pm in stale:
            if (pm.metadata.namespace, pm.metadata.name) not in live:
                try:
                    pm_client.delete(pm.metadata.name, pm.metadata.namespace)
                except Exception:  # noqa: BLE001
                    pass
        live_nodes = {n.metadata.name for n in nodes}
        stale_nodes, _ = nm_client.list()
        for nm in stale_nodes:
            if nm.metadata.name not in live_nodes:
                try:
                    nm_client.delete(nm.metadata.name)
                except Exception:  # noqa: BLE001
                    pass

    @staticmethod
    def _upsert(client, obj) -> None:
        from ..apiserver.server import NotFound

        try:
            live = client.get(obj.metadata.name, obj.metadata.namespace)
            live.timestamp = obj.timestamp
            live.usage = getattr(obj, "usage", None)
            if hasattr(obj, "containers"):
                live.containers = obj.containers
            client.update(live)
        except NotFound:
            client.create(obj)


def pod_metrics_source(clientset):
    """HPA metrics source backed by the metrics API: pod -> CPU
    utilization %% of requests (replica_calculator's
    GetResourceUtilizationRatio numerator/denominator)."""

    def source(pod) -> Optional[int]:
        from ..apiserver.server import NotFound

        try:
            pm = clientset.resource("podmetrics").get(
                pod.metadata.name, pod.metadata.namespace
            )
        except NotFound:
            return None
        used = sum(
            Quantity((c.usage or {}).get("cpu", 0)).milli_value()
            for c in pm.containers or []
        )
        requested = 0
        for c in pod.spec.containers or []:
            req = (c.resources.requests or {}) if c.resources else {}
            requested += Quantity(req.get("cpu", 0)).milli_value()
        if requested == 0:
            return None
        return int(100 * used / requested)

    return source
