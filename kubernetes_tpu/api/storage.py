"""storage.k8s.io/v1 + scheduling.k8s.io/v1 types.

Reference: staging/src/k8s.io/api/storage/v1/types.go (StorageClass,
CSINode) and scheduling/v1/types.go (PriorityClass).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import ObjectMeta


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = "Immediate"  # Immediate | WaitForFirstConsumer
    reclaim_policy: str = "Delete"  # Delete | Retain
    # [{"matchLabelExpressions": [{"key": ..., "values": [...]}]}] — the
    # TopologySelectorTerm shape provisioners honour (storage/v1 types.go
    # AllowedTopologies).
    allowed_topologies: Optional[List[dict]] = None
    # storage/v1 StorageClass.AllowVolumeExpansion — gates the
    # persistentvolume-expander controller
    allow_volume_expansion: bool = False
    kind: str = "StorageClass"
    api_version: str = "storage.k8s.io/v1"

# Provisioner value that means "static provisioning only" (storage/v1).
PROVISIONER_NO_PROVISIONER = "kubernetes.io/no-provisioner"


@dataclass
class CSINodeDriver:
    name: str = ""
    node_id: str = ""
    count: Optional[int] = None  # allocatable volume count


@dataclass
class CSINodeSpec:
    drivers: Optional[List[CSINodeDriver]] = None


@dataclass
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CSINodeSpec = field(default_factory=CSINodeSpec)
    kind: str = "CSINode"
    api_version: str = "storage.k8s.io/v1"


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""
    preemption_policy: Optional[str] = None
    kind: str = "PriorityClass"
    api_version: str = "scheduling.k8s.io/v1"


# -- node.k8s.io/v1 RuntimeClass (staging/src/k8s.io/api/node/v1/types.go)


@dataclass
class RuntimeClassOverhead:
    pod_fixed: Optional[Dict[str, str]] = None


@dataclass
class RuntimeClassScheduling:
    node_selector: Optional[Dict[str, str]] = None
    tolerations: Optional[List] = None  # List[v1.Toleration]


@dataclass
class RuntimeClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    handler: str = ""
    overhead: Optional[RuntimeClassOverhead] = None
    scheduling: Optional[RuntimeClassScheduling] = None
    kind: str = "RuntimeClass"
    api_version: str = "node.k8s.io/v1"
