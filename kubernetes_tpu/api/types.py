"""Core API types (the v1 data model subset the control plane needs).

Hand-written equivalents of the reference's generated API structs
(reference: staging/src/k8s.io/api/core/v1/types.go). Resource maps are kept
as {name: quantity-string} and parsed to exact int64 via api.quantity at the
edges, mirroring how the reference carries resource.Quantity and converts to
framework.Resource int64 milli-units inside the scheduler
(pkg/scheduler/framework/types.go:318 Resource.Add).

JSON round-trip uses utils.serde (camelCase keys, omitempty) so objects are
wire-compatible in shape with the reference's REST API.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# meta/v1


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    creation_timestamp: Optional[float] = None  # unix seconds
    deletion_timestamp: Optional[float] = None
    labels: Optional[Dict[str, str]] = None
    annotations: Optional[Dict[str, str]] = None
    owner_references: Optional[List[OwnerReference]] = None
    finalizers: Optional[List[str]] = None


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = ""  # In | NotIn | Exists | DoesNotExist
    values: Optional[List[str]] = None


@dataclass
class LabelSelector:
    match_labels: Optional[Dict[str, str]] = None
    match_expressions: Optional[List[LabelSelectorRequirement]] = None


# ---------------------------------------------------------------------------
# Node


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: Optional[List[Taint]] = None
    pod_cidr: str = field(default="", metadata={"json": "podCIDR"})
    provider_id: str = field(default="", metadata={"json": "providerID"})


@dataclass
class ContainerImage:
    names: Optional[List[str]] = None
    size_bytes: int = 0


@dataclass
class NodeCondition:
    type: str = ""  # Ready | MemoryPressure | DiskPressure | PIDPressure | ...
    status: str = ""  # True | False | Unknown
    last_heartbeat_time: Optional[float] = None
    last_transition_time: Optional[float] = None
    reason: str = ""
    message: str = ""


@dataclass
class AttachedVolume:
    """core/v1 AttachedVolume (node.status.volumesAttached entries, kept
    by the attach/detach controller)."""

    name: str = ""
    device_path: str = ""


@dataclass
class NodeStatus:
    capacity: Optional[Dict[str, str]] = None
    allocatable: Optional[Dict[str, str]] = None
    conditions: Optional[List[NodeCondition]] = None
    images: Optional[List[ContainerImage]] = None
    phase: str = ""
    volumes_attached: Optional[List[AttachedVolume]] = None
    volumes_in_use: Optional[List[str]] = None


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"
    api_version: str = "v1"


# ---------------------------------------------------------------------------
# Pod spec: affinity


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = ""  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: Optional[List[str]] = None


@dataclass
class NodeSelectorTerm:
    match_expressions: Optional[List[NodeSelectorRequirement]] = None
    match_fields: Optional[List[NodeSelectorRequirement]] = None


@dataclass
class NodeSelector:
    node_selector_terms: Optional[List[NodeSelectorTerm]] = None


@dataclass
class PreferredSchedulingTerm:
    weight: int = 0  # 1-100
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[NodeSelector] = None
    preferred_during_scheduling_ignored_during_execution: Optional[
        List[PreferredSchedulingTerm]
    ] = None


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: Optional[List[str]] = None
    topology_key: str = ""


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 0  # 1-100
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required_during_scheduling_ignored_during_execution: Optional[
        List[PodAffinityTerm]
    ] = None
    preferred_during_scheduling_ignored_during_execution: Optional[
        List[WeightedPodAffinityTerm]
    ] = None


@dataclass
class PodAntiAffinity:
    required_during_scheduling_ignored_during_execution: Optional[
        List[PodAffinityTerm]
    ] = None
    preferred_during_scheduling_ignored_during_execution: Optional[
        List[WeightedPodAffinityTerm]
    ] = None


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class Toleration:
    key: str = ""
    operator: str = ""  # Exists | Equal (default Equal)
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = ""  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


# ---------------------------------------------------------------------------
# Pod spec: containers


@dataclass
class ContainerPort:
    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = field(default="", metadata={"json": "hostIP"})


@dataclass
class ResourceRequirements:
    limits: Optional[Dict[str, str]] = None
    requests: Optional[Dict[str, str]] = None


@dataclass
class Probe:
    """Liveness/readiness probe (core/v1 Probe; the exec handler is the
    one with runtime behavior here — CRI ExecSync)."""

    exec_command: Optional[List[str]] = None
    initial_delay_seconds: float = 0.0
    period_seconds: float = 10.0
    failure_threshold: int = 3
    success_threshold: int = 1


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: Optional[List[ContainerPort]] = None
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    image_pull_policy: str = ""  # "" (default by tag) | Always | IfNotPresent | Never
    # core/v1 SecurityContext subset, carried as a dict (privileged,
    # runAsNonRoot, allowPrivilegeEscalation, capabilities, ...)
    security_context: Optional[Dict[str, object]] = None


@dataclass
class Volume:
    name: str = ""
    # volume sources are opaque to the scheduler core; carried as a dict
    source: Optional[Dict[str, object]] = None


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: Optional[List[Container]] = None
    node_name: str = ""
    node_selector: Optional[Dict[str, str]] = None
    affinity: Optional[Affinity] = None
    tolerations: Optional[List[Toleration]] = None
    topology_spread_constraints: Optional[List[TopologySpreadConstraint]] = None
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: Optional[str] = None  # PreemptLowerPriority | Never
    scheduler_name: str = ""
    overhead: Optional[Dict[str, str]] = None
    runtime_class_name: Optional[str] = None  # node.k8s.io RuntimeClass
    host_network: bool = False
    host_pid: bool = False
    host_ipc: bool = False
    volumes: Optional[List[Volume]] = None
    restart_policy: str = "Always"
    termination_grace_period_seconds: Optional[int] = None
    service_account_name: str = ""
    automount_service_account_token: Optional[bool] = None


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    last_transition_time: Optional[float] = None
    reason: str = ""
    message: str = ""


@dataclass
class ContainerStatus:
    name: str = ""
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    state: str = ""  # waiting | running | terminated
    exit_code: Optional[int] = None


@dataclass
class PodStatus:
    phase: str = ""  # Pending | Running | Succeeded | Failed | Unknown
    conditions: Optional[List[PodCondition]] = None
    nominated_node_name: str = ""
    start_time: Optional[float] = None
    pod_ip: str = field(default="", metadata={"json": "podIP"})
    host_ip: str = field(default="", metadata={"json": "hostIP"})
    container_statuses: Optional[List[ContainerStatus]] = None
    reason: str = ""  # e.g. UnexpectedAdmissionError, Evicted
    message: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"
    api_version: str = "v1"


# Well-known labels (reference: staging/src/k8s.io/api/core/v1/well_known_labels.go)
# ---------------------------------------------------------------------------
# coordination.k8s.io/v1 Lease (leader election + node heartbeats;
# reference: staging/src/k8s.io/api/coordination/v1/types.go)


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: int = 0
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None
    lease_transitions: int = 0


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)
    kind: str = "Lease"
    api_version: str = "coordination.k8s.io/v1"


# ---------------------------------------------------------------------------
# policy/v1beta1 PodDisruptionBudget (subset preemption needs;
# reference: staging/src/k8s.io/api/policy/v1beta1/types.go)


@dataclass
class PodDisruptionBudgetSpec:
    min_available: Optional[str] = None  # int or percentage string
    max_unavailable: Optional[str] = None
    selector: Optional[LabelSelector] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)
    kind: str = "PodDisruptionBudget"
    api_version: str = "policy/v1beta1"


# ---------------------------------------------------------------------------
# Pod templates (workload controllers stamp pods from these;
# reference: staging/src/k8s.io/api/core/v1/types.go PodTemplateSpec)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# ---------------------------------------------------------------------------
# Service / Endpoints (reference: core/v1 Service, Endpoints)


@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: int = 0
    node_port: int = 0


@dataclass
class ServiceSpec:
    selector: Optional[Dict[str, str]] = None
    ports: Optional[List[ServicePort]] = None
    cluster_ip: str = field(default="", metadata={"json": "clusterIP"})
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    session_affinity: str = ""
    external_name: str = ""


@dataclass
class ServiceStatus:
    load_balancer_ingress: Optional[List[str]] = None


@dataclass
class ReplicationControllerSpec:
    replicas: Optional[int] = None
    selector: Optional[Dict[str, str]] = None  # map selector (core/v1)
    template: Optional[PodTemplateSpec] = None
    min_ready_seconds: int = 0


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    ready_replicas: int = 0


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicationControllerSpec = field(
        default_factory=ReplicationControllerSpec
    )
    status: ReplicationControllerStatus = field(
        default_factory=ReplicationControllerStatus
    )
    kind: str = "ReplicationController"
    api_version: str = "v1"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)
    kind: str = "Service"
    api_version: str = "v1"


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_ref_name: str = ""  # pod name (flattened ObjectReference)
    target_ref_namespace: str = ""


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: Optional[List[EndpointAddress]] = None
    not_ready_addresses: Optional[List[EndpointAddress]] = None
    ports: Optional[List[EndpointPort]] = None


@dataclass
class Endpoints:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: Optional[List[EndpointSubset]] = None
    kind: str = "Endpoints"
    api_version: str = "v1"


# ---------------------------------------------------------------------------
# Namespace (reference: core/v1 Namespace; finalizer-driven deletion)


@dataclass
class NamespaceSpec:
    finalizers: Optional[List[str]] = None


@dataclass
class NamespaceStatus:
    phase: str = ""  # Active | Terminating


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)
    kind: str = "Namespace"
    api_version: str = "v1"


# ---------------------------------------------------------------------------
# ConfigMap (reference: core/v1 ConfigMap)


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Optional[Dict[str, str]] = None
    kind: str = "ConfigMap"
    api_version: str = "v1"


@dataclass
class Secret:
    """core/v1 Secret (string data only; the service-account token
    controller's token secrets are the load-bearing use)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Optional[Dict[str, str]] = None
    type: str = "Opaque"
    kind: str = "Secret"
    api_version: str = "v1"


SECRET_TYPE_SERVICE_ACCOUNT_TOKEN = "kubernetes.io/service-account-token"
SERVICE_ACCOUNT_NAME_ANNOTATION = "kubernetes.io/service-account.name"


# ---------------------------------------------------------------------------
# Persistent volumes (subset VolumeBinding needs; reference: core/v1
# PersistentVolume/PersistentVolumeClaim + volume node affinity)


@dataclass
class VolumeNodeAffinity:
    required: Optional[NodeSelector] = None


@dataclass
class PersistentVolumeSpec:
    capacity: Optional[Dict[str, str]] = None
    access_modes: Optional[List[str]] = None
    storage_class_name: str = ""
    claim_ref_namespace: str = ""  # flattened ObjectReference to bound claim
    claim_ref_name: str = ""
    node_affinity: Optional[VolumeNodeAffinity] = None
    persistent_volume_reclaim_policy: str = ""
    # volume source (PersistentVolumeSource, types.go): the CSI member
    # carries scheduling semantics (driver -> attach limits); the three
    # in-tree cloud-disk members exist for CSI MIGRATION
    # (csi-translation-lib) — the scheduler sees them only through
    # volume/csi_translation.py's translated copies
    csi: Optional[Dict[str, str]] = None  # {driver, volumeHandle}
    gce_persistent_disk: Optional[Dict[str, str]] = None  # {pdName, fsType}
    aws_elastic_block_store: Optional[Dict[str, str]] = None  # {volumeID}
    azure_disk: Optional[Dict[str, str]] = None  # {diskName}


@dataclass
class PersistentVolumeStatus:
    phase: str = ""  # Pending | Available | Bound | Released | Failed


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(default_factory=PersistentVolumeStatus)
    kind: str = "PersistentVolume"
    api_version: str = "v1"


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: Optional[List[str]] = None
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    storage_class_name: Optional[str] = None
    volume_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = ""  # Pending | Bound | Lost
    # granted capacity (core/v1 PersistentVolumeClaimStatus.Capacity) —
    # the expand controller reconciles spec.resources.requests against it
    capacity: Optional[Dict[str, str]] = None
    conditions: Optional[List[PodCondition]] = None  # e.g. Resizing


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )
    kind: str = "PersistentVolumeClaim"
    api_version: str = "v1"


# ---------------------------------------------------------------------------
# ResourceQuota / LimitRange (reference: core/v1 ResourceQuota :5512,
# LimitRange :5415 in staging/src/k8s.io/api/core/v1/types.go)


@dataclass
class ResourceQuotaSpec:
    hard: Optional[Dict[str, str]] = None  # resource name -> quantity
    scopes: Optional[List[str]] = None


@dataclass
class ResourceQuotaStatus:
    hard: Optional[Dict[str, str]] = None
    used: Optional[Dict[str, str]] = None


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)
    kind: str = "ResourceQuota"
    api_version: str = "v1"


@dataclass
class LimitRangeItem:
    type: str = "Container"  # Container | Pod
    max: Optional[Dict[str, str]] = None
    min: Optional[Dict[str, str]] = None
    default: Optional[Dict[str, str]] = None  # default limits
    default_request: Optional[Dict[str, str]] = None


@dataclass
class LimitRangeSpec:
    limits: Optional[List[LimitRangeItem]] = None


@dataclass
class LimitRange:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)
    kind: str = "LimitRange"
    api_version: str = "v1"


LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"
LABEL_ZONE_LEGACY = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION_LEGACY = "failure-domain.beta.kubernetes.io/region"

TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

# Resource names (subset of v1.ResourceName)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"


def pod_key(pod: Pod) -> str:
    """namespace/name cache key (reference: framework.GetPodKey)."""
    return f"{pod.metadata.namespace}/{pod.metadata.name}"
