"""batch/v1 + batch/v1beta1 types: Job, CronJob.

Reference: staging/src/k8s.io/api/batch/v1/types.go (Job) and
batch/v1beta1/types.go (CronJob). Fields limited to what the job and
cronjob controllers reconcile on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .types import LabelSelector, ObjectMeta, PodTemplateSpec


@dataclass
class JobSpec:
    parallelism: Optional[int] = None  # default 1
    completions: Optional[int] = None  # default: == parallelism
    backoff_limit: Optional[int] = None  # default 6
    active_deadline_seconds: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    ttl_seconds_after_finished: Optional[int] = None


@dataclass
class JobCondition:
    type: str = ""  # Complete | Failed
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[float] = None


@dataclass
class JobStatus:
    conditions: Optional[List[JobCondition]] = None
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: Optional[float] = None
    completion_time: Optional[float] = None


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)
    kind: str = "Job"
    api_version: str = "batch/v1"


@dataclass
class CronJobSpec:
    schedule: str = ""  # cron format
    suspend: bool = False
    job_template_spec: JobSpec = field(default_factory=JobSpec)
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    successful_jobs_history_limit: Optional[int] = None
    failed_jobs_history_limit: Optional[int] = None


@dataclass
class CronJobStatus:
    last_schedule_time: Optional[float] = None
    active: Optional[List[str]] = None  # names of running Jobs


@dataclass
class CronJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)
    kind: str = "CronJob"
    api_version: str = "batch/v1beta1"
