"""networking.k8s.io/v1 types: NetworkPolicy, Ingress, IngressClass.

Reference: staging/src/k8s.io/api/networking/v1/types.go — NetworkPolicy
(:30) with PolicyTypes/Ingress/Egress rules over peers (podSelector /
namespaceSelector / ipBlock) and ports; Ingress (:393 area) with rules,
TLS, and the ingressClassName pointer; IngressClass (:550 area) with the
is-default-class annotation the DefaultIngressClass admission plugin
consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from .types import LabelSelector, ObjectMeta

# annotation marking the cluster-default IngressClass
# (ingressclass.go AnnotationIsDefaultIngressClass)
DEFAULT_INGRESS_CLASS_ANNOTATION = \
    "ingressclass.kubernetes.io/is-default-class"

POLICY_TYPE_INGRESS = "Ingress"
POLICY_TYPE_EGRESS = "Egress"


# -- NetworkPolicy (types.go:30) -------------------------------------------


@dataclass
class IPBlock:
    cidr: str = ""
    except_: Optional[List[str]] = field(
        default=None, metadata={"json": "except"}
    )


@dataclass
class NetworkPolicyPeer:
    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None
    ip_block: Optional[IPBlock] = None


@dataclass
class NetworkPolicyPort:
    protocol: str = "TCP"
    # None = every port; int = numeric; str = a NAMED container port,
    # resolved against the destination pod's container specs
    # (types.go IntOrString — networking/v1/types.go NetworkPolicyPort)
    port: Optional[Union[int, str]] = None
    end_port: Optional[int] = None  # inclusive range [port, endPort]


@dataclass
class NetworkPolicyIngressRule:
    # empty/missing from_ = every source; empty ports = every port
    from_: Optional[List[NetworkPolicyPeer]] = field(
        default=None, metadata={"json": "from"}
    )
    ports: Optional[List[NetworkPolicyPort]] = None


@dataclass
class NetworkPolicyEgressRule:
    to: Optional[List[NetworkPolicyPeer]] = None
    ports: Optional[List[NetworkPolicyPort]] = None


@dataclass
class NetworkPolicySpec:
    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    ingress: Optional[List[NetworkPolicyIngressRule]] = None
    egress: Optional[List[NetworkPolicyEgressRule]] = None
    # which directions this policy constrains; defaulted per types.go:
    # always Ingress, plus Egress when egress rules are present
    policy_types: Optional[List[str]] = None


@dataclass
class NetworkPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NetworkPolicySpec = field(default_factory=NetworkPolicySpec)
    kind: str = "NetworkPolicy"
    api_version: str = "networking.k8s.io/v1"


def effective_policy_types(spec: NetworkPolicySpec) -> List[str]:
    """types.go PolicyType defaulting: unset -> [Ingress] plus Egress
    iff egress rules exist."""
    if spec.policy_types:
        return list(spec.policy_types)
    out = [POLICY_TYPE_INGRESS]
    if spec.egress:
        out.append(POLICY_TYPE_EGRESS)
    return out


# -- Ingress (types.go Ingress area) ---------------------------------------


@dataclass
class ServiceBackendPort:
    name: str = ""
    number: int = 0


@dataclass
class IngressServiceBackend:
    name: str = ""  # Service name
    port: ServiceBackendPort = field(default_factory=ServiceBackendPort)


@dataclass
class IngressBackend:
    service: Optional[IngressServiceBackend] = None


@dataclass
class HTTPIngressPath:
    path: str = ""
    path_type: str = "Prefix"  # Exact | Prefix | ImplementationSpecific
    backend: IngressBackend = field(default_factory=IngressBackend)


@dataclass
class HTTPIngressRuleValue:
    paths: List[HTTPIngressPath] = field(default_factory=list)


@dataclass
class IngressRule:
    host: str = ""
    http: Optional[HTTPIngressRuleValue] = None


@dataclass
class IngressTLS:
    hosts: Optional[List[str]] = None
    secret_name: str = ""


@dataclass
class IngressSpec:
    ingress_class_name: Optional[str] = None
    default_backend: Optional[IngressBackend] = None
    rules: Optional[List[IngressRule]] = None
    tls: Optional[List[IngressTLS]] = None


@dataclass
class IngressPortStatus:
    port: int = 0
    protocol: str = "TCP"


@dataclass
class IngressLoadBalancerIngress:
    ip: str = ""
    hostname: str = ""
    ports: Optional[List[IngressPortStatus]] = None


@dataclass
class IngressStatus:
    load_balancer_ingress: Optional[List[IngressLoadBalancerIngress]] = None


@dataclass
class Ingress:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressSpec = field(default_factory=IngressSpec)
    status: IngressStatus = field(default_factory=IngressStatus)
    kind: str = "Ingress"
    api_version: str = "networking.k8s.io/v1"


# -- IngressClass ----------------------------------------------------------


@dataclass
class IngressClassParametersReference:
    api_group: str = ""
    kind: str = ""
    name: str = ""
    namespace: str = ""
    scope: str = "Cluster"


@dataclass
class IngressClassSpec:
    controller: str = ""
    parameters: Optional[IngressClassParametersReference] = None


@dataclass
class IngressClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressClassSpec = field(default_factory=IngressClassSpec)
    kind: str = "IngressClass"
    api_version: str = "networking.k8s.io/v1"
