"""certificates.k8s.io CertificateSigningRequest types.

Reference: staging/src/k8s.io/api/certificates/v1/types.go —
CertificateSigningRequest (:28) with Spec (request bytes, signerName,
usages, expirationSeconds, username/groups of the requester) and Status
(conditions Approved/Denied/Failed (:208), issued certificate bytes).

The TPU build's PKI is kubeadm.py's HMAC-signed identity records (an
X.509-shaped subset: CommonName/Organizations/NotAfter), so `request`
carries a JSON-encoded identity request and `certificate` the
JSON-encoded signed record — same object flow, same controller split
(signing vs approval vs cleanup), without an ASN.1 dependency.

Well-known signers (:41-60): kubernetes.io/kube-apiserver-client,
kubernetes.io/kube-apiserver-client-kubelet, kubernetes.io/kubelet-serving.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from .types import ObjectMeta

SIGNER_KUBE_APISERVER_CLIENT = "kubernetes.io/kube-apiserver-client"
SIGNER_KUBE_APISERVER_CLIENT_KUBELET = (
    "kubernetes.io/kube-apiserver-client-kubelet"
)
SIGNER_KUBELET_SERVING = "kubernetes.io/kubelet-serving"

APPROVED = "Approved"
DENIED = "Denied"
FAILED = "Failed"


@dataclass
class CertificateSigningRequestSpec:
    # JSON-encoded identity request: {"commonName": ..., "organizations":
    # [...]} (the CSR PEM's subject, in this build's record shape)
    request: str = ""
    signer_name: str = ""
    usages: Optional[List[str]] = None
    expiration_seconds: Optional[int] = None
    # requester identity, stamped by the apiserver in the reference
    # (types.go:89-99); callers set it from their authenticated user
    username: str = ""
    groups: Optional[List[str]] = None


@dataclass
class CertificateSigningRequestCondition:
    type: str = ""  # Approved | Denied | Failed
    status: str = "True"
    reason: str = ""
    message: str = ""
    last_update_time: Optional[float] = None


@dataclass
class CertificateSigningRequestStatus:
    conditions: Optional[List[CertificateSigningRequestCondition]] = None
    # JSON-encoded signed identity record (kubeadm.Certificate fields)
    certificate: str = ""


@dataclass
class CertificateSigningRequest:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CertificateSigningRequestSpec = field(
        default_factory=CertificateSigningRequestSpec
    )
    status: CertificateSigningRequestStatus = field(
        default_factory=CertificateSigningRequestStatus
    )
    kind: str = "CertificateSigningRequest"
    api_version: str = "certificates.k8s.io/v1"


def encode_request(common_name: str, organizations: List[str]) -> str:
    return json.dumps(
        {"commonName": common_name, "organizations": list(organizations)},
        sort_keys=True,
    )


def decode_request(request: str) -> dict:
    return json.loads(request)


def has_condition(csr: CertificateSigningRequest, cond_type: str) -> bool:
    return any(c.type == cond_type for c in csr.status.conditions or [])
