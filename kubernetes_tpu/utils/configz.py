"""/configz registry (component-base/configz equivalent).

Reference: staging/src/k8s.io/component-base/configz/configz.go — each
component installs its live ComponentConfig under a name; the /configz
handler serializes the whole map so operators can inspect the running
configuration.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict

from . import serde

_lock = threading.Lock()
_registry: Dict[str, Any] = {}


def install(name: str, config: Any) -> None:
    """Register (or replace) a component's live config object."""
    with _lock:
        _registry[name] = config


def install_knobs(name: str, **knobs: Any) -> None:
    """Merge key/value knobs into a named dict entry. The KTPU_* env-var
    surface registers its RUNTIME-EFFECTIVE values here (the resolved
    multipod k, speculation/whatif/session-delta switches, trace level,
    watchdog/drain timeouts) so a running scheduler's configuration is
    inspectable via /configz instead of invisible process environment.
    Multiple components (TPUBackend, Scheduler) contribute to one entry."""
    with _lock:
        entry = _registry.get(name)
        if not isinstance(entry, dict):
            entry = {}
            _registry[name] = entry
        entry.update(knobs)


def delete(name: str) -> None:
    with _lock:
        _registry.pop(name, None)


def delete_if_is(name: str, config: Any) -> None:
    """Remove the entry only if it is still this exact object — two
    components (test clusters) sharing a canonical name must not delete
    each other's live entry."""
    with _lock:
        if _registry.get(name) is config:
            _registry.pop(name, None)


def snapshot() -> Dict[str, Any]:
    """JSON-compatible view of every registered config (the /configz body)."""
    with _lock:
        return {name: serde.to_dict(cfg) for name, cfg in _registry.items()}


def handler_body() -> str:
    return json.dumps(snapshot(), indent=2, sort_keys=True)


def metricsz_body() -> str:
    """Prometheus text exposition of every registered scheduler_* metric
    (the /metricsz body). Served from the same debug HTTP surface as
    /configz so the drift/explain counters are scrapeable without a
    separate metrics server; the import is deferred because configz is
    otherwise metrics-free."""
    from . import metrics as metrics_mod
    from . import selfstats

    # process self-telemetry (RSS/fds/threads) refreshes at scrape time:
    # always-current gauges with no background sampler thread
    selfstats.refresh()
    return metrics_mod.legacy_registry.expose()
