"""Latency tracing with threshold logging (k8s.io/utils/trace equivalent).

Reference: the scheduler wraps each cycle in a utiltrace span and logs the
step breakdown only when it exceeds a threshold
(pkg/scheduler/core/generic_scheduler.go:96-97, 100ms); apiserver handlers
do the same per request (endpoints/handlers/create.go:52).

The structured sibling lives in utils/tracing.py (span recorder +
flight recorder): a Trace answers "was this ONE cycle slow?" at a log
line; record_spans() forwards its step breakdown into the flight
recorder so threshold traces and pipeline spans land in the same
exportable record.
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional, Tuple


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def record_spans(self, stage: str = "cycle") -> None:
        """Mirror the step breakdown into the flight recorder (one span
        per step, named "<trace>/<step>"); no-op when tracing is off."""
        from . import tracing

        if not tracing.enabled():
            return
        last = self.start
        for t, msg in self.steps:
            tracing.RECORDER.record(
                f"{self.name}/{msg}", stage, last, t - last,
                self.fields or None,
            )
            last = t

    def log_if_long(self, threshold: float, out=sys.stderr) -> bool:
        total = self.total_seconds()
        if total < threshold:
            return False
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        print(f'Trace "{self.name}" ({fields}): total {total*1000:.1f}ms', file=out)
        last = self.start
        for t, msg in self.steps:
            print(f"  step {((t - last) * 1000):.1f}ms: {msg}", file=out)
            last = t
        return True
