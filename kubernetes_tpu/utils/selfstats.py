"""Process self-telemetry gauges (process_* metrics on /metricsz).

The endurance soak's leak invariants (bounded RSS/fd/thread growth —
testing/invariants.py) read the SAME surface an operator scrapes instead
of poking process internals: `refresh()` samples the process and updates
the gauges, and `configz.metricsz_body()` calls it right before every
exposition so /metricsz is always current without a background sampler
thread.

Sources are Linux-first with portable fallbacks: RSS from
/proc/self/statm (resource.getrusage reports the PEAK, useless for a
growth invariant), fd count from /proc/self/fd, thread count from
threading (enumerate of live Python threads — the pipeline's workers,
binders, watch writers all register there).
"""

from __future__ import annotations

import os
import threading

from .metrics import Gauge, legacy_registry

process_rss = legacy_registry.register(
    Gauge(
        "process_resident_memory_bytes",
        "Resident set size of this process (from /proc/self/statm; 0 "
        "where /proc is unavailable). The soak's leak invariant bounds "
        "its first-window-to-last-window growth.",
        (),
    )
)
process_open_fds = legacy_registry.register(
    Gauge(
        "process_open_fds",
        "Open file descriptors of this process (from /proc/self/fd; 0 "
        "where /proc is unavailable). Sustained growth under churn = a "
        "leaked socket/stream per wave.",
        (),
    )
)
process_threads = legacy_registry.register(
    Gauge(
        "process_threads",
        "Live Python threads in this process (threading.active_count). "
        "The pipeline workers, binder pool, probe thread, and per-watch "
        "writer threads all count here; growth under churn = a worker "
        "restart or watch path leaking threads.",
        (),
    )
)

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> int:
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def refresh() -> None:
    """Sample the process into the gauges; called by metricsz_body()
    before every exposition. Cheap (two /proc reads) and must never
    raise into the metrics handler."""
    try:
        process_rss.set(rss_bytes())
        process_open_fds.set(open_fds())
        process_threads.set(threading.active_count())
    except Exception:  # noqa: BLE001 — telemetry is best-effort
        pass
