"""Central registry + accessors for every ``KTPU_*`` environment knob.

Before this module existed every knob was an ad-hoc ``os.environ`` read
with its default re-typed at each call site — ``KTPU_COLUMNAR_CACHE``
and ``KTPU_DRAIN_TIMEOUT`` were each parsed in multiple places, and a
knob was visible on ``/configz`` only if someone remembered to
``install_knobs`` it by hand. Now:

  - every knob is **declared once** here (name, type, default, doc);
  - call sites read through the typed accessors (``get_bool`` /
    ``get_int`` / ``get_float`` / ``get_str`` / ``get_flag``), which
    parse defensively (malformed values degrade to the default with a
    warning instead of failing an import — the tracing/devtime
    discipline, now uniform);
  - the whole registry self-installs as a live ``/configz`` entry
    (``ktpu-env``) showing each knob's *effective* value and whether it
    came from the environment or the default;
  - the README knob table is **rendered from this registry**
    (``markdown_table()``, ``scripts/lint.py --knob-table``) and the
    knob-registry checker (``kubernetes_tpu/analysis``) fails any PR
    where a knob is read outside this module, declared but missing from
    the README, or mentioned in the README without a declaration.

Defaults declared as ``DERIVED`` are resolved at the call site (e.g.
``KTPU_MULTIPOD_K`` depends on the platform, ``KTPU_DRAIN_TIMEOUT`` on
the watchdog budget); the accessor then requires an explicit
``default=`` from the caller so the derivation stays next to the code
that owns it — but the knob itself still registers here.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, Optional, Union

logger = logging.getLogger(__name__)

# sentinel for knobs whose default is computed at the call site
DERIVED = "(derived)"

_TRUE = frozenset(("1", "true", "on", "yes"))
_FALSE = frozenset(("0", "false", "off", "no"))


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "bool" | "int" | "float" | "str" | "flag"
    default: Union[str, int, float, bool, None]
    description: str

    @property
    def default_label(self) -> str:
        if self.default is DERIVED:
            return "*(derived)*"
        if self.default is None or self.default == "":
            return "*(unset)*"
        if self.kind == "bool":
            return "`1`" if self.default else "`0`"
        return f"`{self.default}`"


_REGISTRY: Dict[str, Knob] = {}


def _declare(name: str, kind: str, default, description: str) -> Knob:
    knob = Knob(name, kind, default, description)
    _REGISTRY[name] = knob
    return knob


def registry() -> Dict[str, Knob]:
    """Name -> Knob for every declared knob (insertion-ordered)."""
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# typed accessors

_UNSET = object()


def _declared(name: str) -> Knob:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"undeclared knob {name!r}: every KTPU_* env var must be "
            "declared in utils/knobs.py (the knob-registry checker "
            "enforces this)"
        ) from None


def _resolve_default(knob: Knob, override):
    if override is not _UNSET:
        return override
    if knob.default is DERIVED:
        raise ValueError(
            f"{knob.name} has a derived default; the call site must "
            "pass default= explicitly"
        )
    return knob.default


def get_raw(name: str) -> Optional[str]:
    """The raw environment string, or None when unset/empty."""
    _declared(name)
    raw = os.environ.get(name, "")
    return raw if raw != "" else None


def get_str(name: str, default=_UNSET) -> str:
    knob = _declared(name)
    raw = os.environ.get(name, "")
    if raw == "":
        return _resolve_default(knob, default) or ""
    return raw


def get_bool(name: str, default=_UNSET) -> bool:
    knob = _declared(name)
    raw = os.environ.get(name, "").strip().lower()
    fallback = bool(_resolve_default(knob, default))
    if raw == "":
        return fallback
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    logger.warning("invalid %s=%r; using %r", name, raw, fallback)
    return fallback


def get_int(name: str, default=_UNSET) -> Optional[int]:
    knob = _declared(name)
    raw = os.environ.get(name, "")
    fallback = _resolve_default(knob, default)
    if raw == "":
        return fallback
    try:
        return int(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %r", name, raw, fallback)
        return fallback


def get_float(name: str, default=_UNSET) -> Optional[float]:
    knob = _declared(name)
    raw = os.environ.get(name, "")
    fallback = _resolve_default(knob, default)
    if raw == "":
        return fallback
    try:
        return float(raw)
    except ValueError:
        logger.warning("invalid %s=%r; using %r", name, raw, fallback)
        return fallback


def get_flag(name: str) -> bool:
    """Truthy-if-set-nonempty (debug switches like KTPU_DEBUG_INVALIDATE)."""
    _declared(name)
    return os.environ.get(name, "") != ""


# ---------------------------------------------------------------------------
# the declarations — one line per knob, THE source of truth for defaults

# -- device backend / dispatch loop
_declare("KTPU_MULTIPOD_K", "int", DERIVED,
         "pods decided per fused scan step (default 4 on TPU, 1 on CPU; "
         "1 restores one-pod-per-step everywhere)")
_declare("KTPU_SPECULATION", "bool", True,
         "speculative dispatch: chain batch k+1 on the pre-harvest carry "
         "(0 serializes dispatch on harvest)")
_declare("KTPU_SESSION_DELTAS", "bool", True,
         "absorb batchable cluster events into the live session as carry "
         "deltas (0 forces rebuild-on-every-event)")
_declare("KTPU_MAX_QUEUED_DELTAS", "int", 4096,
         "queued-delta backstop: past this a rebuild is cheaper than the "
         "queue and the teardown path absorbs everything")
_declare("KTPU_WHATIF", "bool", DERIVED,
         "device-side preemption what-if planning (default on for TPU, "
         "off on CPU; 0 is the kill switch, 1 the CPU opt-in)")
_declare("KTPU_WATCHDOG_TIMEOUT", "float", 30.0,
         "max seconds any device wait (harvest/flush/probe) may take "
         "before the dispatch is declared a fault")
_declare("KTPU_DISPATCH_RETRIES", "int", 2,
         "bounded re-drives of a faulted dispatch before RETRY_NODE")
_declare("KTPU_RETRY_BASE", "float", 0.05,
         "dispatch retry backoff base seconds (capped exponential + jitter)")
_declare("KTPU_RETRY_MAX", "float", 2.0,
         "dispatch retry backoff cap seconds")
_declare("KTPU_DEMOTE_THRESHOLD", "int", 3,
         "consecutive device faults before the degradation ladder demotes "
         "one rung")
_declare("KTPU_PROBE_INTERVAL", "float", 1.0,
         "re-promotion canary probe cadence seconds")
_declare("KTPU_DRAIN_TIMEOUT", "float", DERIVED,
         "pipeline drain budget seconds (default max(30, 3x watchdog))")
_declare("KTPU_DEBUG_INVALIDATE", "flag", "",
         "debug: print a stack trace at every session teardown")

# -- kernels / sessions
_declare("KTPU_SCAN_UNROLL", "int", 1,
         "hoisted lax.scan unroll factor (compile time for fewer "
         "tunnel launches)")
_declare("KTPU_PALLAS_AOT", "bool", True,
         "AOT-compile + cache pallas executables per batch bucket "
         "(0 pins the lazy jit path)")
_declare("KTPU_PALLAS_GROUP", "int", 4,
         "pods per pallas loop iteration (manual unroll amortizing "
         "Mosaic bookkeeping)")
_declare("KTPU_PALLAS_SKIP", "str", "",
         "comma-separated kernel terms to skip (profiling only — "
         "decisions change)")
_declare("KTPU_COMPILATION_CACHE", "str", "",
         "jax persistent compilation cache dir (0/off disables; unset "
         "uses .xla_cache)")

# -- mesh / scale-out
_declare("KTPU_MESH_DEVICES", "int", 0,
         "local devices to span with the node-axis scoring mesh "
         "(0/unset = all)")
_declare("KTPU_NODE_HEADROOM", "float", 0.0,
         "node-axis growth headroom fraction: capacity targets "
         "n*(1+headroom) so node adds land in pre-padded lanes")

# -- scheduler cache
_declare("KTPU_COLUMNAR_CACHE", "bool", True,
         "mirror scheduler-cache hot state in columnar int64 arrays "
         "(0 pins the per-pod object path)")

# -- observability: flight recorder / device timeline
_declare("KTPU_TRACE", "int", 0,
         "flight-recorder level: 0 off, 1 per-stage spans, 2 + per-pod "
         "provenance")
_declare("KTPU_TRACE_CAPACITY", "int", 8192,
         "flight-recorder ring capacity (span events)")
_declare("KTPU_TRACE_DUMP_DIR", "str", "",
         "where fault-seam ring dumps land as JSON (unset = log only)")
_declare("KTPU_DEVTIME", "int", 0,
         "device-timeline level: 0 off, 1 per-launch submit/ready "
         "records, 2 + bounded jax profiler captures")
_declare("KTPU_DEVTIME_CAPACITY", "int", 4096,
         "device-timeline ring capacity (launch records)")
_declare("KTPU_DEVTIME_PROFILE_MAX", "int", 4,
         "level-2 jax profiler captures allowed per process")
_declare("KTPU_DEVTIME_DUMP_DIR", "str", "",
         "device-timeline dump dir (unset = beside KTPU_TRACE_DUMP_DIR)")

# -- explain / shadow parity sentinel
_declare("KTPU_EXPLAIN", "bool", False,
         "harvest per-plugin filter verdicts + score splits from the "
         "device alongside decisions")
_declare("KTPU_EXPLAIN_TOPK", "int", 3,
         "candidate nodes carried per decided pod in the explain payload")
_declare("KTPU_SHADOW_SAMPLE", "float", 0.0,
         "fraction of decided pods the completion worker replays through "
         "the oracle parity sentinel")
_declare("KTPU_SHADOW_BUNDLE_DIR", "str", "",
         "where drift repro bundles land (unset = "
         "$TMPDIR/ktpu-shadow-bundles)")

# -- host overload monitor
_declare("KTPU_OVERLOAD", "bool", True,
         "host overload monitor: shed optional work under sustained "
         "pressure (0 disables)")
_declare("KTPU_OVERLOAD_FIFO_AGE", "float", 0.5,
         "completion-FIFO age high-water mark seconds")
_declare("KTPU_OVERLOAD_FIFO_AGE_LOW", "float", DERIVED,
         "FIFO-age low mark (default 0.2x the high mark)")
_declare("KTPU_OVERLOAD_QUEUE_DEPTH", "int", DERIVED,
         "scheduling-queue depth high mark (default max(256, 4x "
         "max_batch))")
_declare("KTPU_OVERLOAD_QUEUE_DEPTH_LOW", "int", DERIVED,
         "queue-depth low mark (default high//4)")
_declare("KTPU_OVERLOAD_STAGE_P99", "float", 0.0,
         "windowed completion-stage p99 high mark seconds (0 = signal "
         "off; workload-shaped, deployment sets it)")
_declare("KTPU_OVERLOAD_SHED_DWELL", "int", 3,
         "consecutive hot ticks before shedding the next lever")
_declare("KTPU_OVERLOAD_RESTORE_DWELL", "int", 8,
         "consecutive calm ticks before restoring the last-shed lever")
_declare("KTPU_OVERLOAD_COOLDOWN", "float", 1.0,
         "min seconds between overload-monitor transitions")

# -- apiserver watch wire
_declare("KTPU_WATCH_BUFFER", "int", 256 * 1024,
         "bounded per-watcher send buffer bytes (overflow evicts the "
         "watcher)")
_declare("KTPU_WATCH_EVICT_AFTER", "float", 10.0,
         "max seconds a watcher may hold queued frames with zero socket "
         "progress before eviction")
_declare("KTPU_WIRE_BINARY", "bool", True,
         "clients negotiate the ktpu-binary wire encoding for watch/list "
         "(0 = kill switch: plain JSON, the pre-binary wire bytes)")
_declare("KTPU_WIRE_BATCH_FRAMES", "int", 512,
         "max queued watch frames coalesced into one chunked socket "
         "write (byte-bounded at a quarter of KTPU_WATCH_BUFFER)")

# -- scheduler failover / leader election
_declare("KTPU_LEASE_FENCE_MARGIN", "float", 2.0,
         "seconds before lease expiry a leader self-fences (stops "
         "renewing and demotes) so a GC-paused or partitioned instance "
         "never races the successor's adoption")

# -- gang scheduling (Coscheduling permit transaction)
_declare("KTPU_GANG_PERMIT_TIMEOUT", "float", 60.0,
         "max seconds a gang may hold reserved capacity while waiting "
         "for its remaining members; past this the whole gang rolls "
         "back (also the orphaned-gang bound for promotion reconcile)")
_declare("KTPU_GANG_DEADLOCK_TICKS", "int", 3,
         "consecutive stalled drainer observations (>=2 gangs waiting, "
         "no membership progress) before the deadlock breaker backs "
         "off the youngest gang")
_declare("KTPU_GANG_DEADLOCK_INTERVAL", "float", 0.5,
         "min seconds between gang deadlock-breaker observations (the "
         "hysteresis clock; ticks faster than this are ignored)")

# -- harness / test gates (read by scripts/ and tests/, never by the
#    package; declared so the README table and the knob checker cover
#    the whole KTPU_* surface)
_declare("KTPU_MIDSCALE", "flag", "",
         "opt-in gate for the mid-scale CPU perf tests "
         "(tests/test_perf_midscale.py)")


# ---------------------------------------------------------------------------
# /configz live view + README table rendering


class _KnobConfigz:
    """Live /configz view: serialized at snapshot time, so the body
    always shows the CURRENT effective value of every declared knob and
    whether it came from the process environment or the default."""

    def __serde_to_dict__(self):
        out = {}
        for knob in _REGISTRY.values():
            raw = os.environ.get(knob.name, "")
            out[knob.name] = {
                "value": raw if raw != "" else knob.default,
                "default": knob.default,
                "source": "env" if raw != "" else "default",
                "kind": knob.kind,
            }
        return out


def markdown_table() -> str:
    """The README 'Knob reference' table body, rendered from the
    registry (scripts/lint.py --knob-table). The knob-registry checker
    fails when the README and this registry disagree, so the table can
    never drift from the code again."""
    lines = ["| knob | type | default | meaning |", "|---|---|---|---|"]
    for name in sorted(_REGISTRY):
        k = _REGISTRY[name]
        lines.append(
            f"| `{k.name}` | {k.kind} | {k.default_label} | "
            f"{k.description} |")
    return "\n".join(lines)


def _install_configz() -> None:
    # deferred import: configz pulls serde; knobs must stay importable
    # from anywhere (including the analysis tooling) without dragging
    # the API layer in at module-eval time
    from . import configz

    configz.install("ktpu-env", _KnobConfigz())


_install_configz()
