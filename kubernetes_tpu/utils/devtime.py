"""Device-timeline attribution: per-launch kernel/transfer/compile timing.

The flight recorder (utils/tracing.py) answers "where did the HOST's
time go" — per-stage wall-clock spans over the scheduling pipeline. This
module answers the other half: WHERE DEVICE TIME GOES. Every device
launch (dispatch scan, fused what-if, queued-delta apply, session-build
upload) records a (submit, ready) interval plus its H2D/D2H byte counts,
and every AOT-executable-cache miss records a COMPILE event — so a
compile storm or a transfer-bound mesh row is a counted, attributed
record instead of a mystery stall. Merging this timeline with the host
span ring yields the host<->device OVERLAP accounting (overlap();
device_busy / host_busy / overlapped per window) that the >=0.70
loop_kernel_ratio target turns on: "the 1-CPU box cannot overlap" stops
being a caveat and becomes a measured number any host can report.

Levels (KTPU_DEVTIME):

  0  off — the default. A disabled launch point costs one predicate
     check and allocates nothing (launch() returns a shared no-op
     singleton; decisions are bit-identical with the timeline off —
     both pinned by tests).
  1  per-launch records — submit->ready device intervals, byte counts,
     compile events. The dispatch pipeline's ready edge comes from the
     wait it already pays; synchronous launches (what-if, delta-apply)
     take an explicit block_until_ready at their call site so their
     interval is the launch's own, not a later consumer's. Batch
     granularity, bounded memory, decision-inert.
  2  additionally arms maybe_profile(): a bounded number of launches
     are wrapped in a jax.profiler trace capture written to a directory
     keyed like the flight-recorder dump files. Drills + chip triage
     only; capture cost is real.

The TIMELINE is the same lock-light ring as the flight recorder: slot
allocation is one itertools.count() increment, records are immutable
tuples, and a monotonic slot guard keeps lagging writers from
clobbering newer records. Fault seams dump the timeline alongside the
span ring (scheduler/metrics.dump_seam), so a device fault leaves BOTH
halves of the story. Timebase is time.perf_counter — shared with
tracing spans, which is what makes the overlap merge a plain interval
intersection.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import knobs

logger = logging.getLogger(__name__)

DEVTIME_OFF = 0
DEVTIME_LAUNCHES = 1
DEVTIME_PROFILE = 2

# record kinds (the attribution taxonomy; README "Device-timeline
# attribution" documents each)
KINDS = (
    "kernel",    # scheduling scans: dispatch_many / schedule_many /
                 # what-if / delta-apply launches
    "transfer",  # explicit host<->device state movement: the session
                 # build's cluster upload (H2D); D2H bytes ride the
                 # kernel records' d2h field (harvest readback)
    "compile",   # AOT executable-cache misses (ops/pallas_scan.py) and
                 # any other counted recompile
)

# host stages EXCLUDED from host_busy in overlap(): "wait" is the host
# parked on the device (counting it as host work would make overlap
# tautologically ~1.0), and the zero-duration marker stages carry no
# wall-clock to overlap
OVERLAP_EXCLUDE_STAGES = ("wait", "provenance", "fault")


class _NoopLaunch:
    """Shared do-nothing launch token: the KTPU_DEVTIME=0 fast path
    returns THIS SINGLETON from launch(), so a disabled launch point
    allocates nothing (pinned by the overhead test)."""

    __slots__ = ()

    def done(self, d2h_bytes: int = 0, **attrs) -> "_NoopLaunch":
        return self

    def set(self, **attrs) -> "_NoopLaunch":
        return self


NOOP_LAUNCH = _NoopLaunch()


class _Launch:
    """One in-flight device launch: submit is stamped at construction
    (the enqueue moment), done() stamps ready and commits the record.
    done() is idempotent — recovery paths may race a normal finish."""

    __slots__ = ("_tl", "kind", "name", "h2d_bytes", "attrs", "submit",
                 "_done")

    def __init__(self, tl: "DeviceTimeline", kind: str, name: str,
                 h2d_bytes: int, attrs: Optional[dict]):
        self._tl = tl
        self.kind = kind
        self.name = name
        self.h2d_bytes = int(h2d_bytes)
        self.attrs = attrs
        self.submit = time.perf_counter()
        self._done = False

    def set(self, **attrs) -> "_Launch":
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def done(self, d2h_bytes: int = 0, **attrs) -> "_Launch":
        if self._done:
            return self
        self._done = True
        if attrs:
            self.set(**attrs)
        self._tl.record(
            self.kind, self.name, self.submit, time.perf_counter(),
            h2d_bytes=self.h2d_bytes, d2h_bytes=int(d2h_bytes),
            attrs=self.attrs,
        )
        return self


# record tuple layout: (seq, kind, name, submit, ready, h2d, d2h, tid,
# attrs) — submit/ready in the time.perf_counter timebase shared with
# the flight recorder's spans
Record = Tuple[int, str, str, float, float, int, int, int,
               Optional[dict]]


class DeviceTimeline:
    """Bounded ring of device-launch records; thread-safe, lock-light
    writes (same discipline as tracing.FlightRecorder)."""

    def __init__(self, capacity: Optional[int] = None,
                 level: Optional[int] = None):
        # defensive env parsing: constructed at import time (module-
        # level TIMELINE) — malformed env degrades to defaults, never
        # fails the import; capacity clamps >= 1
        if capacity is None:
            capacity = knobs.get_int("KTPU_DEVTIME_CAPACITY")
        if level is None:
            level = knobs.get_int("KTPU_DEVTIME")
        self.capacity = max(1, int(capacity))
        self.level = max(0, int(level))
        self._buf: List[Optional[Record]] = [None] * self.capacity
        self._seq = itertools.count()
        # monotonic compile counter: survives ring overwrite, so the
        # harness's in-window recompile delta never undercounts a
        # compile storm that out-wrote the ring
        self.compiles = 0
        # level-2 profiler captures remaining (bounded: each capture is
        # a real jax.profiler trace, not a ring write)
        self.profile_budget = max(0, knobs.get_int("KTPU_DEVTIME_PROFILE_MAX"))
        self._dump_lock = threading.Lock()
        self.dump_history: List[dict] = []
        # timeline dumps land beside the flight-recorder dumps unless
        # pointed elsewhere — one triage directory per incident
        self.dump_dir = (knobs.get_str("KTPU_DEVTIME_DUMP_DIR")
                         or knobs.get_str("KTPU_TRACE_DUMP_DIR"))

    # -- write side --------------------------------------------------------

    def record(self, kind: str, name: str, submit: float, ready: float,
               h2d_bytes: int = 0, d2h_bytes: int = 0,
               attrs: Optional[dict] = None) -> None:
        if not self.level:
            return
        if kind == "compile":
            self.compiles += 1  # GIL-atomic enough for a triage counter
        seq = next(self._seq)
        rec = (seq, kind, name, submit, ready, int(h2d_bytes),
               int(d2h_bytes), threading.get_ident(), attrs)
        i = seq % self.capacity
        # monotonic slot guard (see tracing.FlightRecorder.record)
        cur = self._buf[i]
        if cur is None or cur[0] < seq:
            self._buf[i] = rec

    def launch(self, kind: str, name: str, h2d_bytes: int = 0, **attrs):
        """Open a launch record: submit stamps NOW, the returned token's
        done() stamps ready. Returns the shared no-op singleton when the
        timeline is off — no allocation."""
        if not self.level:
            return NOOP_LAUNCH
        return _Launch(self, kind, name, h2d_bytes, attrs or None)

    def compile_event(self, name: str, t0: float, dur: float,
                      **attrs) -> None:
        """One counted recompile (AOT bucket miss, forced eviction):
        records a kind="compile" interval and bumps the monotonic
        compile counter."""
        self.record("compile", name, t0, t0 + max(dur, 0.0),
                    attrs=attrs or None)

    @contextlib.contextmanager
    def maybe_profile(self, name: str):
        """Level-2 jax.profiler trace capture around a launch, bounded
        by profile_budget and keyed like the flight-recorder dump files
        (ktpu-devtime-<ms>-<name>/ under the dump dir). Strictly
        best-effort: no profiler, no dir, or a capture failure all
        degrade to a no-op — profiling must never add a failure mode to
        the dispatch path."""
        if (self.level < DEVTIME_PROFILE or self.profile_budget <= 0
                or not self.dump_dir):
            yield
            return
        self.profile_budget -= 1
        trace_dir = os.path.join(
            self.dump_dir,
            f"ktpu-devtime-{int(time.time() * 1000)}-{name}",
        )
        try:
            import jax

            with jax.profiler.trace(trace_dir):
                yield
            logger.warning("devtime profiler capture (%s) -> %s",
                           name, trace_dir)
        except Exception:  # noqa: BLE001 — capture is best-effort
            logger.warning("devtime profiler capture failed (%s)",
                           name, exc_info=True)
            yield

    # -- read side ---------------------------------------------------------

    def mark(self) -> int:
        """Current sequence high-water mark (window anchor)."""
        seq = next(self._seq)
        return seq + 1

    def snapshot(self, last: Optional[int] = None,
                 since: Optional[int] = None) -> List[Record]:
        """Records currently in the ring, oldest first."""
        records = [r for r in list(self._buf) if r is not None]
        records.sort(key=lambda r: r[0])
        if since is not None:
            records = [r for r in records if r[0] >= since]
        if last is not None:
            records = records[-last:]
        return records

    def clear(self) -> None:
        """Drop buffered records (tests; seq keeps running so mark()
        anchors stay valid). The compile counter is NOT reset — it is
        monotonic by contract; callers delta it."""
        self._buf = [None] * self.capacity

    # -- fault-seam dump ---------------------------------------------------

    def dump(self, reason: str, last: int = 512,
             path: Optional[str] = None, **attrs) -> List[Record]:
        """Snapshot the last N records for a fault seam: append to
        dump_history and (when a path or dump dir is configured) write
        the full record as JSON. Dumped ALONGSIDE the flight-recorder
        ring at every seam (scheduler/metrics.dump_seam), so a device
        fault leaves both the host spans and the device timeline.
        No-op at level 0."""
        if not self.level:
            return []
        records = self.snapshot(last=last)
        record = {
            "reason": reason,
            "ts": time.time(),
            "level": self.level,
            "attrs": attrs,
            "n_records": len(records),
            "compiles": self.compiles,
            "records": [record_dict(r) for r in records],
        }
        out_path = path
        if out_path is None and self.dump_dir:
            out_path = os.path.join(
                self.dump_dir,
                f"ktpu-devtime-{int(time.time() * 1000)}-{reason}.json",
            )
        if out_path:
            try:
                with open(out_path, "w") as f:
                    json.dump(record, f)
                record["path"] = out_path
            except OSError:
                logger.warning("device-timeline dump write failed (%s)",
                               out_path, exc_info=True)
        kinds: Dict[str, int] = {}
        for r in records:
            kinds[r[1]] = kinds.get(r[1], 0) + 1
        logger.warning(
            "device timeline dump (%s): %d records %s%s%s",
            reason, len(records), kinds,
            f" attrs={attrs}" if attrs else "",
            f" -> {out_path}" if out_path else "",
        )
        with self._dump_lock:
            self.dump_history.append(record)
            del self.dump_history[:-64]  # bounded
        return records


# the process-wide timeline (every launch point writes here)
TIMELINE = DeviceTimeline()


def level() -> int:
    return TIMELINE.level


def enabled() -> bool:
    return TIMELINE.level > 0


def set_level(n: int) -> int:
    """Set the live devtime level (tests, drills, the overload-shed
    lever); returns the old level."""
    old, TIMELINE.level = TIMELINE.level, int(n)
    return old


def launch(kind: str, name: str, h2d_bytes: int = 0, **attrs):
    return TIMELINE.launch(kind, name, h2d_bytes=h2d_bytes, **attrs)


def compile_event(name: str, t0: float, dur: float, **attrs) -> None:
    TIMELINE.compile_event(name, t0, dur, **attrs)


def dump(reason: str, **kw) -> List[Record]:
    return TIMELINE.dump(reason, **kw)


def payload_bytes(tree) -> int:
    """Total array bytes in an encoding payload / harvest output: sums
    .nbytes over dict/list/tuple leaves (device arrays expose nbytes
    without forcing a transfer). Cheap enough for the enabled path;
    call sites gate on enabled() so the disabled path never pays it."""
    if tree is None:
        return 0
    n = getattr(tree, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(tree, dict):
        return sum(payload_bytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(payload_bytes(v) for v in tree)
    return 0


# -- export / summaries ----------------------------------------------------


def record_dict(r: Record) -> dict:
    d = {
        "seq": r[0], "kind": r[1], "name": r[2],
        "submit": r[3], "ready": r[4],
        "h2d_bytes": r[5], "d2h_bytes": r[6], "tid": r[7],
    }
    if r[8]:
        d.update(r[8])
    return d


def device_track(records: List) -> List[dict]:
    """Chrome-trace complete events for the device timeline, as a
    SEPARATE track (pid=1, tid=kind index) so scripts/trace_report.py
    can merge it under the host spans (pid=0) in the same µs timebase.
    Accepts raw ring tuples or record_dict() dicts (dump files)."""
    out = []
    for r in records:
        d = r if isinstance(r, dict) else record_dict(r)
        args = {
            k: v for k, v in d.items()
            if k not in ("seq", "kind", "name", "submit", "ready", "tid")
        }
        args["seq"] = d["seq"]
        out.append({
            "name": f"{d['kind']}:{d['name']}",
            "cat": d["kind"],
            "ph": "X",
            "ts": d["submit"] * 1e6,
            "dur": max(d["ready"] - d["submit"], 1e-7) * 1e6,
            "pid": 1,  # the device "process"; host spans ride pid=0
            "tid": KINDS.index(d["kind"]) if d["kind"] in KINDS else 99,
            "args": args,
        })
    return out


def device_time_summary(records: List) -> Dict[str, float]:
    """Per-kind device-time split over a window of records: seconds by
    kind plus byte totals and the launch count — the bench rows'
    device_time_runs payload (kernel/transfer split, compile called
    out)."""
    out = {
        "kernel_s": 0.0, "transfer_s": 0.0, "compile_s": 0.0,
        "h2d_bytes": 0, "d2h_bytes": 0, "launches": 0,
    }
    for r in records:
        d = r if isinstance(r, dict) else record_dict(r)
        key = f"{d['kind']}_s"
        if key in out:
            out[key] += max(0.0, d["ready"] - d["submit"])
        out["h2d_bytes"] += int(d.get("h2d_bytes") or 0)
        out["d2h_bytes"] += int(d.get("d2h_bytes") or 0)
        out["launches"] += 1
    for k in ("kernel_s", "transfer_s", "compile_s"):
        out[k] = round(out[k], 6)
    return out


def _merged(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Sorted union of [start, end) intervals."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out: List[Tuple[float, float]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _measure(merged: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in merged)


def _intersection(a: List[Tuple[float, float]],
                  b: List[Tuple[float, float]]) -> float:
    """Measure of the intersection of two MERGED interval lists
    (two-pointer sweep)."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap(records: List, host_events: List,
            exclude_stages: Tuple[str, ...] = OVERLAP_EXCLUDE_STAGES,
            ) -> Dict[str, float]:
    """Host<->device overlap accounting over one window: merge the
    device timeline (submit->ready intervals) with the flight
    recorder's host spans (t0->t0+dur, excluding the stages that ARE
    the device wait) in their shared perf_counter timebase.

      device_busy_s  union measure of device launch intervals
      host_busy_s    union measure of included host spans
      overlapped_s   measure of the intersection
      overlap_ratio  overlapped / min(host_busy, device_busy) — 1.0
                     means the smaller side fully hides under the
                     larger; 0 means strict serialization (the 1-CPU
                     box) OR an empty side (reported as 0, never NaN)
      window_s       combined first-start .. last-end coverage

    Invariants (trace_report's reconciliation gate): device_busy <=
    window, host_busy <= window, overlapped <= min(host, device)."""
    dev: List[Tuple[float, float]] = []
    for r in records:
        d = r if isinstance(r, dict) else record_dict(r)
        dev.append((float(d["submit"]), float(d["ready"])))
    host: List[Tuple[float, float]] = []
    for e in host_events:
        d = e if isinstance(e, dict) else {
            "stage": e[2], "t0": e[3], "dur": e[4]}
        if d["stage"] in exclude_stages or d["dur"] <= 0:
            continue
        host.append((float(d["t0"]), float(d["t0"]) + float(d["dur"])))
    dev_m = _merged(dev)
    host_m = _merged(host)
    device_busy = _measure(dev_m)
    host_busy = _measure(host_m)
    overlapped = _intersection(dev_m, host_m)
    starts = [a for a, _ in dev_m] + [a for a, _ in host_m]
    ends = [b for _, b in dev_m] + [b for _, b in host_m]
    window = (max(ends) - min(starts)) if starts else 0.0
    floor = min(host_busy, device_busy)
    return {
        "window_s": round(window, 6),
        "device_busy_s": round(device_busy, 6),
        "host_busy_s": round(host_busy, 6),
        "overlapped_s": round(overlapped, 6),
        "overlap_ratio": round(overlapped / floor, 4) if floor > 0 else 0.0,
    }
