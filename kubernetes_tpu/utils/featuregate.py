"""Feature gates (component-base/featuregate equivalent).

Reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go —
a registry of named features with prerelease stages (Alpha default-off,
Beta default-on, GA locked-on), set from the `--feature-gates=k=v,...`
flag, queryable anywhere via Enabled(). The known-gate set mirrors the
subset of pkg/features/kube_features.go this build implements behavior
for.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    pre_release: str = ALPHA
    lock_to_default: bool = False  # GA gates can't be turned off


class FeatureGate:
    def __init__(self, known: Optional[Dict[str, FeatureSpec]] = None):
        self._lock = threading.Lock()
        self._known: Dict[str, FeatureSpec] = dict(known or {})
        self._enabled: Dict[str, bool] = {}

    def add(self, features: Dict[str, FeatureSpec]) -> None:
        with self._lock:
            for name, spec in features.items():
                existing = self._known.get(name)
                if existing is not None and existing != spec:
                    raise ValueError(f"feature gate {name!r} already registered")
                self._known[name] = spec

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            return spec.default

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            if spec.lock_to_default and value != spec.default:
                raise ValueError(
                    f"cannot set feature gate {name} to {value}: locked to "
                    f"{spec.default}"
                )
            self._enabled[name] = value

    def set_from_map(self, overrides: Dict[str, bool]) -> None:
        for name, value in overrides.items():
            self.set(name, value)

    def set_from_string(self, flag: str) -> None:
        """--feature-gates=Foo=true,Bar=false."""
        for part in flag.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            if val.lower() not in ("true", "false"):
                raise ValueError(f"invalid feature gate value {part!r}")
            self.set(key.strip(), val.lower() == "true")

    def known_features(self) -> Dict[str, FeatureSpec]:
        with self._lock:
            return dict(self._known)

    def overrides(self) -> Dict[str, bool]:
        """Current explicit overrides (for save/restore around a scope)."""
        with self._lock:
            return dict(self._enabled)

    def restore(self, overrides: Dict[str, bool]) -> None:
        with self._lock:
            self._enabled = dict(overrides)

    def state(self) -> Dict[str, bool]:
        with self._lock:
            return {
                name: self._enabled.get(name, spec.default)
                for name, spec in sorted(self._known.items())
            }


# The gate set the TPU build has behavior for (subset of the reference's
# 94 gates in pkg/features/kube_features.go, at their v1.21 stages).
DEFAULT_FEATURE_GATES: Dict[str, FeatureSpec] = {
    "DefaultPodTopologySpread": FeatureSpec(default=True, pre_release=BETA),
    "PodDisruptionBudget": FeatureSpec(default=True, pre_release=BETA),
    "TaintBasedEvictions": FeatureSpec(default=True, pre_release=GA, lock_to_default=True),
    "EndpointSlice": FeatureSpec(default=True, pre_release=GA, lock_to_default=True),
    "TTLAfterFinished": FeatureSpec(default=True, pre_release=BETA),
    "CronJobControllerV2": FeatureSpec(default=True, pre_release=BETA),
    "CSIStorageCapacity": FeatureSpec(default=False, pre_release=ALPHA),
    # TPU-build-specific: selects the XLA scoring backend by default
    "TPUScoringKernel": FeatureSpec(default=True, pre_release=BETA),
}


default_feature_gate = FeatureGate(DEFAULT_FEATURE_GATES)
