"""Structured pipeline tracing: span recorder + bounded flight recorder.

Extends the threshold tracer in utils/trace.py (which answers "was this
ONE cycle slow?") with the causal record the pipeline needs: WHERE a
pod's time went across the three-stage scheduling pipeline
(pop -> encode -> queued-delta apply -> dispatch -> wait -> harvest ->
validate -> assume -> reserve/permit -> bind), plus the failure seams'
last-N-events dump. The loop-vs-kernel gap (~1600-2000 loop pods/s vs
9353 kernel-direct) is argued from totals today; the per-stage span
record turns it into a stage breakdown the chip rerun can adjudicate.

Levels (KTPU_TRACE):

  0  off — the default. A disabled trace point costs one predicate
     check plus trivially-cheap per-BATCH argument evaluation (sites
     whose attrs would take a lock guard on enabled() first), and
     allocates nothing per pod (span() returns a shared no-op
     singleton; tests pin this).
  1  per-stage spans — every pipeline stage records (name, stage, t0,
     dur, tid, attrs) into the ring. Batch granularity: a few spans per
     dispatched batch, bounded memory, safe to leave on in production.
  2  per-pod provenance — additionally, every decided pod records a
     provenance event: backend rung, session kind, last build/rebuild
     reason, pallas bucket, speculative chaining, replay/re-drive
     state, planner-ladder path. Costly per pod; drills + traces only.

The FLIGHT RECORDER is a fixed-capacity ring written lock-light: slot
allocation is one itertools.count() increment (atomic under the GIL)
and the write is a single guarded list-item assignment, so concurrent
writers never block each other; events are immutable tuples, so a
reader sees whole records only, and a monotonic slot guard keeps a
lagging writer from clobbering a newer record (in the pathological
deschedule window a slot may briefly hold an older record — never a
torn one). Every fault seam
(watchdog timeout, harvest-validation fault, PipelineStalled, ladder
demotion, supervised-worker restart) dumps the last N events before
recovery proceeds — a `PipelineStalled` leaves a triageable record, not
just gauge values.

Export: Chrome-trace / Perfetto JSON (chrome://tracing "trace event
format", ph="X" complete events) via chrome_trace(); text stage-latency
summaries via stage_stats(). scripts/trace_report.py renders dumps.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import knobs

logger = logging.getLogger(__name__)

TRACE_OFF = 0
TRACE_STAGES = 1
TRACE_PODS = 2

# canonical pipeline stage names (the span taxonomy; README
# "Observability" documents the meaning of each)
STAGES = (
    "pop",          # scheduler thread: queue pop + batch gather
    "encode",       # pod -> dense arrays (PodEncoder)
    "delta-apply",  # queued cluster-event deltas fused into the carry
    "dispatch",     # scan enqueue on the session (incl. speculative)
    "wait",         # watchdog-bounded device wait
    "harvest",      # decode + validate + apply decisions
    "replay",       # conflict-suffix / re-drive sequential replays
    "assume",       # cache.assume (completion worker)
    "reserve-permit",  # Reserve + Permit plugin pass
    "bind",         # batched bind POST
    "planner",      # preemption planner ladder: the per-WAVE plan span
    "whatif",       # per-pod fused what-if launches (nested inside a
                    # planner span — a separate stage so stage_stats
                    # never double-counts the wave's wall-clock)
    "session",      # session builds / teardowns
    "fault",        # fault + recovery markers (zero-duration events)
    "provenance",   # per-pod provenance records (level 2)
)


class _NoopSpan:
    """Shared do-nothing span: the KTPU_TRACE=0 fast path returns THIS
    SINGLETON from span(), so a disabled trace point allocates nothing
    (pinned by the overhead test)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()


class Span:
    __slots__ = ("_rec", "name", "stage", "attrs", "t0")

    def __init__(self, rec: "FlightRecorder", name: str, stage: str,
                 attrs: Optional[dict]):
        self._rec = rec
        self.name = name
        self.stage = stage
        self.attrs = attrs
        self.t0 = 0.0

    def set(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        self._rec.record(
            self.name, self.stage, self.t0,
            time.perf_counter() - self.t0, self.attrs,
        )
        return False


# event tuple layout: (seq, name, stage, t0, dur, tid, attrs)
Event = Tuple[int, str, str, float, float, int, Optional[dict]]


class FlightRecorder:
    """Bounded ring of span events; thread-safe, lock-light writes."""

    def __init__(self, capacity: Optional[int] = None,
                 level: Optional[int] = None):
        # defensive env parsing: the recorder is constructed at import
        # time (module-level RECORDER), so a malformed KTPU_TRACE=off or
        # KTPU_TRACE_CAPACITY=64k must degrade to the default, never
        # fail the scheduler's import; capacity is clamped >= 1 (a
        # zero-size ring would divide by zero on the first record)
        if capacity is None:
            capacity = knobs.get_int("KTPU_TRACE_CAPACITY")
        if level is None:
            level = knobs.get_int("KTPU_TRACE")
        self.capacity = max(1, int(capacity))
        self.level = max(0, int(level))
        self._buf: List[Optional[Event]] = [None] * self.capacity
        self._seq = itertools.count()
        # dump bookkeeping (tests + drills read these; the dump itself
        # is the observable for the fault-seam acceptance contract)
        self._dump_lock = threading.Lock()
        self.dump_history: List[dict] = []
        self.dump_dir = knobs.get_str("KTPU_TRACE_DUMP_DIR")

    # -- write side --------------------------------------------------------

    def record(self, name: str, stage: str, t0: float, dur: float,
               attrs: Optional[dict] = None) -> None:
        if not self.level:
            return
        seq = next(self._seq)
        ev = (seq, name, stage, t0, dur, threading.get_ident(), attrs)
        i = seq % self.capacity
        # monotonic slot guard: a writer descheduled for a full ring
        # revolution between its seq draw and its store must not clobber
        # the newer occupant with its stale record (the check/store pair
        # is itself racy, but it shrinks the hazard from "any write
        # latency" to two adjacent bytecodes — in the worst case one
        # slot briefly holds an older record, which snapshot()'s sort
        # tolerates)
        cur = self._buf[i]
        if cur is None or cur[0] < seq:
            self._buf[i] = ev

    def event(self, name: str, stage: str, **attrs) -> None:
        """Zero-duration marker (fault seams, state transitions)."""
        self.record(name, stage, time.perf_counter(), 0.0, attrs or None)

    def span(self, name: str, stage: str, **attrs):
        """Context manager recording a timed span at exit. Returns the
        shared no-op singleton when tracing is off — no allocation."""
        if not self.level:
            return NOOP_SPAN
        return Span(self, name, stage, attrs or None)

    def pod_level(self) -> bool:
        return self.level >= TRACE_PODS

    def provenance(self, pod_key: str, **fields) -> None:
        """Level-2 per-pod provenance record (rung, session kind, build
        reason, bucket, speculative, replay, planner path, ...)."""
        if self.level >= TRACE_PODS:
            self.record(pod_key, "provenance",
                        time.perf_counter(), 0.0, fields)

    # -- read side ---------------------------------------------------------

    def mark(self) -> int:
        """Current sequence high-water mark (a window anchor: events
        with seq >= mark() were recorded after this call)."""
        seq = next(self._seq)
        return seq + 1

    def snapshot(self, last: Optional[int] = None,
                 since: Optional[int] = None) -> List[Event]:
        """Events currently in the ring, oldest first. `last` keeps only
        the newest N; `since` keeps seq >= since (a mark() anchor)."""
        events = [e for e in list(self._buf) if e is not None]
        events.sort(key=lambda e: e[0])
        if since is not None:
            events = [e for e in events if e[0] >= since]
        if last is not None:
            events = events[-last:]
        return events

    def clear(self) -> None:
        """Drop buffered events (tests; the seq counter keeps running so
        mark() anchors stay valid)."""
        self._buf = [None] * self.capacity

    # -- fault-seam dump ---------------------------------------------------

    def dump(self, reason: str, last: int = 512,
             path: Optional[str] = None, **attrs) -> List[Event]:
        """Snapshot the last N events for a fault seam: append to
        dump_history, log a one-line summary, and (when a path or
        KTPU_TRACE_DUMP_DIR is configured) write the full record as
        JSON. No-op at level 0 — the ring is empty there, and the fault
        path must stay cheap for untraced production runs."""
        if not self.level:
            return []
        events = self.snapshot(last=last)
        record = {
            "reason": reason,
            "ts": time.time(),
            "level": self.level,
            "attrs": attrs,
            "n_events": len(events),
            "events": [event_dict(e) for e in events],
        }
        out_path = path
        if out_path is None and self.dump_dir:
            out_path = os.path.join(
                self.dump_dir,
                f"ktpu-trace-{int(time.time() * 1000)}-{reason}.json",
            )
        if out_path:
            try:
                with open(out_path, "w") as f:
                    json.dump(record, f)
                record["path"] = out_path
            except OSError:
                logger.warning("flight-recorder dump write failed (%s)",
                               out_path, exc_info=True)
        stages: Dict[str, int] = {}
        for e in events:
            stages[e[2]] = stages.get(e[2], 0) + 1
        logger.warning(
            "flight recorder dump (%s): %d events %s%s%s",
            reason, len(events), stages,
            f" attrs={attrs}" if attrs else "",
            f" -> {out_path}" if out_path else "",
        )
        with self._dump_lock:
            self.dump_history.append(record)
            del self.dump_history[:-64]  # bounded
        return events


# the process-wide recorder (the instrumentation points all write here)
RECORDER = FlightRecorder()


def level() -> int:
    return RECORDER.level


def enabled() -> bool:
    return RECORDER.level > 0


def set_level(n: int) -> int:
    """Set the live trace level (tests, drills); returns the old level."""
    old, RECORDER.level = RECORDER.level, int(n)
    return old


def span(name: str, stage: str, **attrs):
    return RECORDER.span(name, stage, **attrs)


def event(name: str, stage: str, **attrs) -> None:
    RECORDER.event(name, stage, **attrs)


def provenance(pod_key: str, **fields) -> None:
    RECORDER.provenance(pod_key, **fields)


def dump(reason: str, **kw) -> List[Event]:
    return RECORDER.dump(reason, **kw)


# -- export / summaries ----------------------------------------------------


def event_dict(e: Event) -> dict:
    d = {
        "seq": e[0], "name": e[1], "stage": e[2],
        "t0": e[3], "dur": e[4], "tid": e[5],
    }
    if e[6]:
        d.update(e[6])
    return d


def chrome_trace(events: List) -> List[dict]:
    """Chrome-trace "trace event format" complete events (ph="X", µs
    timebase) — loadable in chrome://tracing and Perfetto. Accepts raw
    ring tuples or event_dict() dicts (dump files)."""
    out = []
    for e in events:
        d = e if isinstance(e, dict) else event_dict(e)
        args = {
            k: v for k, v in d.items()
            if k not in ("seq", "name", "stage", "t0", "dur", "tid")
        }
        args["seq"] = d["seq"]
        out.append({
            "name": d["name"],
            "cat": d["stage"],
            "ph": "X",
            "ts": d["t0"] * 1e6,
            "dur": max(d["dur"], 1e-7) * 1e6,
            "pid": 0,
            "tid": d["tid"],
            "args": args,
        })
    return out


def _pctile(samples: List[float], p: float) -> float:
    """Nearest-rank percentile: ceil(p/100 * n) - 1. (round(x + 0.5)
    would hit banker's rounding on exact .5 ties — p50 of two samples
    must be the lower rank, not the max.)"""
    if not samples:
        return 0.0
    s = sorted(samples)
    import math

    idx = min(len(s) - 1, max(0, math.ceil(p / 100.0 * len(s)) - 1))
    return s[idx]


def stage_stats(events: List) -> Dict[str, Dict[str, float]]:
    """Per-stage wall-clock summary over a window of events: count,
    total seconds, p50/p99 span duration. Zero-duration marker stages
    (fault, provenance) report counts with zero totals."""
    durs: Dict[str, List[float]] = {}
    for e in events:
        d = e if isinstance(e, dict) else event_dict(e)
        durs.setdefault(d["stage"], []).append(float(d["dur"]))
    out: Dict[str, Dict[str, float]] = {}
    for stage, vals in sorted(durs.items()):
        out[stage] = {
            "count": len(vals),
            "total_s": round(sum(vals), 6),
            "p50_s": round(_pctile(vals, 50), 6),
            "p99_s": round(_pctile(vals, 99), 6),
        }
    return out


def window_span(events: List) -> float:
    """Wall-clock coverage of a window of events: last span end minus
    first span start (seconds). The reconciliation anchor: with tracing
    on, the harness pins this against the measured first-bind ->
    last-bind window."""
    t0s, t1s = [], []
    for e in events:
        d = e if isinstance(e, dict) else event_dict(e)
        t0s.append(d["t0"])
        t1s.append(d["t0"] + d["dur"])
    if not t0s:
        return 0.0
    return max(t1s) - min(t0s)


def provenance_mix(events: List) -> Dict[str, Dict[str, int]]:
    """Distribution of the level-2 provenance fields over a window:
    {field: {value: count}} for rung / session / planner path /
    speculative — the "which path did pods actually ride" summary
    trace_report prints."""
    mix: Dict[str, Dict[str, int]] = {}
    for e in events:
        d = e if isinstance(e, dict) else event_dict(e)
        if d["stage"] != "provenance":
            continue
        for field in ("rung", "session", "build_reason", "planner",
                      "speculative", "redrive", "bucket"):
            if field in d and d[field] is not None:
                vals = mix.setdefault(field, {})
                key = str(d[field])
                vals[key] = vals.get(key, 0) + 1
    return mix
