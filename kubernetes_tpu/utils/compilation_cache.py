"""Persistent XLA compilation cache for cold-start control.

The scheduler's first binding decision waits on XLA/Mosaic compiles
(~35s+ per scan shape on the TPU tunnel). The reference's CI disables
tests that blow its time window rather than paying recompiles
(scheduler_perf scheduler_test.go:93-101); the TPU-native answer is
jax's persistent compilation cache: compiled executables are keyed by
(HLO, compile options, backend) and reloaded from disk on the next
process start, so only the FIRST run of a given shape pays the compile.

Enabled by every bench/driver entry point; tests keep the default
in-memory cache (CPU compiles there are cheap and the suite mutates
shapes constantly).
"""

from __future__ import annotations

import os

from . import knobs

DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".xla_cache",
)


def enable_persistent_cache(path: str = "") -> str:
    """Turn on jax's on-disk compilation cache; returns the cache dir.

    Honors KTPU_COMPILATION_CACHE (set to "0"/"off" to disable)."""
    env = knobs.get_str("KTPU_COMPILATION_CACHE")
    if env.lower() in ("0", "off", "disable"):
        return ""
    cache_dir = path or env or DEFAULT_CACHE_DIR
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything that took meaningful compile time; the default
    # min-entry gate would skip small-but-hot programs
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except AttributeError:  # older jax: names differ; best-effort
        pass
    return cache_dir
