"""Prometheus-style metrics registry (component-base/metrics equivalent).

Reference: staging/src/k8s.io/component-base/metrics — Counter/Gauge/
Histogram vectors with label sets, a process-wide legacy registry
(legacyregistry/registry.go) backing every component's /metrics handler,
and text exposition in the Prometheus format.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Metric:
    def __init__(self, name: str, help: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared {sorted(self.label_names)}"
            )
        return tuple(labels[k] for k in self.label_names)

    def _fmt_labels(self, key: Tuple[str, ...]) -> str:
        if not self.label_names:
            return ""
        inner = ",".join(f'{n}="{v}"' for n, v in zip(self.label_names, key))
        return "{" + inner + "}"


class Counter(_Metric):
    type_name = "counter"

    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def items(self) -> List[Tuple[Tuple[str, ...], float]]:
        """All (label-values, value) pairs — the public iteration surface
        (consumers must not reach into _values/_lock)."""
        with self._lock:
            return list(self._values.items())

    def collect(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{self._fmt_labels(k)} {v}"
                for k, v in sorted(self._values.items())
            ]


class Gauge(_Metric):
    type_name = "gauge"

    def __init__(self, name, help, label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def remove(self, **labels) -> None:
        """Drop one label series (per-entity gauges — e.g. a per-watcher
        buffer depth — must not leak series after the entity is gone)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    collect = Counter.collect


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(self, name, help, label_names=(), buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}
        if not label_names:
            # Prometheus convention: an unlabeled histogram exports at
            # zero from birth, so a reader can tell "no observations
            # yet" from "metric missing" — an SLI that only appears
            # under traffic is invisible exactly when its absence is
            # the signal (labeled series still appear on first use).
            key = self._key({})
            self._counts[key] = [0] * len(self.buckets)
            self._sums[key] = 0.0
            self._totals[key] = 0

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def percentile(self, p: float, **labels) -> float:
        """Approximate percentile from bucket counts (upper bound)."""
        key = self._key(labels)
        with self._lock:
            counts = self._counts.get(key)
            total = self._totals.get(key, 0)
        if not counts or not total:
            return 0.0
        target = p / 100.0 * total
        acc = 0
        for i, cnt in enumerate(counts):
            acc += cnt
            if acc >= target:
                return self.buckets[i]
        return self.buckets[-1]

    def collect(self) -> List[str]:
        out = []
        with self._lock:
            for key in sorted(self._counts):
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += self._counts[key][i]
                    labels = list(zip(self.label_names, key)) + [("le", str(b))]
                    inner = ",".join(f'{n}="{v}"' for n, v in labels)
                    out.append(f"{self.name}_bucket{{{inner}}} {cum}")
                inf_labels = list(zip(self.label_names, key)) + [("le", "+Inf")]
                inner = ",".join(f'{n}="{v}"' for n, v in inf_labels)
                out.append(f"{self.name}_bucket{{{inner}}} {self._totals[key]}")
                out.append(f"{self.name}_sum{self._fmt_labels(key)} {self._sums[key]}")
                out.append(f"{self.name}_count{self._fmt_labels(key)} {self._totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text format (the /metrics body)."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.type_name}")
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


# the process-wide registry (legacyregistry)
legacy_registry = Registry()
