"""Dataclass <-> JSON-dict serialization with Kubernetes-style camelCase keys.

The reference's API types round-trip through JSON with camelCase field names
(e.g. staging/src/k8s.io/api/core/v1/types.go struct tags). Here every API
dataclass gets the same property via type-hint driven generic serde instead
of per-type generated codecs (the reference generates these with
k8s.io/code-generator).

Conventions:
  - snake_case python field  <->  camelCase JSON key
  - a field may override its JSON key with metadata={"json": "name"}
  - zero-valued fields (None, "", 0, False, empty list/dict) are omitted on
    serialization (matches Go `omitempty`)
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, Type, TypeVar, Union, get_args, get_origin

T = TypeVar("T")


def snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _json_key(field: dataclasses.Field) -> str:
    return field.metadata.get("json", snake_to_camel(field.name))


def _is_optional(tp: Any) -> bool:
    return get_origin(tp) is Union and type(None) in get_args(tp)


def _unwrap_optional(tp: Any) -> Any:
    if _is_optional(tp):
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


# Per-class field plan: (attr name, json key, resolved type, is_optional).
# typing.get_type_hints re-evaluates string annotations with compile() on
# EVERY call — uncached it was ~2.8ms per Pod round-trip, the single
# hottest host cost on the apiserver write path.
_PLAN_CACHE: Dict[type, list] = {}


def _field_plan(cls: type) -> list:
    plan = _PLAN_CACHE.get(cls)
    if plan is None:
        hints = typing.get_type_hints(cls)
        plan = [
            (
                f.name,
                _json_key(f),
                hints.get(f.name, f.type),
                _is_optional(hints.get(f.name, f.type)),
            )
            for f in dataclasses.fields(cls)
        ]
        _PLAN_CACHE[cls] = plan
    return plan


def to_dict(obj: Any) -> Any:
    """Serialize a dataclass (or container of them) to JSON-compatible dicts."""
    if obj is None:
        return None
    custom = getattr(obj, "__serde_to_dict__", None)
    if custom is not None and not isinstance(obj, type):
        return custom()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {}
        for name, key, _tp, is_opt in _field_plan(type(obj)):
            v = getattr(obj, name)
            if v is None:
                continue
            # Optional fields mirror Go pointers: a present zero value (e.g.
            # *int32 replicas = 0) is serialized, only nil is omitted.
            if not is_opt and (
                v == "" or v == 0 or v is False or v == [] or v == {}
            ):
                continue
            out[key] = to_dict(v)
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def from_dict(cls: Type[T], data: Any) -> T:
    """Deserialize JSON-compatible data into dataclass `cls` using type hints."""
    return _from_value(cls, data)


def _from_value(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    tp = _unwrap_optional(tp)
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem_tp,) = get_args(tp) or (Any,)
        return [_from_value(elem_tp, v) for v in data]
    if origin is dict:
        args = get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _from_value(val_tp, v) for k, v in data.items()}
    if isinstance(tp, type) and hasattr(tp, "__serde_from_dict__"):
        return tp.__serde_from_dict__(data)
    if dataclasses.is_dataclass(tp):
        kwargs = {}
        for name, key, field_tp, _is_opt in _field_plan(tp):
            if key in data:
                kwargs[name] = _from_value(field_tp, data[key])
        return tp(**kwargs)
    if tp in (Any, object) or isinstance(tp, TypeVar):
        return data
    if tp is float and isinstance(data, int):
        return float(data)
    return data
