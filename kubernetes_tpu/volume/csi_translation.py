"""In-tree -> CSI volume translation (CSI migration).

Reference: staging/src/k8s.io/csi-translation-lib/translate.go:30
(CSITranslator) with the per-cloud plugins
(plugins/{gce_pd,aws_ebs,azure_disk}.go). The reference registers six
in-tree plugins; this build translates the three whose CSI drivers the
scheduler's attach-limit machinery models (DEFAULT_LIMITS /
_INTREE_TO_CSI in scheduler/plugins/volumes.py) — GCE PD, AWS EBS,
Azure Disk. The translation is consumed in two places:

  * VolumeDeviceResolver indexes PVs through `translate_pv` — a
    migratable in-tree PV reaches the kernel path as its CSI twin, so
    SchedulingMigratedInTreePVs rides the same attach-scalar +
    node-affinity machinery as native CSI PVs;
  * the oracle NodeVolumeLimits plugin's PVC->driver lookup uses
    `pv_csi_source`, so fast path and oracle can never disagree about
    a migrated PV's driver.

Topology (translateTopology, translate.go:209): the reference rewrites
zone/region labels into the CSI driver's own topology keys
(e.g. topology.gke.io/zone). This build's nodes carry the standard
kubernetes.io zone labels, so the translated PV keeps its zone labels
AND gains an explicit spec.node_affinity requirement on LABEL_ZONE —
semantically the reference's constraint expressed in the vocabulary the
kernel's node-affinity tables already understand.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Tuple

from ..api import types as v1

# in-tree PV spec field -> (CSI driver, identity field in the source)
IN_TREE_SOURCES: Dict[str, Tuple[str, str]] = {
    "gce_persistent_disk": ("pd.csi.storage.gke.io", "pdName"),
    "aws_elastic_block_store": ("ebs.csi.aws.com", "volumeID"),
    "azure_disk": ("disk.csi.azure.com", "diskName"),
}

_UNSPECIFIED = "UNSPECIFIED"  # gce_pd.go UnspecifiedValue


def migratable_plugin(pv: v1.PersistentVolume) -> Optional[str]:
    """The in-tree spec field this PV uses, or None (already CSI or no
    translatable source)."""
    if getattr(pv.spec, "csi", None):
        return None
    for field in IN_TREE_SOURCES:
        if getattr(pv.spec, field, None):
            return field
    return None


def _zones_of(pv: v1.PersistentVolume):
    from ..scheduler.plugins.volumes import _ZONE_LABELS

    for key, value in (pv.metadata.labels or {}).items():
        if key in _ZONE_LABELS and "zone" in key:
            # multi-zone labels join with __ (labelMultiZoneDelimiter)
            return sorted(set(value.replace("__", ",").split(",")))
    return []


def translate_pv(pv: v1.PersistentVolume) -> v1.PersistentVolume:
    """TranslateInTreePVToCSI: returns the PV itself when no translation
    applies, else a COPY with the in-tree source replaced by its CSI
    twin and the zone labels lifted into spec.node_affinity."""
    field = migratable_plugin(pv)
    if field is None:
        return pv
    driver, ident_key = IN_TREE_SOURCES[field]
    src = getattr(pv.spec, field) or {}
    name = src.get(ident_key) or pv.metadata.name
    zones = _zones_of(pv)
    if field == "gce_persistent_disk":
        # gce_pd.go volIDZonalFmt projects/U/zones/<zone|region>/disks/<pd>
        where = zones[0] if len(zones) == 1 else (
            _region_from_zones(zones) if zones else _UNSPECIFIED)
        handle = f"projects/{_UNSPECIFIED}/zones/{where}/disks/{name}"
    else:
        handle = name
    out = copy.deepcopy(pv)
    setattr(out.spec, field, None)
    out.spec.csi = {"driver": driver, "volumeHandle": handle}
    if zones and out.spec.node_affinity is None:
        # translateTopology: the zone constraint becomes an explicit
        # node-affinity requirement (expressed on the standard zone key
        # this build's nodes are labeled with)
        out.spec.node_affinity = v1.VolumeNodeAffinity(
            required=v1.NodeSelector(node_selector_terms=[
                v1.NodeSelectorTerm(match_expressions=[
                    v1.NodeSelectorRequirement(
                        key=v1.LABEL_ZONE, operator="In", values=zones)
                ])
            ])
        )
    return out


def _region_from_zones(zones) -> str:
    """getRegionFromZones: strip the trailing zone suffix (-a, -b, ...);
    heterogeneous prefixes fall back to UNSPECIFIED."""
    regions = {z.rsplit("-", 1)[0] for z in zones if "-" in z}
    return regions.pop() if len(regions) == 1 else _UNSPECIFIED


def pv_csi_source(pv: v1.PersistentVolume) -> Optional[Dict[str, str]]:
    """The PV's effective CSI source, translating in-tree sources — the
    single lookup both the kernel resolver and the oracle plugin use."""
    csi = getattr(pv.spec, "csi", None)
    if isinstance(csi, dict) and csi.get("driver"):
        return csi
    if migratable_plugin(pv) is not None:
        return translate_pv(pv).spec.csi
    return None
