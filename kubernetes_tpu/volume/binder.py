"""Scheduler volume binder: the VolumeBinding plugin's engine.

Reference: pkg/controller/volume/scheduling/scheduler_binder.go —
GetPodVolumes (claim triage), FindPodVolumes (per-node feasibility),
AssumePodVolumes (optimistic PV reservation), BindPodVolumes (API
writes at PreBind), RevertAssumedPodVolumes; PV matching semantics from
pkg/controller/volume/persistentvolume/index.go findBestMatchForClaim.

Design notes (TPU build): the binder is pure host-side control logic —
it never touches the device. It reads cluster state through injected
lister callables (informer caches in production, plain lists in tests)
and keeps a small in-memory assume cache of PV-name→claim reservations so
concurrent scheduling cycles don't hand the same Available PV to two
pods. Dynamic provisioning is performed in-process at bind time
(the reference defers to an external provisioner and polls; we are the
provisioner, which keeps PreBind deterministic).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..api import types as v1
from ..api.labels import match_node_selector_terms, node_fields
from ..api.quantity import parse_quantity
from ..api.storage import PROVISIONER_NO_PROVISIONER, StorageClass

# FindPodVolumes conflict reasons (scheduler_binder.go:52-58)
ERR_REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"

# PVC annotation naming the node chosen by the scheduler, consumed by the
# provisioner (pv_controller annSelectedNode).
ANN_SELECTED_NODE = "volume.kubernetes.io/selected-node"


@dataclass
class PodVolumes:
    """Per-(pod,node) binding decision (scheduler_binder.go PodVolumes)."""

    static_bindings: List[Tuple[v1.PersistentVolume, v1.PersistentVolumeClaim]] = field(
        default_factory=list
    )
    dynamic_provisions: List[v1.PersistentVolumeClaim] = field(default_factory=list)


def _claim_request_bytes(claim: v1.PersistentVolumeClaim) -> int:
    req = (claim.spec.resources.requests or {}).get("storage", "0")
    return int(parse_quantity(req))


def _pv_capacity_bytes(pv: v1.PersistentVolume) -> int:
    cap = (pv.spec.capacity or {}).get("storage", "0")
    return int(parse_quantity(cap))


def _class_name(claim: v1.PersistentVolumeClaim) -> str:
    return claim.spec.storage_class_name or ""


def pv_node_affinity_matches(pv: v1.PersistentVolume, node: v1.Node) -> bool:
    """volume_host.go CheckNodeAffinity: nil affinity matches every node."""
    aff = pv.spec.node_affinity
    if aff is None or aff.required is None:
        return True
    return match_node_selector_terms(
        aff.required.node_selector_terms, node.metadata.labels or {}, node_fields(node)
    )


def _access_modes_contained(requested: Sequence[str], offered: Sequence[str]) -> bool:
    return all(m in (offered or []) for m in (requested or []))


def pv_matches_claim(
    pv: v1.PersistentVolume,
    claim: v1.PersistentVolumeClaim,
    node: Optional[v1.Node] = None,
) -> bool:
    """Static-binding compatibility (index.go findMatchingVolume per-PV checks)."""
    if pv.status.phase != "Available":
        return False
    if pv.spec.claim_ref_name:
        return False
    if (pv.spec.storage_class_name or "") != _class_name(claim):
        return False
    if not _access_modes_contained(claim.spec.access_modes, pv.spec.access_modes):
        return False
    if _pv_capacity_bytes(pv) < _claim_request_bytes(claim):
        return False
    if node is not None and not pv_node_affinity_matches(pv, node):
        return False
    return True


def find_matching_volume(
    claim: v1.PersistentVolumeClaim,
    pvs: Sequence[v1.PersistentVolume],
    node: Optional[v1.Node] = None,
    excluded: Optional[set] = None,
) -> Optional[v1.PersistentVolume]:
    """Smallest Available PV that satisfies the claim
    (index.go findBestMatchForClaim's smallest-first ordering)."""
    best = None
    best_cap = None
    for pv in pvs:
        if excluded and pv.metadata.name in excluded:
            continue
        if not pv_matches_claim(pv, claim, node):
            continue
        cap = _pv_capacity_bytes(pv)
        if best is None or cap < best_cap:
            best, best_cap = pv, cap
    return best


def _storage_class_topology_matches(sc: StorageClass, node: v1.Node) -> bool:
    """AllowedTopologies gate for dynamic provisioning
    (scheduler_binder.go checkVolumeProvisions → AllowedTopologies)."""
    if not sc.allowed_topologies:
        return True
    labels = node.metadata.labels or {}
    for term in sc.allowed_topologies:
        exprs = term.get("matchLabelExpressions", [])
        if all(labels.get(e["key"]) in e.get("values", []) for e in exprs):
            return True
    return False


class SchedulerVolumeBinder:
    """scheduler_binder.go volumeBinder, informer-cache backed."""

    def __init__(
        self,
        list_pvcs: Callable[[], List[v1.PersistentVolumeClaim]],
        list_pvs: Callable[[], List[v1.PersistentVolume]],
        list_storage_classes: Callable[[], List[StorageClass]],
        client=None,
        bind_timeout: float = 10.0,
        get_pvc: Optional[Callable[[str], Any]] = None,
    ):
        self._list_pvcs = list_pvcs
        self._list_pvs = list_pvs
        self._list_classes = list_storage_classes
        self._client = client
        self._bind_timeout = bind_timeout
        # keyed 'namespace/name' lookup (the informer store's own get):
        # a full list scan per lookup ran at Reserve AND PreBind per pod
        # — O(pods x PVCs) made the 5000-node PV workload binder-bound
        self._keyed_get_pvc = get_pvc
        self._lock = threading.Lock()
        # pv name -> (claim namespace, claim name) optimistic reservations
        self._assumed: Dict[str, Tuple[str, str]] = {}

    # -- lookups -----------------------------------------------------------

    def _get_pvc(self, namespace: str, name: str) -> Optional[v1.PersistentVolumeClaim]:
        if self._keyed_get_pvc is not None:
            return self._keyed_get_pvc(
                f"{namespace}/{name}" if namespace else name
            )
        for c in self._list_pvcs():
            if c.metadata.namespace == namespace and c.metadata.name == name:
                return c
        return None

    def _get_class(self, name: str) -> Optional[StorageClass]:
        for sc in self._list_classes():
            if sc.metadata.name == name:
                return sc
        return None

    # -- GetPodVolumes (scheduler_binder.go:280 GetPodVolumes) -------------

    def get_pod_volumes(
        self, pod: v1.Pod
    ) -> Tuple[
        List[v1.PersistentVolumeClaim],  # bound
        List[v1.PersistentVolumeClaim],  # to bind (delayed)
        List[v1.PersistentVolumeClaim],  # unbound immediate (blocks scheduling)
        List[str],  # missing claim names (unresolvable)
    ]:
        bound, to_bind, immediate, missing = [], [], [], []
        for vol in pod.spec.volumes or []:
            src = vol.source or {}
            pvc_src = src.get("persistentVolumeClaim")
            if not pvc_src:
                continue
            claim = self._get_pvc(pod.metadata.namespace, pvc_src.get("claimName", ""))
            if claim is None:
                missing.append(pvc_src.get("claimName", ""))
                continue
            if claim.spec.volume_name:
                bound.append(claim)
                continue
            sc = self._get_class(_class_name(claim))
            if sc is not None and sc.volume_binding_mode == "WaitForFirstConsumer":
                to_bind.append(claim)
            else:
                immediate.append(claim)
        return bound, to_bind, immediate, missing

    # -- FindPodVolumes (scheduler_binder.go:320) --------------------------

    def find_pod_volumes(
        self,
        pod: v1.Pod,
        bound_claims: List[v1.PersistentVolumeClaim],
        claims_to_bind: List[v1.PersistentVolumeClaim],
        node: v1.Node,
    ) -> Tuple[List[str], PodVolumes]:
        reasons: List[str] = []
        pod_volumes = PodVolumes()

        # Bound claims: the PV it's bound to must tolerate this node.
        if bound_claims:
            by_name = {pv.metadata.name: pv for pv in self._list_pvs()}
            for claim in bound_claims:
                pv = by_name.get(claim.spec.volume_name)
                if pv is None or not pv_node_affinity_matches(pv, node):
                    reasons.append(ERR_REASON_NODE_CONFLICT)
                    return reasons, pod_volumes

        # Unbound delayed claims: match a PV or check provisionability.
        if claims_to_bind:
            with self._lock:
                assumed = set(self._assumed)
            chosen: set = set()
            pvs = self._list_pvs()
            for claim in claims_to_bind:
                pv = find_matching_volume(claim, pvs, node, excluded=assumed | chosen)
                if pv is not None:
                    chosen.add(pv.metadata.name)
                    pod_volumes.static_bindings.append((pv, claim))
                    continue
                sc = self._get_class(_class_name(claim))
                if (
                    sc is not None
                    and sc.provisioner
                    and sc.provisioner != PROVISIONER_NO_PROVISIONER
                    and _storage_class_topology_matches(sc, node)
                ):
                    pod_volumes.dynamic_provisions.append(claim)
                    continue
                reasons.append(ERR_REASON_BIND_CONFLICT)
                return reasons, PodVolumes()
        return reasons, pod_volumes

    # -- AssumePodVolumes (scheduler_binder.go:389) ------------------------

    def assume_pod_volumes(self, pod: v1.Pod, pod_volumes: PodVolumes) -> bool:
        """Reserve the chosen PVs; returns all_fully_bound."""
        if not pod_volumes.static_bindings and not pod_volumes.dynamic_provisions:
            return True
        with self._lock:
            for pv, claim in pod_volumes.static_bindings:
                self._assumed[pv.metadata.name] = (
                    claim.metadata.namespace,
                    claim.metadata.name,
                )
        return False

    def revert_assumed_pod_volumes(self, pod_volumes: PodVolumes) -> None:
        with self._lock:
            for pv, _claim in pod_volumes.static_bindings:
                self._assumed.pop(pv.metadata.name, None)

    # -- BindPodVolumes (scheduler_binder.go:439) --------------------------

    def bind_pod_volumes(
        self, pod: v1.Pod, node_name: str, pod_volumes: PodVolumes
    ) -> None:
        """Execute the binding via API writes (PreBind). Raises on failure."""
        if self._client is None:
            raise RuntimeError("volume binder has no API client; cannot bind")
        try:
            for pv, claim in pod_volumes.static_bindings:
                self._bind_claim_to_pv(claim, pv)
            for claim in pod_volumes.dynamic_provisions:
                self._provision(claim, node_name)
        finally:
            self.revert_assumed_pod_volumes(pod_volumes)

    def _bind_claim_to_pv(
        self, claim: v1.PersistentVolumeClaim, pv: v1.PersistentVolume
    ) -> None:
        cs = self._client
        live_pv = cs.persistentvolumes.get(pv.metadata.name)
        if live_pv.spec.claim_ref_name and (
            live_pv.spec.claim_ref_namespace != claim.metadata.namespace
            or live_pv.spec.claim_ref_name != claim.metadata.name
        ):
            raise RuntimeError(
                f"pv {pv.metadata.name} already bound to another claim"
            )
        live_pv.spec.claim_ref_namespace = claim.metadata.namespace
        live_pv.spec.claim_ref_name = claim.metadata.name
        live_pv.status.phase = "Bound"
        cs.persistentvolumes.update(live_pv)

        live_claim = cs.persistentvolumeclaims.get(
            claim.metadata.name, claim.metadata.namespace
        )
        live_claim.spec.volume_name = pv.metadata.name
        live_claim.status.phase = "Bound"
        cs.persistentvolumeclaims.update(live_claim)

    def _provision(self, claim: v1.PersistentVolumeClaim, node_name: str) -> None:
        """In-process dynamic provisioning: create a node-affine PV and bind.

        The reference annotates the claim with the selected node and waits
        for an external provisioner (scheduler_binder.go:560
        checkBindings poll); here the binder IS the provisioner.
        """
        cs = self._client
        sc = self._get_class(_class_name(claim))
        live_claim = cs.persistentvolumeclaims.get(
            claim.metadata.name, claim.metadata.namespace
        )
        anns = live_claim.metadata.annotations or {}
        anns[ANN_SELECTED_NODE] = node_name
        live_claim.metadata.annotations = anns
        live_claim = cs.persistentvolumeclaims.update(live_claim)

        pv = v1.PersistentVolume(
            metadata=v1.ObjectMeta(
                name=f"pvc-{live_claim.metadata.uid or live_claim.metadata.name}",
            ),
            spec=v1.PersistentVolumeSpec(
                capacity={
                    "storage": (live_claim.spec.resources.requests or {}).get(
                        "storage", "0"
                    )
                },
                access_modes=list(live_claim.spec.access_modes or []),
                storage_class_name=_class_name(live_claim),
                claim_ref_namespace=live_claim.metadata.namespace,
                claim_ref_name=live_claim.metadata.name,
                node_affinity=v1.VolumeNodeAffinity(
                    required=v1.NodeSelector(
                        node_selector_terms=[
                            v1.NodeSelectorTerm(
                                match_expressions=[
                                    v1.NodeSelectorRequirement(
                                        key=v1.LABEL_HOSTNAME,
                                        operator="In",
                                        values=[node_name],
                                    )
                                ]
                            )
                        ]
                    )
                ),
                persistent_volume_reclaim_policy=sc.reclaim_policy if sc else "Delete",
            ),
            status=v1.PersistentVolumeStatus(phase="Bound"),
        )
        pv = cs.persistentvolumes.create(pv)
        live_claim.spec.volume_name = pv.metadata.name
        live_claim.status.phase = "Bound"
        cs.persistentvolumeclaims.update(live_claim)
