"""Volume subsystem: scheduler volume binder + PV matching.

Reference: pkg/controller/volume/scheduling (SchedulerVolumeBinder),
pkg/controller/volume/persistentvolume (binder controller, index.go
findBestMatchForClaim).
"""

from .binder import (  # noqa: F401
    PodVolumes,
    SchedulerVolumeBinder,
    find_matching_volume,
    pv_matches_claim,
    pv_node_affinity_matches,
)
