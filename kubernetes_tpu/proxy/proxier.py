"""Service data plane: the kube-proxy equivalent.

Reference: pkg/proxy/iptables/proxier.go — syncProxyRules (:814) is a
full-state resync: walk every service port, synthesize the chain graph

    PREROUTING -> KUBE-SERVICES -> KUBE-SVC-<hash> (per service port)
                    -> [affinity] KUBE-SEP-<hash> via recent-match
                    -> statistic random 1/n -> KUBE-SEP-<hash> (DNAT)
    KUBE-NODEPORTS -> KUBE-SVC-<hash>   (NodePort services)
    REJECT for service ports with no ready endpoints

and restore it atomically. The kernel's netfilter is native surface the
TPU build can't inherit (SURVEY §2.4.3); `Netfilter` here is a faithful
in-memory model of the chain semantics (first-match, jumps, statistic
random, recent/affinity) so the routing behavior — VIP -> backend
selection, session affinity, nodePorts, REJECT on empty — is testable
and hollow nodes get a real data path.

Endpoint state comes from EndpointSlices via EndpointSliceCache
(pkg/proxy/endpointslicecache.go), services from the service informer;
sync is event-driven with a min-interval, like the reference's
async.BoundedFrequencyRunner (proxier.go:788).
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.informer import EventHandler
from .endpointslicecache import EndpointSliceCache

CLIENT_IP_DEFAULT_TIMEOUT = 10800.0  # core/v1 DefaultClientIPServiceAffinitySeconds


@dataclass(frozen=True)
class Packet:
    dst_ip: str
    dst_port: int
    protocol: str = "TCP"
    src_ip: str = ""


@dataclass
class Rule:
    """One iptables rule: match fields -> target.

    target is a chain name (jump), ("DNAT", ip, port), or "REJECT".
    probability models `-m statistic --mode random --probability p`;
    affinity_check models `-m recent --rcheck` against the service
    chain's bucket.
    """

    target: object
    dst_ip: Optional[str] = None
    dst_port: Optional[int] = None
    protocol: Optional[str] = None
    probability: Optional[float] = None
    affinity_check: bool = False


@dataclass
class Chain:
    name: str
    rules: List[Rule] = field(default_factory=list)
    records_affinity: bool = False  # service chain with ClientIP affinity


class Netfilter:
    """In-memory chain evaluator with first-match + jump semantics."""

    def __init__(self, rng: Optional[random.Random] = None):
        self.chains: Dict[str, Chain] = {}
        self._rng = rng or random.Random(0)
        # affinity buckets: (svc chain, src_ip) -> (sep chain, stamp)
        self._recent: Dict[Tuple[str, str], Tuple[str, float]] = {}
        self._affinity_timeout = CLIENT_IP_DEFAULT_TIMEOUT
        self._lock = threading.Lock()

    def replace(self, chains: Dict[str, Chain], affinity_timeout: float) -> None:
        """Atomic rule swap (iptables-restore)."""
        with self._lock:
            self.chains = chains
            self._affinity_timeout = affinity_timeout
            live = set(chains)
            self._recent = {k: v for k, v in self._recent.items() if k[0] in live}

    def route(self, pkt: Packet) -> Optional[Tuple[str, int]]:
        """Evaluate a packet from KUBE-SERVICES. Returns the DNAT
        destination (ip, port) or None for no match (pass through);
        raises ConnectionRefusedError for REJECT. Updates the affinity
        bucket when a ClientIP service chain is traversed."""
        with self._lock:
            path: List[Tuple[str, str]] = []  # (chain, chosen sep) markers
            res = self._eval_chain("KUBE-SERVICES", pkt, 0, path)
            if res is not None:
                for chain_name, sep in path:
                    self._recent[(chain_name, pkt.src_ip)] = (sep, time.time())
            return res

    def _eval_chain(self, name: str, pkt: Packet, depth: int, path) -> Optional[Tuple[str, int]]:
        if depth > 16:  # kernel max chain-jump depth analog
            return None
        chain = self.chains.get(name)
        if chain is None:
            return None
        for rule in chain.rules:
            if rule.dst_ip is not None and rule.dst_ip != pkt.dst_ip:
                continue
            if rule.dst_port is not None and rule.dst_port != pkt.dst_port:
                continue
            if rule.protocol is not None and rule.protocol != pkt.protocol:
                continue
            if rule.affinity_check:
                hit = self._recent.get((name, pkt.src_ip))
                if hit is None or time.time() - hit[1] > self._affinity_timeout:
                    continue
                res = self._eval_chain(hit[0], pkt, depth + 1, path)
                if res is not None:
                    path.append((name, hit[0]))
                    return res
                continue
            if rule.probability is not None and self._rng.random() >= rule.probability:
                continue
            if rule.target == "REJECT":
                raise ConnectionRefusedError(f"{pkt.dst_ip}:{pkt.dst_port} rejected")
            if isinstance(rule.target, tuple) and rule.target[0] == "DNAT":
                return rule.target[1], rule.target[2]
            res = self._eval_chain(rule.target, pkt, depth + 1, path)
            if res is not None:
                if chain.records_affinity and isinstance(rule.target, str):
                    path.append((name, rule.target))  # svc chain -> chosen sep
                return res
        return None


def _chain_hash(*parts: str) -> str:
    return hashlib.sha256("/".join(parts).encode()).hexdigest()[:16].upper()


class BoundedFrequencyRunner:
    """Serialize + rate-limit a sync function (the reference's
    async.BoundedFrequencyRunner): immediate run when outside the
    min interval, one deferred timer-run otherwise. Shared by the
    iptables and ipvs proxier modes."""

    def __init__(self, fn, min_interval: float = 0.0):
        self._fn = fn
        self._min = min_interval
        self._lock = threading.Lock()
        self._mutex = threading.Lock()  # serializes fn itself
        self._last = 0.0
        self._pending = False

    def run(self) -> None:
        with self._lock:
            now = time.time()
            if self._min and now - self._last < self._min:
                if not self._pending:
                    self._pending = True
                    delay = max(0.0, self._min - (now - self._last))
                    timer = threading.Timer(delay, self.flush)
                    timer.daemon = True
                    timer.start()
                return
            self._last = now
        with self._mutex:
            self._fn()

    def flush(self) -> None:
        with self._lock:
            if not self._pending:
                return
            self._pending = False
            self._last = time.time()
        with self._mutex:
            self._fn()

    def run_now(self) -> None:
        """Unconditional serialized run (tests / manual resync)."""
        with self._lock:
            self._last = time.time()
        with self._mutex:
            self._fn()


class Proxier:
    """Per-node proxy: informers -> Netfilter rule graph.

    Reference: pkg/proxy/iptables/proxier.go NewProxier + syncProxyRules;
    the reference's ServiceChangeTracker/EndpointChangeTracker feed the
    same full-state walk this performs straight from the informer caches.
    """

    def __init__(
        self,
        informer_factory,
        node_name: str = "",
        min_sync_period: float = 0.0,
        rng: Optional[random.Random] = None,
    ):
        self.node_name = node_name
        self.netfilter = Netfilter(rng=rng)
        self.slice_cache = EndpointSliceCache()
        # serializes rule synthesis (service and slice events arrive on
        # different informer dispatch threads; an older-snapshot sync must
        # not finish last and clobber newer rules) and rate-limits it
        self._runner = BoundedFrequencyRunner(
            self._sync_proxy_rules_locked, min_sync_period
        )
        self.sync_count = 0
        self.svc_informer = informer_factory.informer_for("services")
        self.slice_informer = informer_factory.informer_for("endpointslices")
        self.svc_informer.add_event_handler(
            EventHandler(
                on_add=lambda s: self._schedule_sync(),
                on_update=lambda o, n: self._schedule_sync(),
                on_delete=lambda s: self._schedule_sync(),
            )
        )
        self.slice_informer.add_event_handler(
            EventHandler(
                on_add=self._on_slice,
                on_update=lambda o, n: self._on_slice(n),
                on_delete=self._on_slice_delete,
            )
        )

    def _on_slice(self, sl) -> None:
        self.slice_cache.update_slice(sl)
        self._schedule_sync()

    def _on_slice_delete(self, sl) -> None:
        self.slice_cache.delete_slice(sl)
        self._schedule_sync()

    def _schedule_sync(self) -> None:
        self._runner.run()

    def flush_pending(self) -> None:
        """Run a sync if one was rate-limited (BoundedFrequencyRunner tick)."""
        self._runner.flush()

    # -- the resync ---------------------------------------------------------

    def sync_proxy_rules(self) -> None:
        self._runner.run_now()

    def _sync_proxy_rules_locked(self) -> None:
        chains: Dict[str, Chain] = {}
        services = Chain("KUBE-SERVICES")
        nodeports = Chain("KUBE-NODEPORTS")
        chains[services.name] = services
        chains[nodeports.name] = nodeports
        for svc in sorted(
            self.svc_informer.list(),
            key=lambda s: (s.metadata.namespace, s.metadata.name),
        ):
            if svc.spec.type == "ExternalName" or not svc.spec.cluster_ip:
                continue
            ns, name = svc.metadata.namespace, svc.metadata.name
            use_affinity = svc.spec.session_affinity == "ClientIP"
            for port in svc.spec.ports or []:
                svc_chain = f"KUBE-SVC-{_chain_hash(ns, name, port.name, port.protocol)}"
                eps = [
                    e
                    for e in self.slice_cache.endpoints_for(ns, name, port.name)
                    if e.ready
                ]
                is_nodeport = (
                    svc.spec.type in ("NodePort", "LoadBalancer") and port.node_port
                )
                if not eps:
                    # no ready endpoints: REJECT (proxier.go:1078, filter table)
                    services.rules.append(
                        Rule(
                            target="REJECT",
                            dst_ip=svc.spec.cluster_ip,
                            dst_port=port.port,
                            protocol=port.protocol,
                        )
                    )
                    if is_nodeport:
                        nodeports.rules.append(
                            Rule(
                                target="REJECT",
                                dst_port=port.node_port,
                                protocol=port.protocol,
                            )
                        )
                    continue
                svc_rules: List[Rule] = []
                if use_affinity:
                    svc_rules.append(Rule(target=None, affinity_check=True))
                for i, ep in enumerate(eps):
                    sep = f"KUBE-SEP-{_chain_hash(ns, name, port.name, ep.ip, str(ep.port))}"
                    chains[sep] = Chain(sep, [Rule(target=("DNAT", ep.ip, ep.port))])
                    remaining = len(eps) - i
                    # statistic-random cascade: P(k) = 1/(n-k) yields uniform
                    # selection across endpoints (proxier.go:1540)
                    svc_rules.append(
                        Rule(
                            target=sep,
                            probability=(1.0 / remaining) if remaining > 1 else None,
                        )
                    )
                chains[svc_chain] = Chain(
                    svc_chain, svc_rules, records_affinity=use_affinity
                )
                services.rules.append(
                    Rule(
                        target=svc_chain,
                        dst_ip=svc.spec.cluster_ip,
                        dst_port=port.port,
                        protocol=port.protocol,
                    )
                )
                if is_nodeport:
                    nodeports.rules.append(
                        Rule(
                            target=svc_chain,
                            dst_port=port.node_port,
                            protocol=port.protocol,
                        )
                    )
        # KUBE-SERVICES falls through to KUBE-NODEPORTS last (proxier.go:1292)
        services.rules.append(Rule(target="KUBE-NODEPORTS"))
        self.netfilter.replace(chains, CLIENT_IP_DEFAULT_TIMEOUT)
        self.sync_count += 1

    # -- client surface (the "kernel" path) ---------------------------------

    def route(self, pkt: Packet) -> Tuple[str, int]:
        """Route a flow; raises ConnectionRefusedError on REJECT and
        LookupError when no rule matches. Returns the DNAT (pod_ip, port)."""
        res = self.netfilter.route(pkt)
        if res is None:
            raise LookupError(f"no service rule for {pkt.dst_ip}:{pkt.dst_port}")
        return res
