"""IPVS proxier mode.

Reference: pkg/proxy/ipvs/proxier.go — syncProxyRules (:1023) programs
the kernel's IP Virtual Server table: one virtual server per
(clusterIP/nodePort, port, protocol) with the service's ready endpoints
as real servers, scheduled by rr/wrr/lc/sh... (--ipvs-scheduler,
default rr); session affinity uses IPVS persistence (timeout per
virtual server). `IPVSTable` models that kernel table; `IPVSProxier`
is the same informer-driven resync loop as the iptables mode
(proxy/proxier.py) targeting the table instead of chains.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.informer import EventHandler
from .endpointslicecache import EndpointSliceCache
from .proxier import CLIENT_IP_DEFAULT_TIMEOUT, BoundedFrequencyRunner, Packet


@dataclass
class RealServer:
    ip: str
    port: int
    weight: int = 1
    active_conn: int = 0


@dataclass
class VirtualServer:
    ip: str
    port: int
    protocol: str = "TCP"
    scheduler: str = "rr"  # rr | lc (least-connection) | sh (source hash)
    persistence_seconds: float = 0.0  # >0 = ClientIP affinity
    reals: List[RealServer] = field(default_factory=list)
    _rr_index: int = 0


class IPVSTable:
    """In-memory IP Virtual Server table with scheduling semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vs: Dict[Tuple[str, int, str], VirtualServer] = {}
        # persistence: (vs key, src ip) -> (real index key, stamp)
        self._affinity: Dict[Tuple, Tuple[Tuple[str, int], float]] = {}

    def replace(self, servers: List[VirtualServer]) -> None:
        with self._lock:
            new = {(v.ip, v.port, v.protocol): v for v in servers}
            # carry connection counts + rr position for unchanged servers
            for key, old in self._vs.items():
                cur = new.get(key)
                if cur is None:
                    continue
                cur._rr_index = old._rr_index
                by_addr = {(r.ip, r.port): r for r in cur.reals}
                for r in old.reals:
                    live = by_addr.get((r.ip, r.port))
                    if live is not None:
                        live.active_conn = r.active_conn
            self._vs = new
            live_keys = set(new)
            self._affinity = {
                k: v for k, v in self._affinity.items() if k[0] in live_keys
            }

    def virtual_servers(self) -> List[VirtualServer]:
        with self._lock:
            return list(self._vs.values())

    def route(self, pkt: Packet) -> Optional[Tuple[str, int]]:
        """Schedule one connection; None when no virtual server matches,
        ConnectionRefusedError when the VS has no real servers."""
        with self._lock:
            vs = self._vs.get((pkt.dst_ip, pkt.dst_port, pkt.protocol))
            if vs is None:
                return None
            if not vs.reals:
                raise ConnectionRefusedError(
                    f"{pkt.dst_ip}:{pkt.dst_port} has no real servers"
                )
            key = (pkt.dst_ip, pkt.dst_port, pkt.protocol)
            if vs.persistence_seconds > 0:
                hit = self._affinity.get((key, pkt.src_ip))
                if hit is not None and time.time() - hit[1] <= vs.persistence_seconds:
                    addr = hit[0]
                    real = next(
                        (r for r in vs.reals if (r.ip, r.port) == addr), None
                    )
                    if real is not None:
                        real.active_conn += 1
                        self._affinity[(key, pkt.src_ip)] = (addr, time.time())
                        return real.ip, real.port
            real = self._schedule(vs, pkt)
            real.active_conn += 1
            if vs.persistence_seconds > 0:
                self._affinity[(key, pkt.src_ip)] = (
                    (real.ip, real.port),
                    time.time(),
                )
            return real.ip, real.port

    @staticmethod
    def _schedule(vs: VirtualServer, pkt: Packet) -> RealServer:
        if vs.scheduler == "lc":
            return min(vs.reals, key=lambda r: (r.active_conn, r.ip))
        if vs.scheduler == "sh":
            return vs.reals[hash(pkt.src_ip) % len(vs.reals)]
        # rr
        real = vs.reals[vs._rr_index % len(vs.reals)]
        vs._rr_index += 1
        return real

    def conn_close(self, pkt_dst: Tuple[str, int, str], real: Tuple[str, int]) -> None:
        with self._lock:
            vs = self._vs.get(pkt_dst)
            if vs is None:
                return
            for r in vs.reals:
                if (r.ip, r.port) == real and r.active_conn > 0:
                    r.active_conn -= 1


class IPVSProxier:
    """Same resync loop as the iptables proxier, targeting IPVSTable."""

    def __init__(
        self,
        informer_factory,
        node_name: str = "",
        scheduler: str = "rr",
        min_sync_period: float = 0.0,
    ):
        self.node_name = node_name
        self.scheduler = scheduler
        self.table = IPVSTable()
        self.slice_cache = EndpointSliceCache()
        self._runner = BoundedFrequencyRunner(
            self._sync_proxy_rules_locked, min_sync_period
        )
        self.sync_count = 0
        self.svc_informer = informer_factory.informer_for("services")
        self.slice_informer = informer_factory.informer_for("endpointslices")
        self.svc_informer.add_event_handler(
            EventHandler(
                on_add=lambda s: self._runner.run(),
                on_update=lambda o, n: self._runner.run(),
                on_delete=lambda s: self._runner.run(),
            )
        )
        self.slice_informer.add_event_handler(
            EventHandler(
                on_add=self._on_slice,
                on_update=lambda o, n: self._on_slice(n),
                on_delete=self._on_slice_delete,
            )
        )

    def _on_slice(self, sl) -> None:
        self.slice_cache.update_slice(sl)
        self._runner.run()

    def _on_slice_delete(self, sl) -> None:
        self.slice_cache.delete_slice(sl)
        self._runner.run()

    def sync_proxy_rules(self) -> None:
        self._runner.run_now()

    def _sync_proxy_rules_locked(self) -> None:
        servers: List[VirtualServer] = []
        for svc in self.svc_informer.list():
            if svc.spec.type == "ExternalName" or not svc.spec.cluster_ip:
                continue
            ns, name = svc.metadata.namespace, svc.metadata.name
            persistence = (
                CLIENT_IP_DEFAULT_TIMEOUT
                if svc.spec.session_affinity == "ClientIP"
                else 0.0
            )
            for port in svc.spec.ports or []:
                reals = [
                    RealServer(ip=e.ip, port=e.port)
                    for e in self.slice_cache.endpoints_for(ns, name, port.name)
                    if e.ready
                ]
                servers.append(
                    VirtualServer(
                        ip=svc.spec.cluster_ip,
                        port=port.port,
                        protocol=port.protocol,
                        scheduler=self.scheduler,
                        persistence_seconds=persistence,
                        reals=reals,
                    )
                )
                if (
                    svc.spec.type in ("NodePort", "LoadBalancer")
                    and port.node_port
                ):
                    # ipvs binds nodePorts on the node's own addresses;
                    # model with a wildcard node address
                    servers.append(
                        VirtualServer(
                            ip="0.0.0.0",
                            port=port.node_port,
                            protocol=port.protocol,
                            scheduler=self.scheduler,
                            persistence_seconds=persistence,
                            reals=[
                                RealServer(ip=r.ip, port=r.port) for r in reals
                            ],
                        )
                    )
        self.table.replace(servers)
        self.sync_count += 1

    def route(self, pkt: Packet) -> Tuple[str, int]:
        res = self.table.route(pkt)
        if res is None and pkt.dst_ip != "0.0.0.0":
            # nodePort fallthrough: any node address -> the 0.0.0.0 VS
            res = self.table.route(
                Packet("0.0.0.0", pkt.dst_port, pkt.protocol, pkt.src_ip)
            )
        if res is None:
            raise LookupError(f"no virtual server for {pkt.dst_ip}:{pkt.dst_port}")
        return res
