from .proxier import Netfilter, Packet, Proxier
from .endpointslicecache import EndpointSliceCache

__all__ = ["Netfilter", "Packet", "Proxier", "EndpointSliceCache"]
