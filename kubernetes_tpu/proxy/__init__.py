from .proxier import Netfilter, Packet, Proxier
from .ipvs import IPVSProxier, IPVSTable
from .endpointslicecache import EndpointSliceCache

__all__ = [
    "Netfilter",
    "Packet",
    "Proxier",
    "IPVSProxier",
    "IPVSTable",
    "EndpointSliceCache",
]
