"""EndpointSlice cache: merge a service's slices into one endpoint list.

Reference: pkg/proxy/endpointslicecache.go — EndpointSliceCache keeps
per-service slice info (updatePending/checkoutChanges) and
endpointInfoByServicePort (:204) flattens every tracked slice of a service
into per-port endpoint lists, deduplicating by address.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import discovery


@dataclass(frozen=True)
class EndpointInfo:
    ip: str
    port: int
    ready: bool
    node_name: str


class EndpointSliceCache:
    def __init__(self):
        self._lock = threading.Lock()
        # (namespace, service) -> {slice name -> EndpointSlice}
        self._slices: Dict[Tuple[str, str], Dict[str, discovery.EndpointSlice]] = {}

    @staticmethod
    def _service_key(sl: discovery.EndpointSlice) -> Optional[Tuple[str, str]]:
        svc = (sl.metadata.labels or {}).get(discovery.LABEL_SERVICE_NAME)
        if not svc:
            return None
        return (sl.metadata.namespace, svc)

    def update_slice(self, sl: discovery.EndpointSlice) -> None:
        key = self._service_key(sl)
        if key is None:
            return
        with self._lock:
            self._slices.setdefault(key, {})[sl.metadata.name] = sl

    def delete_slice(self, sl: discovery.EndpointSlice) -> None:
        key = self._service_key(sl)
        if key is None:
            return
        with self._lock:
            per_svc = self._slices.get(key)
            if per_svc is not None:
                per_svc.pop(sl.metadata.name, None)
                if not per_svc:
                    self._slices.pop(key, None)

    def endpoints_for(
        self, namespace: str, service: str, port_name: str
    ) -> List[EndpointInfo]:
        """Flattened, deduplicated endpoints of one service port
        (endpointInfoByServicePort)."""
        with self._lock:
            slices = list(self._slices.get((namespace, service), {}).values())
        seen = set()
        out: List[EndpointInfo] = []
        for sl in slices:
            port_num = None
            for p in sl.ports or []:
                if p.name == port_name:
                    port_num = p.port
                    break
            if port_num is None:
                continue
            for ep in sl.endpoints or []:
                for addr in ep.addresses:
                    if addr in seen:
                        continue
                    seen.add(addr)
                    out.append(
                        EndpointInfo(
                            ip=addr,
                            port=port_num,
                            ready=ep.conditions.ready,
                            node_name=ep.node_name,
                        )
                    )
        out.sort(key=lambda e: e.ip)
        return out
