"""NetworkPolicy evaluation model.

Reference semantics: the NetworkPolicy API contract
(staging/src/k8s.io/api/networking/v1/types.go:30 + the conformance
behaviors CNI plugins implement — kube-proxy itself does not enforce
NetworkPolicy; this model is the data-plane twin the same way
proxier.py models the Service chains without a kernel):

  * a pod UNSELECTED by any policy for a direction accepts everything
    in that direction (default-allow);
  * once ANY policy selects it for a direction, only traffic matched by
    SOME rule of SOME selecting policy passes (policies are additive,
    whitelist-only);
  * a rule with no peers matches every source/destination; a rule with
    no ports matches every port;
  * peers match by podSelector (same namespace unless a
    namespaceSelector is present), namespaceSelector (any pod in
    matching namespaces), both ANDed when both are set, or ipBlock
    (CIDR minus excepts).
"""

from __future__ import annotations

import ipaddress
from typing import Dict, List, Optional, Sequence

from ..api import types as v1
from ..api.labels import Selector
from ..api.networking import (
    NetworkPolicy,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    POLICY_TYPE_EGRESS,
    POLICY_TYPE_INGRESS,
    effective_policy_types,
)


class Endpoint:
    """One traffic endpoint: a pod (labels + namespace + ip) or a bare
    IP (external traffic)."""

    __slots__ = ("namespace", "labels", "ip", "named_ports")

    def __init__(self, namespace: str = "", labels: Optional[Dict] = None,
                 ip: str = "", named_ports: Optional[Dict] = None):
        self.namespace = namespace
        self.labels = labels or {}
        self.ip = ip
        # container port name -> (containerPort, protocol): named
        # NetworkPolicyPort targets resolve against the DESTINATION
        # pod's container specs PER (name, protocol) — a UDP "web"
        # container port must not satisfy a TCP policy port (types.go:
        # the named lookup matches both fields). Bare-int values are
        # accepted and read as TCP (the ContainerPort default).
        self.named_ports = {
            name: (v if isinstance(v, tuple) else (v, "TCP"))
            for name, v in (named_ports or {}).items()
        }

    @classmethod
    def from_pod(cls, pod: v1.Pod) -> "Endpoint":
        named = {
            p.name: (p.container_port, getattr(p, "protocol", None) or "TCP")
            for c in pod.spec.containers or []
            for p in c.ports or []
            if getattr(p, "name", None)
        }
        return cls(
            namespace=pod.metadata.namespace,
            labels=dict(pod.metadata.labels or {}),
            ip=pod.status.pod_ip,
            named_ports=named,
        )

    @classmethod
    def external(cls, ip: str) -> "Endpoint":
        return cls(ip=ip)

    @property
    def is_pod(self) -> bool:
        return bool(self.namespace)


class NetworkPolicyEvaluator:
    """Evaluates allowed() over a policy set + namespace labels."""

    def __init__(self, policies: Sequence[NetworkPolicy],
                 namespaces: Optional[Dict[str, Dict[str, str]]] = None):
        self.policies = list(policies)
        # namespace name -> labels (namespaceSelector targets)
        self.namespaces = namespaces or {}

    def _selecting(self, pod: Endpoint, direction: str) -> List[NetworkPolicy]:
        out = []
        for pol in self.policies:
            if pol.metadata.namespace != pod.namespace:
                continue
            if direction not in effective_policy_types(pol.spec):
                continue
            sel = Selector.from_label_selector(pol.spec.pod_selector)
            if sel.matches(pod.labels):
                out.append(pol)
        return out

    def _peer_matches(self, peer: NetworkPolicyPeer, other: Endpoint,
                      policy_ns: str) -> bool:
        if peer.ip_block is not None:
            if not other.ip:
                return False
            try:
                addr = ipaddress.ip_address(other.ip)
                if addr not in ipaddress.ip_network(peer.ip_block.cidr):
                    return False
                for ex in peer.ip_block.except_ or []:
                    if addr in ipaddress.ip_network(ex):
                        return False
                return True
            except ValueError:
                return False
        if not other.is_pod:
            return False  # selector peers never match external IPs
        if peer.namespace_selector is not None:
            ns_labels = self.namespaces.get(other.namespace, {})
            if not Selector.from_label_selector(
                peer.namespace_selector
            ).matches(ns_labels):
                return False
            if peer.pod_selector is not None:
                return Selector.from_label_selector(
                    peer.pod_selector
                ).matches(other.labels)
            return True
        if peer.pod_selector is not None:
            # no namespaceSelector: same-namespace pods only (types.go)
            return other.namespace == policy_ns and \
                Selector.from_label_selector(
                    peer.pod_selector
                ).matches(other.labels)
        return False

    @staticmethod
    def _port_matches(ports: Optional[List[NetworkPolicyPort]],
                      port: int, protocol: str, dst: Endpoint) -> bool:
        if not ports:
            return True  # no ports = every port
        for p in ports:
            if (p.protocol or "TCP") != protocol:
                continue
            if p.port is None:
                return True
            lo = p.port
            if isinstance(lo, str):
                # named port: resolves against the destination pod's
                # container specs per (name, protocol) — a name whose
                # container port carries a different protocol resolves
                # to nothing; unresolvable names match nothing
                # (endPort is invalid with a named port, types.go)
                resolved = dst.named_ports.get(lo)
                if resolved is None:
                    continue
                num, proto = resolved
                if proto != (p.protocol or "TCP"):
                    continue
                if port == num:
                    return True
                continue
            hi = p.end_port if p.end_port is not None else lo
            if lo <= port <= hi:
                return True
        return False

    def allowed(self, src: Endpoint, dst: Endpoint, port: int,
                protocol: str = "TCP") -> bool:
        """Both directions must pass: dst's ingress policies AND src's
        egress policies (conformance: a connection needs both sides).
        `port` is a port on dst; named policy ports resolve against dst."""
        return self._direction_allowed(
            dst, src, port, protocol, POLICY_TYPE_INGRESS
        ) and self._direction_allowed(
            src, dst, port, protocol, POLICY_TYPE_EGRESS
        )

    def _direction_allowed(self, subject: Endpoint, other: Endpoint,
                           port: int, protocol: str, direction: str) -> bool:
        if not subject.is_pod:
            return True  # external endpoints are not policy subjects
        # traffic destination: the subject for ingress, the remote for
        # egress — named policy ports always resolve against it
        dst = subject if direction == POLICY_TYPE_INGRESS else other
        selecting = self._selecting(subject, direction)
        if not selecting:
            return True  # default-allow when unselected
        for pol in selecting:
            rules = (
                pol.spec.ingress if direction == POLICY_TYPE_INGRESS
                else pol.spec.egress
            ) or []
            for rule in rules:
                peers = (
                    rule.from_ if direction == POLICY_TYPE_INGRESS
                    else rule.to
                )
                if not self._port_matches(rule.ports, port, protocol, dst):
                    continue
                if not peers:
                    return True  # no peers = every counterpart
                if any(
                    self._peer_matches(p, other, pol.metadata.namespace)
                    for p in peers
                ):
                    return True
        return False
