"""Batched sequential scheduling: B pods in ONE device dispatch.

The reference schedules strictly one pod per cycle (reference:
pkg/scheduler/scheduler.go:427 scheduleOne), paying the full host loop per
pod. Under a TPU tunnel, per-pod dispatch latency dominates; this module
keeps the decision semantics sequential — pod i sees the assumed state of
pods 0..i-1, exactly like the assume-cache (pkg/scheduler/internal/cache/
cache.go:361 AssumePod) — but runs the whole batch inside one `lax.scan`:

    carry = mutable slice of cluster state (requested, nz_requested,
            pod_count + the pod-row table)
    step  = fused filter/score kernel (ops/kernel.py) -> argmax ->
            in-carry assume update

Restrictions (callers fall back to the per-pod path otherwise):
  * batch pods must share encoded array shapes (template-stamped pods do);
  * batch pods must carry no pod-(anti-)affinity terms and no host ports —
    those mutate the term/port tables, which stay static in the carry.
    Labels, resources, spread constraints, node affinity are all fine:
    their effect on later pods flows through the carried pod rows.

Tie-breaking is lowest-node-index (deterministic argmax) rather than the
reference's reservoir sample over ties (core/generic_scheduler.go:152);
the A/B decision tests pin the oracle to the same rule.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kernel import DEFAULT_WEIGHTS, schedule_pod

# cluster arrays mutated by the in-scan assume update
CARRY_KEYS = (
    "requested", "nz_requested", "pod_count",
    "ppair", "pkey", "pnode", "pns", "pterm", "pvalid",
)


def _step(static_c: Dict, weights: Dict, carry: Dict, x: Dict):
    c = dict(static_c)
    c.update(carry)
    out = schedule_pod(c, x["pod"], weights)
    total = out["total"]
    best = jnp.argmax(total).astype(jnp.int32)
    feasible = (total[best] >= 0) & x["valid"]
    p = x["pod"]
    add = feasible.astype(jnp.int64)
    carry = dict(carry)
    carry["requested"] = carry["requested"].at[best].add(p["req"] * add)
    carry["nz_requested"] = carry["nz_requested"].at[best].add(p["nz_req"] * add)
    carry["pod_count"] = carry["pod_count"].at[best].add(add.astype(jnp.int32))
    pidx = x["pidx"]
    carry["pvalid"] = carry["pvalid"].at[pidx].set(feasible)
    carry["ppair"] = carry["ppair"].at[pidx].set(p["self_ppair"])
    carry["pkey"] = carry["pkey"].at[pidx].set(p["self_pkey"])
    carry["pnode"] = carry["pnode"].at[pidx].set(jnp.where(feasible, best, 0))
    carry["pns"] = carry["pns"].at[pidx].set(p["self_ns"])
    carry["pterm"] = carry["pterm"].at[pidx].set(False)
    y = {
        "best": jnp.where(feasible, best, -1),
        "score": jnp.where(feasible, total[best], -1),
        "n_feasible": jnp.sum(out["feasible"].astype(jnp.int32)),
    }
    return carry, y


@functools.partial(jax.jit, static_argnames=("weights_key",))
def _scan_batch(static_c: Dict, carry: Dict, xs: Dict, weights_key) -> Tuple[Dict, Dict]:
    # NOTE: no buffer donation — the carry aliases ClusterEncoding's cached
    # device arrays; donating would invalidate its copies.
    step = functools.partial(_step, static_c, dict(weights_key))
    return jax.lax.scan(step, carry, xs)


# -- pod-array packing ------------------------------------------------------
# Tunneled TPUs pay a round-trip per host->device transfer; a batch's ~50
# stacked pod arrays are therefore packed host-side into one buffer per
# dtype group (bool / int32-ish / int64) and sliced back apart on-device
# inside the jit. 3 transfers per batch instead of ~50.

_GROUP_OF_DTYPE = {
    np.dtype(np.bool_): ("b", np.bool_),
    np.dtype(np.int8): ("i4", np.int32),
    np.dtype(np.int16): ("i4", np.int32),
    np.dtype(np.int32): ("i4", np.int32),
    np.dtype(np.int64): ("i8", np.int64),
}


def _pack_stacked(stacked: Dict[str, np.ndarray]):
    """-> ({group: [B, W] array}, layout) with layout hashable/static."""
    b = next(iter(stacked.values())).shape[0]
    offsets = {"b": 0, "i4": 0, "i8": 0}
    chunks = {"b": [], "i4": [], "i8": []}
    layout = []
    for key in sorted(stacked):
        arr = stacked[key]
        group, gdtype = _GROUP_OF_DTYPE[arr.dtype]
        flat = np.ascontiguousarray(arr.reshape(b, -1), dtype=gdtype)
        layout.append(
            (key, group, offsets[group], flat.shape[1], arr.shape[1:], arr.dtype.str)
        )
        offsets[group] += flat.shape[1]
        chunks[group].append(flat)
    packed = {
        g: (
            np.concatenate(chunks[g], axis=1)
            if chunks[g]
            else np.zeros((b, 0), np.dtype(np.bool_ if g == "b" else np.int32))
        )
        for g in chunks
    }
    return packed, tuple(layout)


def _unpack_stacked(packed: Dict, layout) -> Dict:
    """Inverse of _pack_stacked, traceable (runs inside jit)."""
    out = {}
    for key, group, off, width, shape, dtype_str in layout:
        b = packed[group].shape[0]
        sl = jax.lax.slice_in_dim(packed[group], off, off + width, axis=1)
        out[key] = sl.reshape((b,) + tuple(shape)).astype(jnp.dtype(dtype_str))
    return out


@functools.partial(jax.jit, static_argnames=("weights_key", "layout"))
def _scan_batch_packed(
    static_c: Dict, carry: Dict, packed: Dict, pidx, valid, weights_key, layout
) -> Tuple[Dict, Dict]:
    xs = {"pod": _unpack_stacked(packed, layout), "pidx": pidx, "valid": valid}
    step = functools.partial(_step, static_c, dict(weights_key))
    return jax.lax.scan(step, carry, xs)


def pod_batchable(pod_arrays: Dict) -> bool:
    """True if the encoded pod leaves term/port tables untouched when
    assumed: no required/preferred (anti-)affinity terms, no host ports."""
    return not (
        np.asarray(pod_arrays["ipaa_valid"]).any()
        or np.asarray(pod_arrays["ipaaa_valid"]).any()
        or np.asarray(pod_arrays["ipap_valid"]).any()
        or np.asarray(pod_arrays["want_valid"]).any()
    )


def shape_signature(pod_arrays: Dict) -> Tuple:
    return tuple(sorted((k, np.shape(v)) for k, v in pod_arrays.items()))


def schedule_batch(
    cluster: Dict,
    pod_arrays_list: List[Dict],
    free_slots: List[int],
    weights: Optional[Dict[str, int]] = None,
) -> Tuple[List[int], Dict]:
    """Schedule the batch sequentially on-device.

    cluster: full device dict (models/encoding.py device_state()).
    pod_arrays_list: encoded pods, all with identical shapes.
    free_slots: pre-allocated pod-table row ids, len >= len(batch).

    Returns (decisions, new_carry): decisions[i] is the chosen node index
    or -1; new_carry holds the post-batch mutable arrays (callers sync the
    host encoding from the returned decisions instead).
    """
    b = len(pod_arrays_list)
    assert len(free_slots) >= b
    sig0 = shape_signature(pod_arrays_list[0])
    for pa in pod_arrays_list[1:]:
        assert shape_signature(pa) == sig0, "batch pods must share shapes"
    # stack host-side, then pack into 3 dtype-grouped buffers: transfers
    # per batch drop from ~50 (one per key) to 3 — decisive on tunneled TPUs
    stacked = {
        k: np.stack([np.asarray(pa[k]) for pa in pod_arrays_list])
        for k in pod_arrays_list[0]
        if not k.startswith("_")
    }
    packed, layout = _pack_stacked(stacked)
    static_c = {k: v for k, v in cluster.items() if k not in CARRY_KEYS}
    carry = {k: cluster[k] for k in CARRY_KEYS}
    key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    new_carry, ys = _scan_batch_packed(
        static_c,
        carry,
        {g: jnp.asarray(a) for g, a in packed.items()},
        jnp.asarray(np.asarray(free_slots[:b], np.int32)),
        jnp.ones(b, bool),
        key,
        layout,
    )
    return [int(v) for v in np.asarray(ys["best"])], new_carry
