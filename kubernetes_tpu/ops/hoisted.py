"""Template-hoisted batched scheduling: the pod-table sweeps leave the scan.

The generic batched scan (ops/batch.py) re-evaluates the incoming pod's
selector tables against the ENTIRE pod table every step — ~4.2ms/pod of
the measured cost, all of it redundant for template-stamped workloads:

  * batch pods are stamped from <= a few distinct templates, so the
    selector tables repeat;
  * during one scan the pod table is STATIC — batchable pods (no
    affinity terms, no host ports: ops/batch.py pod_batchable) never
    mutate the term/port tables, and assumed pods' effects on
    PodTopologySpread counts are additive one-column updates.

So everything except NodeResourcesFit/BalancedAllocation/LeastAllocated
(which read the carried utilization) and the PTS pair counts is computed
ONCE per template in a prologue, and the counts are carried incrementally:
assuming pod j on node b adds its precomputed per-template match vector to
column b. The step body is then O(N + C·Vnp) instead of O(P·C·R·V).

Decision parity with the generic path (and therefore with the Go-semantics
oracle) is pinned by tests/test_hoisted.py.

Reference frame: this replaces findNodesThatPassFilters +
RunScorePlugins (pkg/scheduler/core/generic_scheduler.go:235,
pkg/scheduler/framework/runtime/framework.go:723) exactly like the
generic kernel, but restructured the way the PreFilter/PreScore split
intends (precompute once, reuse per node) — lifted to precompute once per
TEMPLATE per BATCH.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import knobs
from . import kernel as K
from .eval import eval_reqs, eval_reqs_single, ns_member
from .kernel import _CNT, _F64, _I64, DEFAULT_WEIGHTS

# carried cluster arrays (utilization only — pod-table rows are NOT
# written in-scan; the host syncs them after the batch, as bench.py does).
# When session templates have host ports, copies of the node port tables
# join the carry as cp_any/cp_wild/cp_trip (_init_dynamic_carries).
CARRY_KEYS = ("requested", "nz_requested", "pod_count")

TEMPLATE_KEYS_EXCLUDED = ("node_name_idx", "has_node_name")

# Explain mode (KTPU_EXPLAIN): canonical per-plugin attribution orders.
# Filter verdicts pack into ONE int32 per node — bit i set = plugin i
# passed the node — in EXPLAIN_FILTER_PLUGINS order (the oracle filter
# plugins the kernel models; volume constraints ride the NodeAffinity /
# NodeResourcesFit masks). Score rows stack in EXPLAIN_SCORE_KEYS order
# and are already WEIGHTED, matching kernel.schedule_pod's
# score_<key> = normalized * weight convention, so a row sums to the
# decision total on feasible nodes.
EXPLAIN_FILTER_PLUGINS = (
    "NodeName", "NodeUnschedulable", "TaintToleration", "NodePorts",
    "NodeResourcesFit", "NodeAffinity", "PodTopologySpread",
    "InterPodAffinity",
)
EXPLAIN_SCORE_KEYS = (
    "balanced", "image", "ipa", "least", "node_affinity",
    "prefer_avoid", "pts", "taint",
)


_FP_MEMO = None  # id(anchor array) -> fingerprint; finalizer-evicted


def template_fingerprint(pod_arrays: Dict) -> Tuple:
    """Identity of the scheduling-relevant template: every encoded array
    except the per-pod node-name fields (which must be absent/false for
    batchable pending pods anyway).

    Memoized on the identity of the self_ppair buffer: the pod encoder
    caches encodings by spec fingerprint and hands out shallow copies, so
    same-template pods share the SAME array objects — hashing ~50 arrays
    (tobytes over a multi-KB label bitmap among them) per pod per batch
    was a measurable slice of the full-loop host cost at 4096-pod
    batches. Arrays are never mutated after encode; a fresh array (tests,
    non-encoder callers) simply misses the memo and pays the hash."""
    global _FP_MEMO
    if _FP_MEMO is None:
        _FP_MEMO = {}
    anchor = pod_arrays.get("self_ppair")
    if isinstance(anchor, np.ndarray):
        # ndarrays are unhashable, so key by id(); a weakref finalizer
        # evicts the entry when the array dies, BEFORE the id can be
        # reused (CPython refcounting runs finalizers at free time)
        hit = _FP_MEMO.get(id(anchor))
        if hit is not None:
            return hit
    else:
        anchor = None
    items = []
    for k in sorted(pod_arrays):
        if k.startswith("_") or k in TEMPLATE_KEYS_EXCLUDED:
            continue
        a = np.asarray(pod_arrays[k])
        items.append((k, a.shape, a.dtype.str, a.tobytes()))
    fp = tuple(items)
    if anchor is not None:
        import weakref

        key = id(anchor)
        _FP_MEMO[key] = fp
        weakref.finalize(anchor, _FP_MEMO.pop, key, None)
    return fp


def _stack_templates(templates: List[Dict]) -> Dict:
    out = {
        k: jnp.asarray(np.stack([np.asarray(t[k]) for t in templates]))
        for k in templates[0]
        if not k.startswith("_") and k not in TEMPLATE_KEYS_EXCLUDED
    }
    # kernel sections read these; hoisted pods are asserted unbound
    t = len(templates)
    out["has_node_name"] = jnp.zeros(t, bool)
    out["node_name_idx"] = jnp.full(t, -1, jnp.int32)
    return out


# ---------------------------------------------------------------------------
# template term machinery: what makes affinity/host-port pods batchable.
#
# A session-assumed pod of template u changes, for every LATER pod of
# template t, exactly these InterPodAffinity quantities (filtering.go /
# scoring.go semantics):
#   D1 its required ANTI terms now repel t wherever t matches them;
#   D2 it now counts toward t's own required-anti term counts;
#   D3 it now counts toward t's required-affinity term counts (iff it
#      matches ALL of t's terms);
#   D4 its score terms (required-affinity at hardPodAffinityWeight,
#      preferred ±weight) now contribute to t's raw IPA score;
#   D5 it now counts toward t's preferred-term score counts.
# All five reduce to TOPOLOGY-GROUP COUNTS of assumed pods — "how many
# assumed u-pods sit on nodes sharing (key k, value of candidate node)" —
# gated by STATIC template×term match booleans (a template's self labels
# vs another template's term selector+namespaces). So the scan carries
#   u_cnt[U, Vnp]  assumed-pod counts per template per (key,value) pair id
#   k_cnt[U, K]    assumed-pod counts per template per topology key
# and the step combines per-term gathers of u_cnt with the prologue's
# static counts through kernel.ipa_compose — the same composition the
# one-pod kernel uses, so parity is structural. Host ports ride the same
# way: the node port tables join the carry and the step recomputes the
# NodePorts mask against them (encoding._apply_ports semantics).


def _term_gates(tp: Dict):
    """Static template×term match tensors.

    M_anti[a, τ, b]: template b's self row matches template a's required
    anti-affinity term τ (selector + namespaces + validity). Same layout
    for M_aff (required affinity) and M_pref (preferred, signed-weight
    terms). match_all[a, b]: b matches ALL of a's required-affinity terms
    (podMatchesAllAffinityTerms, filtering.go:357)."""

    def vs_entity(pp, pk, ns):
        def fam(prefix):
            m = eval_reqs_single(
                tp[f"{prefix}_op"], tp[f"{prefix}_rkey"], tp[f"{prefix}_pairs"],
                pp, pk,
            )  # [T, X]
            return m & ns_member(tp[f"{prefix}_ns"], ns) & tp[f"{prefix}_valid"]

        return fam("ipaaa"), fam("ipaa"), fam("ipap")

    m_anti, m_aff, m_pref = jax.vmap(vs_entity, out_axes=-1)(
        tp["self_ppair"], tp["self_pkey"], tp["self_ns"]
    )  # each [T(owner), X, T(entity)]
    has_aff = jnp.any(tp["ipaa_valid"], axis=1)  # [T]
    match_all = (
        jnp.all(jnp.where(tp["ipaa_valid"][:, :, None], m_aff, True), axis=1)
        & has_aff[:, None]
    )  # [T(owner), T(entity)]
    # template-level IPA interference for the multipod conflict test:
    # G[u, t] true when assuming a template-u pod can perturb ANY of the
    # D1-D5 quantities a template-t evaluation reads (u_cnt[u]/k_cnt[u]
    # flow through M_anti[u,:,t] / M_anti[t,:,u] / match_all[t,u] /
    # M_aff[u,:,t] / M_pref[u,:,t] / M_pref[t,:,u]). Symmetrized: a
    # conservative superset is sound — a false positive only costs a
    # replay, never a wrong decision.
    a1 = jnp.any(m_anti, axis=1)
    a2 = jnp.any(m_aff, axis=1)
    a3 = jnp.any(m_pref, axis=1)
    g = (a1 | a1.T | a2 | a2.T | a3 | a3.T | match_all | match_all.T)
    return {
        "M_anti": m_anti, "M_aff": m_aff, "M_pref": m_pref,
        "match_all": match_all, "G_ipa": g,
    }


def templates_have_terms(templates: List[Dict]) -> bool:
    return any(
        np.asarray(t["ipaa_valid"]).any()
        or np.asarray(t["ipaaa_valid"]).any()
        or np.asarray(t["ipap_valid"]).any()
        for t in templates
    )


def templates_have_ports(templates: List[Dict]) -> bool:
    return any(np.asarray(t["want_valid"]).any() for t in templates)


def _port_add_vectors(templates: List[Dict], vp: int, vt: int):
    """Per-template port-table increments for one assumed pod, with
    HostPortInfo's per-(ip,proto,port) set semantics (dedup by triple id —
    mirrors encoding._apply_ports exactly)."""
    t_n = len(templates)
    add_any = np.zeros((t_n, vp), np.int32)
    add_wild = np.zeros((t_n, vp), np.int32)
    add_trip = np.zeros((t_n, vt), np.int32)
    for t, pa in enumerate(templates):
        valid = np.asarray(pa["want_valid"])
        trips = np.asarray(pa["want_triple"])[valid]
        pairs = np.asarray(pa["want_pair"])[valid]
        wild = np.asarray(pa["want_wild"])[valid]
        seen = set()
        for tr, pr, wl in zip(trips, pairs, wild):
            if int(tr) in seen:
                continue
            seen.add(int(tr))
            add_trip[t, tr] += 1
            add_any[t, pr] += 1
            if wl:
                add_wild[t, pr] += 1
    return add_any, add_wild, add_trip


# ---------------------------------------------------------------------------
# prologue: per-template static data + initial PTS counts


def _pts_template_static(c: Dict, p: Dict, node_match):
    """Static PTS data for one template (both filter and score passes)."""
    n = c["valid"].shape[0]
    vnp = c["npair"].shape[1]
    col = jnp.arange(vnp)[None, :]

    def shared(prefix):
        valid_c = p[f"{prefix}_valid"]
        key_c = p[f"{prefix}_key"]
        pair_cn = c["pair_of_key"][:, key_c]              # [N, C]
        key_on_node = c["nkey"][:, key_c]                 # [N, C]
        has_all = jnp.all(jnp.where(valid_c[None, :], key_on_node, True), axis=1)
        match = eval_reqs(
            p[f"{prefix}_op"], p[f"{prefix}_rkey"], p[f"{prefix}_pairs"],
            c["ppair"], c["pkey"],
        )
        match = (
            match
            & c["pvalid"][:, None]
            & ~c["pterm"][:, None]
            & (c["pns"] == p["self_ns"])[:, None]
        )  # [P, C]
        node_counts = jax.vmap(
            lambda m: K._seg_sum(m.astype(_CNT), c["pnode"], n), in_axes=1
        )(match)  # [C, N]
        same_key = (
            (key_c[:, None] == key_c[None, :]) & valid_c[:, None] & valid_c[None, :]
        )
        self_match = eval_reqs_single(
            p[f"{prefix}_op"], p[f"{prefix}_rkey"], p[f"{prefix}_pairs"],
            p["self_ppair"], p["self_pkey"],
        ).astype(_CNT)
        return dict(
            valid_c=valid_c, key_c=key_c, pair_cn=pair_cn,
            key_on_node=key_on_node, has_all=has_all,
            node_counts=node_counts, same_key=same_key, self_match=self_match,
        )

    f = shared("ptsf")
    s = shared("ptss")

    # filter: registered pairs over eligible nodes (filtering.go:224) —
    # eligibility is nodeSelector/affinity + keys, NOT feasibility: static
    eligible = node_match & f["has_all"] & c["valid"]
    reg_f = jax.vmap(
        lambda pids: K._seg_max_bool(eligible, jnp.where(eligible, pids, 0), vnp),
        in_axes=1,
    )(f["pair_cn"])
    reg_real_f = reg_f & (col > 0)
    cnt_f0 = jax.vmap(
        lambda cnts, pids: K._seg_sum(cnts, pids, vnp), in_axes=(0, 1)
    )(f["node_counts"], f["pair_cn"])  # [C, Vnp]

    # score: count eligibility (scoring.go:252) is static; pair
    # REGISTRATION is over filtered nodes — feasibility-dependent, so it
    # stays in the step
    src = node_match & s["has_all"] & c["valid"]          # [N]
    cnt_s0 = jax.vmap(
        lambda cnts, pids: K._seg_sum(cnts * src.astype(_CNT), pids, vnp),
        in_axes=(0, 1),
    )(s["node_counts"], s["pair_cn"])  # [C, Vnp]

    return dict(
        # filter statics
        f_valid=f["valid_c"], f_pair_cn=f["pair_cn"],
        f_key_on_node=f["key_on_node"], f_same_key=f["same_key"],
        f_self_match=f["self_match"], f_reg_real=reg_real_f,
        f_skew=p["ptsf_skew"].astype(_CNT), f_cnt0=cnt_f0,
        # score statics
        s_valid=s["valid_c"], s_pair_cn=s["pair_cn"],
        s_key_on_node=s["key_on_node"], s_has_all=s["has_all"],
        s_same_key=s["same_key"], s_src=src,
        s_hostname=p["ptss_hostname"], s_first=p["ptss_first"],
        s_skew=p["ptss_skew"], s_cnt0=cnt_s0, h_cnt0=s["node_counts"],
    )


def _prologue(c: Dict, tp: Dict, dyn_ipa: bool = False, dyn_ports: bool = False,
              explain: bool = False):
    """Per-template static arrays, stacked over the template axis.

    dyn_ipa/dyn_ports: leave the InterPodAffinity mask / NodePorts mask
    OUT of static_mask and expose their static parts separately, so the
    scan step can recombine them with in-scan dynamic counts.

    explain: additionally keep the individual pre-fold masks (normally
    folded into static_mask and discarded) so the step can attribute a
    rejected node to the exact plugin that filtered it."""

    def one(p):
        node_match = K._node_match(c, p)
        _, mask_unsched, mask_taint, mask_ports, _ = K._filter_basics(c, p)
        parts = K._ipa_filter_parts(c, p)
        mask_ipa, _ = K.ipa_compose(p, parts)
        static_mask = c["valid"] & mask_unsched & mask_taint & node_match
        if not dyn_ports:
            static_mask = static_mask & mask_ports
        if not dyn_ipa:
            static_mask = static_mask & mask_ipa
        raw_ipa, ipa_present = K._score_ipa_raw(c, p)
        out = dict(
            static_mask=static_mask,
            node_match=node_match,
            raw_ipa=raw_ipa,
            ipa_present=ipa_present,
            cnt_taint=K._taint_count(c, p),
            cnt_nodeaff=K._nodeaff_count(c, p),
            sc_image=K._score_image(c, p),
            sc_avoid=K._score_prefer_avoid(c, p),
        )
        if explain:
            out.update(
                expl_unsched=mask_unsched,
                expl_taint=mask_taint,
                expl_ports=mask_ports,
                expl_ipa=mask_ipa,
            )
        if dyn_ipa:
            out.update({f"ipa_{k}": v for k, v in parts.items()})
        out.update(_pts_template_static(c, p, node_match))
        return out

    S = jax.vmap(one)(tp)
    if dyn_ipa:
        S.update(_term_gates(tp))
    return S


def _match_matrices(tp: Dict, batch: Dict):
    """Mf/Ms [T, B, C]: does batch pod b's row match template t's
    PTS constraint selectors (incl. the namespace gate)?"""

    def one_t(p):
        def one_b(self_ppair, self_pkey, ns):
            mf = eval_reqs_single(
                p["ptsf_op"], p["ptsf_rkey"], p["ptsf_pairs"], self_ppair, self_pkey
            ) & (ns == p["self_ns"])
            ms = eval_reqs_single(
                p["ptss_op"], p["ptss_rkey"], p["ptss_pairs"], self_ppair, self_pkey
            ) & (ns == p["self_ns"])
            return mf.astype(_CNT), ms.astype(_CNT)

        return jax.vmap(one_b)(
            batch["self_ppair"], batch["self_pkey"], batch["self_ns"]
        )

    mf, ms = jax.vmap(one_t)(tp)
    return mf, ms  # each [T, B, C]


def _eval_reqs_batch_np(op, key, pairs, pair_vecs, key_vecs):
    """numpy twin of eval_reqs_single over a pod batch: op/key [C, R],
    pairs [C, R, V], pair_vecs [B, P] bool, key_vecs [B, K] bool ->
    [B, C] bool. Pad ids are 0 = the never-present sentinel column, so
    plain fancy indexing matches the device gather semantics."""
    from ..models.selectors import (
        OP_EXISTS, OP_FALSE, OP_GT, OP_IN, OP_LT, OP_NOT_EXISTS, OP_NOT_IN,
    )

    any_pair = pair_vecs[:, pairs].any(axis=-1)  # [B, C, R]
    has_key = key_vecs[:, key]                   # [B, C, R]
    res = np.ones_like(has_key, dtype=bool)      # OP_PAD -> True
    res = np.where(op == OP_IN, any_pair, res)
    res = np.where(op == OP_NOT_IN, ~any_pair, res)
    res = np.where(op == OP_EXISTS, has_key, res)
    res = np.where(op == OP_NOT_EXISTS, ~has_key, res)
    res = np.where((op == OP_GT) | (op == OP_LT), False, res)
    res = np.where(op == OP_FALSE, False, res)
    return res.all(axis=-1)  # [B, C]


# tp keys the HOST-side batch prep reads (match_matrices_np); sessions
# snapshot these as numpy at construction so per-batch/per-delta match
# evaluation never round-trips the device
SESSION_TP_NP_KEYS = (
    "ptsf_op", "ptsf_rkey", "ptsf_pairs",
    "ptss_op", "ptss_rkey", "ptss_pairs", "self_ns",
)

# tp keys of the templates' OWN affinity terms — the delta classifier
# (tpu_backend) evaluates a foreign pod's row against these: a pod that
# matches any template term contributes to the prologue's STATIC IPA
# counts (anti_cnt_n / aff_cnt_n / D5 score rows), so its add/remove
# cannot ride the carry-delta fast path
TERM_NP_KEYS = tuple(
    f"{prefix}_{suffix}"
    for prefix in ("ipaaa", "ipaa", "ipap")
    for suffix in ("op", "rkey", "pairs", "ns", "valid")
)


def ipa_term_match_np(term_np: Dict, pod_rows: Dict) -> bool:
    """Does this pod's self row match ANY session template's required /
    preferred (anti-)affinity term (selector + namespaces + validity)?
    Host twin of _term_gates.vs_entity, used by the session-delta
    classifier: matching pods affect prologue statics, not just the
    carry, so they force a rebuild."""
    pp = np.asarray(pod_rows["self_ppair"]).astype(bool)[None]
    pk = np.asarray(pod_rows["self_pkey"]).astype(bool)[None]
    ns = int(np.asarray(pod_rows["self_ns"]))
    t_n = term_np["ipaaa_op"].shape[0]
    for prefix in ("ipaaa", "ipaa", "ipap"):
        valid = term_np[f"{prefix}_valid"].astype(bool)
        if not valid.any():
            continue
        op = term_np[f"{prefix}_op"]
        rkey = term_np[f"{prefix}_rkey"]
        pairs = term_np[f"{prefix}_pairs"]
        ns_tbl = term_np[f"{prefix}_ns"]
        for t in range(t_n):
            if not valid[t].any():
                continue
            m = _eval_reqs_batch_np(op[t], rkey[t], pairs[t], pp, pk)[0]
            ns_ok = ((ns_tbl[t] == ns) & (ns_tbl[t] != 0)).any(axis=-1)
            if (m & ns_ok & valid[t]).any():
                return True
    return False


def match_matrices_np(tp_np: Dict, pod_arrays_list: List[Dict]):
    """Host-side Mf/Ms [T, B, C] — numpy twin of _match_matrices.

    The pallas dispatch packs these into its int8 host->device transfer.
    Computing them with the jnp vmap and then np.asarray-ing the result
    blocks behind everything already enqueued on the device stream —
    including the PREVIOUS batch's scan — which serializes the scheduler
    loop's 1-deep pipeline. Pure-host numpy keeps the dispatch async.

    tp_np: numpy template stacks (fields ptsf_*/ptss_*/self_ns, [T, ...]).
    """
    B = len(pod_arrays_list)
    pair_vecs = np.stack(
        [np.asarray(pa["self_ppair"]) for pa in pod_arrays_list]
    ).astype(bool)
    key_vecs = np.stack(
        [np.asarray(pa["self_pkey"]) for pa in pod_arrays_list]
    ).astype(bool)
    ns = np.asarray(
        [int(np.asarray(pa["self_ns"])) for pa in pod_arrays_list]
    )
    T = tp_np["self_ns"].shape[0]
    C = tp_np["ptsf_op"].shape[1]
    mf = np.zeros((T, B, C), _CNT)
    ms = np.zeros((T, B, C), _CNT)
    for t in range(T):
        ns_ok = ns == int(tp_np["self_ns"][t])  # [B]
        mf[t] = (
            _eval_reqs_batch_np(
                tp_np["ptsf_op"][t], tp_np["ptsf_rkey"][t],
                tp_np["ptsf_pairs"][t], pair_vecs, key_vecs,
            ) & ns_ok[:, None]
        ).astype(_CNT)
        ms[t] = (
            _eval_reqs_batch_np(
                tp_np["ptss_op"][t], tp_np["ptss_rkey"][t],
                tp_np["ptss_pairs"][t], pair_vecs, key_vecs,
            ) & ns_ok[:, None]
        ).astype(_CNT)
    return mf, ms


# ---------------------------------------------------------------------------
# the scan step


def _eval_pod(S: Dict, c_static: Dict, weights: Dict, dyn_ipa: bool,
              dyn_ports: bool, carry: Dict, tj, explain: bool = False):
    """Filter + score one pod of template `tj` against `carry` WITHOUT
    committing: returns (feasible [N] bool, total [N] int64 with -1 at
    infeasible nodes, n_feasible scalar, expl). The one-pod _step and the
    multipod _step_multi both build on this — the eval math exists
    exactly once, so the speculative k-wide evaluation cannot drift
    from the sequential reference.

    expl is None unless `explain`: then a dict with `bits` ([N] int32,
    per-plugin filter verdicts packed in EXPLAIN_FILTER_PLUGINS bit
    order) and `scores` ([8, N] weighted per-plugin components in
    EXPLAIN_SCORE_KEYS order) — the SAME intermediates the total is
    built from, kept instead of folded, so attribution cannot drift
    from the decision."""
    n = c_static["valid"].shape[0]
    vnp = c_static["npair"].shape[1]
    col = jnp.arange(vnp)[None, :]

    def sel(key):
        return S[key][tj]

    # -- NodeResourcesFit (dynamic: carried utilization) --------------------
    req = sel("req")
    mask_fit = K.fit_mask(
        carry["requested"], carry["pod_count"], c_static["alloc"],
        c_static["allowed_pods"], req, sel("req_check"), sel("req_has_any"),
    )

    # -- NodePorts over the carried port tables (dyn_ports) -----------------
    if dyn_ports:
        mask_ports = K.ports_mask(
            carry["cp_any"], carry["cp_wild"], carry["cp_trip"],
            {k: sel(k) for k in _PORT_STEP_KEYS},
        )
    else:
        mask_ports = True

    # -- InterPodAffinity: static parts + in-scan assumed-pod counts --------
    if dyn_ipa:
        u_cnt, k_cnt = carry["u_cnt"], carry["k_cnt"]
        pok, nk = c_static["pair_of_key"], c_static["nkey"]

        # D1: assumed pods' required anti terms repel this pod where it
        # matches them (filtering.go:162 existing-anti map, dynamic part)
        kaa = S["ipaaa_key"]                          # [U, TAA]
        cnt1 = jax.vmap(lambda uc, pv: uc[pv])(
            u_cnt, pok[:, kaa].transpose(1, 0, 2)
        )  # [U, N, TAA]
        g1 = S["M_anti"][:, :, tj]                    # [U, TAA]
        nk1 = nk[:, kaa].transpose(1, 0, 2)           # [U, N, TAA]
        fail_existing_dyn = jnp.any(
            g1[:, None, :] & nk1 & (cnt1 > 0), axis=(0, 2)
        )  # [N]

        # D2: assumed pods counting toward this pod's own anti terms
        g2 = S["M_anti"][tj].astype(_CNT)             # [TAA, U]
        w2 = g2 @ u_cnt                               # [TAA, Vnp]
        p2 = pok[:, sel("ipaaa_key")]                 # [N, TAA]
        anti_dyn = jax.vmap(
            lambda wv, pv: wv[pv], in_axes=(0, 1), out_axes=1
        )(w2, p2)                                     # [N, TAA]

        # D3: assumed pods matching ALL of this pod's affinity terms
        g3 = S["match_all"][tj].astype(_CNT)          # [U]
        w3 = g3 @ u_cnt                               # [Vnp]
        p3 = pok[:, sel("ipaa_key")]                  # [N, Ta]
        aff_dyn = w3[p3]                              # [N, Ta]
        aff_total_dyn = jnp.sum(
            sel("ipaa_valid")[None, :] * g3[:, None] * k_cnt[:, sel("ipaa_key")]
        )

        p_t = {"ipaaa_valid": sel("ipaaa_valid"), "ipaa_valid": sel("ipaa_valid")}
        parts_t = {
            k: sel(f"ipa_{k}")
            for k in ("fail_existing", "anti_cnt_n", "anti_key_on_node",
                      "aff_cnt_n", "aff_all_keys", "aff_total",
                      "self_match_all", "has_aff")
        }
        mask_ipa, _ = K.ipa_compose(
            p_t, parts_t, anti_dyn=anti_dyn, aff_dyn=aff_dyn,
            aff_total_dyn=aff_total_dyn, fail_existing_dyn=fail_existing_dyn,
        )
    else:
        mask_ipa = True

    # -- PTS filter (dynamic counts) ---------------------------------------
    f_valid = sel("f_valid")
    any_f = jnp.any(f_valid)
    cnt = carry["f_cnt"][tj]  # [C, Vnp]
    shared = jnp.sum(
        jnp.where(sel("f_same_key")[:, :, None], cnt[None, :, :], 0), axis=1
    )
    reg_real = sel("f_reg_real")
    big = jnp.iinfo(_CNT).max
    min_c = jnp.min(jnp.where(reg_real, shared, big), axis=1)
    min_c = jnp.where(min_c == big, 0, min_c)
    pair_cn = sel("f_pair_cn")  # [N, C]
    cnt_n = jnp.take_along_axis(shared.T, pair_cn, axis=0)
    reg_n = jnp.take_along_axis(reg_real.T, pair_cn, axis=0)
    cnt_n = jnp.where(reg_n, cnt_n, 0)
    key_on_node = sel("f_key_on_node")
    fail_missing = jnp.any(f_valid[None, :] & ~key_on_node, axis=1)
    skew = cnt_n + sel("f_self_match")[None, :] - min_c[None, :]
    fail_skew = jnp.any(
        f_valid[None, :] & key_on_node & (skew > sel("f_skew")[None, :]), axis=1
    )
    mask_pts = ~(any_f & (fail_missing | fail_skew))

    feasible = sel("static_mask") & mask_fit & mask_pts & mask_ports & mask_ipa

    # -- scores -------------------------------------------------------------
    nz_req = sel("nz_req")
    sc_balanced = K.balanced_score(carry["nz_requested"], nz_req, c_static["alloc"])
    sc_least = K.least_allocated_score(
        carry["nz_requested"], nz_req, c_static["alloc"]
    )

    # PTS score (scoring.go:221-287): registration over the FILTERED set
    s_valid = sel("s_valid")
    any_s = jnp.any(s_valid)
    has_all = sel("s_has_all")
    hostname = sel("s_hostname")
    scored = feasible & has_all
    ignored = feasible & ~has_all
    pair_cn_s = sel("s_pair_cn")  # [N, C]
    reg_s = jax.vmap(
        lambda pids: K._seg_max_bool(scored, jnp.where(scored, pids, 0), vnp),
        in_axes=1,
    )(pair_cn_s)
    reg_real_s = reg_s & (col > 0) & ~hostname[:, None] & s_valid[:, None]
    topo_size = jnp.where(sel("s_first"), jnp.sum(reg_real_s, axis=1), 0).astype(_F64)
    n_scored = jnp.sum(scored).astype(_F64)
    weight = jnp.log(jnp.where(hostname, n_scored, topo_size) + 2.0)
    shared_s = jnp.sum(
        jnp.where(sel("s_same_key")[:, :, None], carry["s_cnt"][tj][None, :, :], 0),
        axis=1,
    )
    cnt_n_s = jnp.take_along_axis(shared_s.T, pair_cn_s, axis=0)
    reg_n_s = jnp.take_along_axis(reg_real_s.T, pair_cn_s, axis=0)
    cnt_n_s = jnp.where(reg_n_s, cnt_n_s, 0)
    cnt_n_s = jnp.where(hostname[None, :], carry["h_cnt"][tj].T, cnt_n_s)
    terms = jnp.where(
        s_valid[None, :] & sel("s_key_on_node"),
        cnt_n_s.astype(_F64) * weight[None, :]
        + (sel("s_skew")[None, :].astype(_F64) - 1.0),
        0.0,
    )
    raw = jnp.sum(terms, axis=1).astype(_I64)
    big64 = jnp.iinfo(jnp.int64).max
    min_r = jnp.min(jnp.where(scored, raw, big64))
    max_r = jnp.max(jnp.where(scored, raw, 0))
    min_r = jnp.where(min_r == big64, 0, min_r)
    norm = K.MAX_NODE_SCORE * (max_r + min_r - raw) // jnp.where(max_r == 0, 1, max_r)
    norm = jnp.where(max_r == 0, K.MAX_NODE_SCORE, norm)
    norm = jnp.where(ignored, 0, norm)
    sc_pts = jnp.where(any_s, norm, 0)

    # -- IPA score: static raw + assumed-pod contributions ------------------
    raw_ipa = sel("raw_ipa")
    ipa_present = sel("ipa_present")
    if dyn_ipa:
        hard_w = c_static["hard_pod_affinity_weight"].astype(_CNT)

        def existing_terms(key_tbl, gate, w):
            """D4: assumed pods' score terms vs this pod. key_tbl [U, X],
            gate [U, X] (match+validity), w [U, X] signed weights."""
            cnt = jax.vmap(lambda uc, pv: uc[pv])(
                u_cnt, pok[:, key_tbl].transpose(1, 0, 2)
            )  # [U, N, X]
            nkx = nk[:, key_tbl].transpose(1, 0, 2)
            contrib = jnp.sum(
                jnp.where(gate[:, None, :] & nkx, cnt, 0)
                * w[:, None, :], axis=(0, 2),
            )  # [N]
            present = jnp.any(gate & (k_cnt[:, key_tbl] > 0))
            return contrib, present

        # required-affinity terms of assumed pods score at hardPodAffinityWeight
        # (scoring.go:88 processExistingPod)
        g4a = S["M_aff"][:, :, tj] & (hard_w > 0)
        c4a, p4a = existing_terms(
            S["ipaa_key"], g4a, jnp.broadcast_to(hard_w, g4a.shape)
        )
        # preferred terms of assumed pods, signed weight
        g4p = S["M_pref"][:, :, tj]
        c4p, p4p = existing_terms(
            S["ipap_key"], g4p, S["ipap_weight"].astype(_CNT)
        )
        # D5: assumed pods vs this pod's own preferred terms
        g5 = S["M_pref"][tj].astype(_CNT)             # [TP, U]
        w5 = g5 @ u_cnt                               # [TP, Vnp]
        p5 = pok[:, sel("ipap_key")]                  # [N, TP]
        cnt5 = jax.vmap(
            lambda wv, pv: wv[pv], in_axes=(0, 1), out_axes=1
        )(w5, p5)                                     # [N, TP]
        c5 = jnp.sum(
            jnp.where(nk[:, sel("ipap_key")], cnt5, 0)
            * sel("ipap_weight").astype(_CNT)[None, :], axis=1,
        )
        p5p = jnp.any((S["M_pref"][tj]) & (k_cnt[:, sel("ipap_key")].T > 0))
        raw_ipa = raw_ipa + c4a + c4p + c5
        ipa_present = ipa_present | p4a | p4p | p5p
    sc_ipa = K._score_ipa_normalize(raw_ipa, ipa_present, feasible)
    sc_taint = K._normalize_default(sel("cnt_taint"), feasible, reverse=True)
    sc_nodeaff = K._normalize_default(sel("cnt_nodeaff"), feasible, reverse=False)

    total = (
        sc_balanced * weights["balanced"]
        + sel("sc_image") * weights["image"]
        + sc_ipa * weights["ipa"]
        + sc_least * weights["least"]
        + sc_nodeaff * weights["node_affinity"]
        + sel("sc_avoid") * weights["prefer_avoid"]
        + sc_pts * weights["pts"]
        + sc_taint * weights["taint"]
    )
    total = jnp.where(feasible, total, -1)
    n_feasible = jnp.sum(feasible.astype(jnp.int32))
    if not explain:
        return feasible, total, n_feasible, None
    # pack the per-plugin verdicts/components the fold normally discards.
    # NodeName is identically true — session pods are unbound
    # (prepare_batch / schedule assert has_node_name is false).
    plugin_masks = (
        jnp.ones(n, bool),
        sel("expl_unsched"),
        sel("expl_taint"),
        mask_ports if dyn_ports else sel("expl_ports"),
        mask_fit,
        sel("node_match"),
        mask_pts,
        mask_ipa if dyn_ipa else sel("expl_ipa"),
    )
    bits = jnp.zeros(n, jnp.int32)
    for i, m in enumerate(plugin_masks):
        bits = bits | (m.astype(jnp.int32) << i)
    scores = jnp.stack(
        [
            sc_balanced * weights["balanced"],
            sel("sc_image") * weights["image"],
            sc_ipa * weights["ipa"],
            sc_least * weights["least"],
            sc_nodeaff * weights["node_affinity"],
            sel("sc_avoid") * weights["prefer_avoid"],
            sc_pts * weights["pts"],
            sc_taint * weights["taint"],
        ]
    )
    return feasible, total, n_feasible, {"bits": bits, "scores": scores}


def _commit_pod(S: Dict, c_static: Dict, dyn_ipa: bool, dyn_ports: bool,
                carry: Dict, tj, j, best, ok):
    """Apply one decided pod (batch row j, template tj, node `best`) to
    the carry — the assume side of the step, shared verbatim by _step
    and _step_multi. All updates are gated on `ok` (no-op for failed /
    padding rows)."""
    req = S["req"][tj]
    nz_req = S["nz_req"][tj]
    add64 = ok.astype(_I64)
    addc = ok.astype(_CNT)

    carry = dict(carry)
    carry["requested"] = carry["requested"].at[best].add(req * add64)
    carry["nz_requested"] = carry["nz_requested"].at[best].add(nz_req * add64)
    carry["pod_count"] = carry["pod_count"].at[best].add(ok.astype(jnp.int32))
    # incremental count updates for EVERY template: the assumed pod's row
    # may match other templates' constraints too
    t_n = S["f_pair_cn"].shape[0]
    c_n = S["f_pair_cn"].shape[2]
    t_idx = jnp.arange(t_n)[:, None]
    c_idx = jnp.arange(c_n)[None, :]
    mf = S["Mf"][:, j, :] * addc  # [T, C]
    ms = S["Ms"][:, j, :] * addc
    pair_b_f = S["f_pair_cn"][:, best, :]  # [T, C]
    pair_b_s = S["s_pair_cn"][:, best, :]
    src_b = S["s_src"][:, best]  # [T]
    carry["f_cnt"] = carry["f_cnt"].at[t_idx, c_idx, pair_b_f].add(mf)
    carry["s_cnt"] = carry["s_cnt"].at[t_idx, c_idx, pair_b_s].add(
        ms * src_b[:, None].astype(_CNT)
    )
    carry["h_cnt"] = carry["h_cnt"].at[:, :, best].add(ms)
    if dyn_ipa:
        # the assumed pod joins its node's topology groups for every key
        # the node carries (pair id 0 rows get +0 via the nkey gate)
        nb = (c_static["nkey"][best] & ok).astype(_CNT)  # [K]
        carry["u_cnt"] = carry["u_cnt"].at[tj, c_static["pair_of_key"][best]].add(nb)
        carry["k_cnt"] = carry["k_cnt"].at[tj].add(nb)
    if dyn_ports:
        carry["cp_any"] = carry["cp_any"].at[best].add(S["padd_any"][tj] * addc)
        carry["cp_wild"] = carry["cp_wild"].at[best].add(S["padd_wild"][tj] * addc)
        carry["cp_trip"] = carry["cp_trip"].at[best].add(S["padd_trip"][tj] * addc)
    return carry


def _step(S: Dict, c_static: Dict, weights: Dict, dyn_ipa: bool,
          dyn_ports: bool, explain_k: int, carry: Dict, x: Dict):
    feasible, total, n_feasible, expl = _eval_pod(
        S, c_static, weights, dyn_ipa, dyn_ports, carry, x["tmpl"],
        explain=explain_k > 0,
    )
    best = jnp.argmax(total).astype(jnp.int32)
    ok = (total[best] >= 0) & x["valid"]
    carry = _commit_pod(
        S, c_static, dyn_ipa, dyn_ports, carry, x["tmpl"], x["j"], best, ok
    )
    y = {
        "best": jnp.where(ok, best, -1),
        "score": jnp.where(ok, total[best], -1),
        "n_feasible": n_feasible,
    }
    if explain_k > 0:
        # top-k candidates with full attribution; lax.top_k breaks ties
        # toward lower indices, the same first-max convention argmax
        # uses, so topk_idx[0] IS the decision
        kk = min(int(explain_k), int(total.shape[0]))
        topv, topi = jax.lax.top_k(total, kk)
        y["expl_bits"] = expl["bits"]
        y["expl_topk_idx"] = topi.astype(jnp.int32)
        y["expl_topk_total"] = topv
        y["expl_topk_scores"] = expl["scores"][:, topi].T  # [kk, 8]
    return carry, y


def _step_multi(S: Dict, c_static: Dict, weights: Dict, dyn_ipa: bool,
                dyn_ports: bool, k: int, carry: Dict, xk: Dict):
    """k pods per scan step with EXACT conflict replay (PERF_NOTES
    round 9): all k pods are filtered + scored in ONE vmapped evaluation
    against the step-initial carry (the device-parallel win — the common
    no-conflict case costs one eval for k pods), then a cheap inner scan
    commits them in order. A pod's speculative decision stands only when
    NONE of the step's earlier committed pods could have perturbed what
    its evaluation read:

      same-node  — an earlier pod consumed capacity on the chosen node
                   (the stale score there cannot stand);
      PTS        — an earlier pod's row matches one of this template's
                   VALID spread selectors (Mf/Ms gated by f/s_valid):
                   the f_cnt/s_cnt/h_cnt rows this pod reads moved.
                   Counts written to invalid constraint slots are never
                   read (f_same_key/terms are valid-gated), so the gate
                   is exact at template granularity;
      IPA        — template-level interference via the prologue's G_ipa
                   superset (u_cnt/k_cnt flow through the D1-D5 gates);
      fit flip / — the shared utilization algebra
      overtake     (kernel.multipod_utilization_conflicts): fit /
                   balanced / least are the ONLY carry-reading plugins
                   left once the count gates are clean, so re-evaluating
                   exactly those three against the current carry decides
                   exactness.

    A conflicted pod REPLAYS in-device (lax.cond) — the full eval against
    the current carry, i.e. the sequential reference computation — so
    decisions, scores and n_feasible stay bit-identical to
    one-pod-per-step whatever the conflict rate. Replays are counted in
    ys["conflicts"] (scheduler_multipod_conflicts_total)."""
    carry0 = carry
    ev_feas, ev_total, ev_nfeas, _ = jax.vmap(
        lambda t: _eval_pod(S, c_static, weights, dyn_ipa, dyn_ports,
                            carry0, t)
    )(xk["tmpl"])
    n = c_static["valid"].shape[0]
    lane = jnp.arange(n, dtype=jnp.int32)
    w_bal = weights["balanced"]
    w_least = weights["least"]
    alloc = c_static["alloc"]

    def wbl(nz_requested, nz_req):
        return (
            K.balanced_score(nz_requested, nz_req, alloc) * w_bal
            + K.least_allocated_score(nz_requested, nz_req, alloc) * w_least
        )

    def inner(state, i):
        carry_i, best_arr, ok_arr = state
        tj = xk["tmpl"][i]
        jj = xk["j"][i]
        valid_i = xk["valid"][i]
        total_i = ev_total[i]
        feas_i = ev_feas[i]
        best_spec = jnp.argmax(total_i).astype(jnp.int32)
        score_spec = total_i[best_spec]
        # committed earlier pods of this step (placed: best_arr >= 0)
        prior = (jnp.arange(k) < i) & ok_arr
        same = jnp.any(prior & (best_arr == best_spec)) & (score_spec >= 0)
        mf_k = (S["Mf"][tj][xk["j"]] != 0) & S["f_valid"][tj][None, :]
        ms_k = (S["Ms"][tj][xk["j"]] != 0) & S["s_valid"][tj][None, :]
        pts_conf = jnp.any(
            prior & (jnp.any(mf_k, axis=1) | jnp.any(ms_k, axis=1))
        )
        if dyn_ipa:
            ipa_conf = jnp.any(prior & S["G_ipa"][xk["tmpl"], tj])
        else:
            ipa_conf = jnp.bool_(False)
        nz_req = S["nz_req"][tj]
        fit_new = K.fit_mask(
            carry_i["requested"], carry_i["pod_count"], alloc,
            c_static["allowed_pods"], S["req"][tj], S["req_check"][tj],
            S["req_has_any"][tj],
        )
        flip_row, over_row = K.multipod_utilization_conflicts(
            feas_i, total_i, best_spec, score_spec, lane, fit_new,
            wbl(carry0["nz_requested"], nz_req),
            wbl(carry_i["nz_requested"], nz_req),
        )
        util_conf = jnp.any(flip_row) | (
            jnp.any(over_row) & (score_spec >= 0)
        )
        conflict = (same | pts_conf | ipa_conf | util_conf) & valid_i

        def replay(c):
            _, t2, nf2, _ = _eval_pod(
                S, c_static, weights, dyn_ipa, dyn_ports, c, tj
            )
            b2 = jnp.argmax(t2).astype(jnp.int32)
            return b2, t2[b2], nf2

        def spec(c):
            return best_spec, score_spec, ev_nfeas[i]

        best, score, n_feasible = jax.lax.cond(conflict, replay, spec,
                                               carry_i)
        ok = (score >= 0) & valid_i
        carry_i = _commit_pod(
            S, c_static, dyn_ipa, dyn_ports, carry_i, tj, jj, best, ok
        )
        y = {
            "best": jnp.where(ok, best, -1),
            "score": jnp.where(ok, score, -1),
            "n_feasible": n_feasible,
            "conflicts": conflict.astype(jnp.int32),
        }
        return (
            (carry_i, best_arr.at[i].set(jnp.where(ok, best, -1)),
             ok_arr.at[i].set(ok)),
            y,
        )

    state = (carry, jnp.full(k, -1, jnp.int32), jnp.zeros(k, bool))
    (carry, _, _), ys = jax.lax.scan(inner, state, jnp.arange(k))
    return carry, ys


# tp keys the step reads directly when the dynamic-IPA / dynamic-ports
# machinery is on
_TERM_STEP_KEYS = (
    "ipaaa_key", "ipaaa_valid", "ipaa_key", "ipaa_valid",
    "ipap_key", "ipap_weight",
)
_PORT_STEP_KEYS = ("want_pair", "want_triple", "want_wild", "want_valid")


def _merge_step_inputs(S: Dict, tp: Dict, dyn_ipa: bool, dyn_ports: bool,
                       port_adds) -> None:
    for k in ("req", "req_check", "req_has_any", "nz_req"):
        S[k] = tp[k]
    if dyn_ipa:
        for k in _TERM_STEP_KEYS:
            S[k] = tp[k]
    if dyn_ports:
        for k in _PORT_STEP_KEYS:
            S[k] = tp[k]
        S["padd_any"], S["padd_wild"], S["padd_trip"] = port_adds


def _init_dynamic_carries(carry: Dict, c_all: Dict, n_templates: int,
                          dyn_ipa: bool, dyn_ports: bool) -> None:
    """Zero-initialize the assumed-pod count carries and copy-adopt the
    port tables. The copies are unconditional (not astype tricks): the
    session scan DONATES its carry, and donating a buffer the encoder's
    device-state cache still references is the session-killing bug class
    fixed in commit ee84cbf."""
    if dyn_ipa:
        vnp = c_all["npair"].shape[1]
        k_n = c_all["nkey"].shape[1]
        carry["u_cnt"] = jnp.zeros((n_templates, vnp), _CNT)
        carry["k_cnt"] = jnp.zeros((n_templates, k_n), _CNT)
    if dyn_ports:
        carry["cp_any"] = jnp.array(c_all["ports_pair_any"], dtype=_CNT)
        carry["cp_wild"] = jnp.array(c_all["ports_pair_wild"], dtype=_CNT)
        carry["cp_trip"] = jnp.array(c_all["ports_triple"], dtype=_CNT)


@functools.partial(
    jax.jit, static_argnames=("weights_key", "dyn_ipa", "dyn_ports",
                              "explain_k")
)
def _run(c_all: Dict, tp: Dict, batch_self: Dict, xs: Dict, weights_key,
         dyn_ipa: bool = False, dyn_ports: bool = False, port_adds=None,
         explain_k: int = 0):
    weights = dict(weights_key)
    S = _prologue(c_all, tp, dyn_ipa, dyn_ports, explain=explain_k > 0)
    mf, ms = _match_matrices(tp, batch_self)
    S["Mf"], S["Ms"] = mf, ms
    _merge_step_inputs(S, tp, dyn_ipa, dyn_ports, port_adds)
    carry = {
        "requested": c_all["requested"],
        "nz_requested": c_all["nz_requested"],
        "pod_count": c_all["pod_count"],
        "f_cnt": S.pop("f_cnt0"),
        "s_cnt": S.pop("s_cnt0"),
        "h_cnt": S.pop("h_cnt0"),
    }
    _init_dynamic_carries(carry, c_all, tp["req"].shape[0], dyn_ipa, dyn_ports)
    c_static = {k: v for k, v in c_all.items() if k not in CARRY_KEYS}
    step = functools.partial(_step, S, c_static, weights, dyn_ipa, dyn_ports,
                             explain_k)
    return jax.lax.scan(step, carry, xs)


def batch_bucket(b: int, minimum: int = 64) -> int:
    """Power-of-two batch-length bucket: every distinct scan length is a
    fresh XLA compile, so ragged production batches (the queue drains
    whatever arrived) are padded to at most log2 distinct shapes."""
    cap = minimum
    while cap < b:
        cap *= 2
    return cap


def _batch_inputs(
    pod_arrays_list: List[Dict], tmpl_ids: np.ndarray, pad_to: int = 0
) -> Tuple[Dict, Dict]:
    """(batch_self, xs) for one scan over these pods (shared by
    prepare_batch and HoistedSession.schedule — the scan's xs contract
    lives here and nowhere else). Rows past len(pod_arrays_list) (up to
    pad_to) are zero-filled with valid=False: the step gates every carry
    update on valid, so they are pure no-ops."""
    b = len(pod_arrays_list)
    bp = max(pad_to, b)

    def stack(key):
        a = np.stack([np.asarray(pa[key]) for pa in pod_arrays_list])
        if bp > b:
            a = np.concatenate(
                [a, np.zeros((bp - b,) + a.shape[1:], a.dtype)]
            )
        return jnp.asarray(a)

    batch_self = {k: stack(k) for k in ("self_ppair", "self_pkey", "self_ns")}
    tmpl = np.zeros(bp, np.int32)
    tmpl[:b] = tmpl_ids
    xs = {
        "tmpl": jnp.asarray(tmpl),
        "j": jnp.arange(bp, dtype=jnp.int32),
        "valid": jnp.asarray(np.arange(bp) < b),
    }
    return batch_self, xs


def prepare_batch(
    pod_arrays_list: List[Dict],
) -> Tuple[Dict, Dict, Dict, List[Dict]]:
    """Group the batch by template and build the scan inputs: (stacked
    templates, batch self-rows, xs, template list). Pods with affinity
    terms and host ports ARE hoistable — the scan carries their dynamic
    effects (see the term-machinery block above); only bound pods
    (spec.nodeName) are excluded."""
    b = len(pod_arrays_list)
    for pa in pod_arrays_list:
        assert not bool(np.asarray(pa["has_node_name"])), "hoisted: pods must be unbound"
    fps: Dict[Tuple, int] = {}
    templates: List[Dict] = []
    tmpl_ids = np.zeros(b, np.int32)
    for i, pa in enumerate(pod_arrays_list):
        fp = template_fingerprint(pa)
        t = fps.get(fp)
        if t is None:
            t = len(templates)
            fps[fp] = t
            templates.append(pa)
        tmpl_ids[i] = t
    tp = _stack_templates(templates)
    batch_self, xs = _batch_inputs(pod_arrays_list, tmpl_ids)
    return tp, batch_self, xs, templates


def _port_adds_for(templates: List[Dict], cluster: Dict):
    return tuple(
        jnp.asarray(a)
        for a in _port_add_vectors(
            templates,
            cluster["ports_pair_any"].shape[1],
            cluster["ports_triple"].shape[1],
        )
    )


# ktpu: allow-sync(harvest decode: one-shot API drains decisions to host lists by design)
def schedule_batch_hoisted(
    cluster: Dict,
    pod_arrays_list: List[Dict],
    weights: Optional[Dict[str, int]] = None,
    explain_k: int = 0,
) -> Tuple[List[int], Dict]:
    """Schedule a batch with template hoisting (affinity/port pods
    included — their assume effects ride the dynamic carries). Pods must
    be unbound (no spec.nodeName). Returns (decisions, ys).

    explain_k > 0 additionally returns per-pod attribution in ys
    (expl_bits / expl_topk_*; see HoistedSession.explain_payload).
    Decisions are bit-identical either way — explain only KEEPS
    intermediates the fold otherwise discards."""
    tp, batch_self, xs, templates = prepare_batch(pod_arrays_list)
    dyn_ipa = templates_have_terms(templates)
    dyn_ports = templates_have_ports(templates)
    port_adds = _port_adds_for(templates, cluster) if dyn_ports else None
    key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
    _, ys = _run(cluster, tp, batch_self, xs, key, dyn_ipa, dyn_ports,
                 port_adds, explain_k)
    return [int(v) for v in np.asarray(ys["best"])], ys


# ---------------------------------------------------------------------------
# cross-batch session: carry lives on-device, prologue runs ONCE


@functools.partial(jax.jit, static_argnames=("dyn_ipa", "dyn_ports",
                                             "explain"))
def _session_prologue(c_all: Dict, tp: Dict, dyn_ipa: bool = False,
                      dyn_ports: bool = False, explain: bool = False) -> Dict:
    return _prologue(c_all, tp, dyn_ipa, dyn_ports, explain)


@functools.partial(jax.jit, donate_argnames=("carry",))
def _session_apply_deltas(carry, f_pair_cn, s_pair_cn, s_src,
                          nodes, dres, dnz, dcount, mf, ms):
    """Apply a batch of cluster-event deltas to the session carry in ONE
    fused launch: per event e, a batchable pod landed on (sign +1) or
    left (sign -1) node nodes[e]. The math is exactly the _step carry
    update with `best := nodes[e]` — utilization rows plus the PTS
    pair-count scatter through the same match vectors — so a
    delta-patched carry is bit-identical to one whose scan assumed /
    never saw the pod. mf/ms arrive sign-multiplied (and zeroed for
    terminating pods, which the prologue's ~pterm gate never counted);
    padding rows are node 0 with all-zero payloads (pure no-ops). The
    old carry buffers are donated, chaining the patch onto any in-flight
    scans as a pure data dependency."""
    carry = dict(carry)
    carry["requested"] = carry["requested"].at[nodes].add(dres)
    carry["nz_requested"] = carry["nz_requested"].at[nodes].add(dnz)
    carry["pod_count"] = carry["pod_count"].at[nodes].add(dcount)
    t_n, _, c_n = f_pair_cn.shape[0], f_pair_cn.shape[1], f_pair_cn.shape[2]
    t_ix = jnp.arange(t_n)[:, None, None]
    c_ix = jnp.arange(c_n)[None, None, :]
    mf_t = jnp.transpose(mf, (1, 0, 2))                   # [T, E, C]
    ms_t = jnp.transpose(ms, (1, 0, 2))
    pair_f = f_pair_cn[:, nodes, :]                       # [T, E, C]
    carry["f_cnt"] = carry["f_cnt"].at[t_ix, c_ix, pair_f].add(mf_t)
    pair_s = s_pair_cn[:, nodes, :]
    src = s_src[:, nodes].astype(mf.dtype)                # [T, E]
    carry["s_cnt"] = carry["s_cnt"].at[t_ix, c_ix, pair_s].add(
        ms_t * src[:, :, None]
    )
    c2_ix = jnp.arange(c_n)[None, :, None]
    carry["h_cnt"] = carry["h_cnt"].at[
        t_ix, c2_ix, nodes[None, None, :]
    ].add(jnp.transpose(ms, (1, 2, 0)))
    return carry


@functools.partial(
    jax.jit,
    static_argnames=("weights_key", "dyn_ipa", "dyn_ports", "k",
                     "explain_k"),
    donate_argnames=("carry",),
)
def _session_scan(S, c_static, tp, carry, batch_self, xs, weights_key,
                  dyn_ipa: bool = False, dyn_ports: bool = False,
                  k: int = 1, explain_k: int = 0):
    weights = dict(weights_key)
    S = dict(S)
    S["Mf"], S["Ms"] = _match_matrices(tp, batch_self)
    # unroll: the tunnel pays a fixed cost per fused-kernel launch, and
    # launches scale with scan iterations; unrolling trades compile time
    # for fewer iterations (semantics identical) — see PERF_NOTES.md
    unroll = knobs.get_int("KTPU_SCAN_UNROLL")
    if k <= 1 or explain_k > 0:
        # explain rides the one-pod-per-step scan (the session pins
        # multipod_k to 1 in explain mode; decisions are identical)
        step = functools.partial(_step, S, c_static, weights, dyn_ipa,
                                 dyn_ports, explain_k)
        return jax.lax.scan(step, carry, xs, unroll=unroll)
    # multipod: fold the batch axis into [steps, k] — every pow2 bucket
    # divides by the pow2 k (kernel.multipod_k clamps it) — and run the
    # k-wide step; ys come back [steps, k, ...] and unfold to [Bp, ...]
    bp = int(xs["tmpl"].shape[0])
    xk = {key: v.reshape((bp // k, k) + v.shape[1:]) for key, v in xs.items()}
    step = functools.partial(_step_multi, S, c_static, weights, dyn_ipa,
                             dyn_ports, k)
    carry, ys = jax.lax.scan(step, carry, xk, unroll=unroll)
    ys = {key: v.reshape((bp,) + v.shape[2:]) for key, v in ys.items()}
    return carry, ys


class HoistedSession:
    """Hoisted scheduling with the carry kept ON-DEVICE across batches.

    The one session kind with explain support (supports_explain): with
    explain_k > 0 every scan step also returns packed per-plugin filter
    bits and the top-k candidates' weighted score stacks, decoded by
    explain_payload (decisions stay bit-identical; multipod pins to 1).

    schedule_batch_hoisted pays the prologue (per-template pod-table
    sweeps + count bases) and a full cluster upload on EVERY dispatch
    because the host syncs assumed pods into the pod table between
    batches. That sync is redundant for batchable pods: a batchable pod
    (no affinity terms, no host ports — ops/batch.py pod_batchable) has
    no term/port rows, so assuming it changes exactly (a) node
    utilization (requested / nz_requested / pod_count — NodeResourcesFit,
    Balanced, LeastAllocated inputs) and (b) PodTopologySpread pair
    counts. Both are *already* the scan's carry. Every other prologue
    product — IPA raw scores and anti-affinity masks (driven by TERM
    rows, which batchable pods don't add), taint/affinity/ports/
    unschedulable masks, image and prefer-avoid scores (node-side) — is
    invariant under batchable assumes.

    So the session computes the prologue once, keeps carry + statics
    device-resident, and schedules batch after batch with ZERO host
    round-trips on the critical path. Dispatch is async: schedule()
    returns device arrays immediately, so the host can encode batch k+1
    while the device scans batch k (the pipelining bench.py exploits).

    Decision parity with the per-batch hoisted path (host-synced between
    batches) — and therefore with the generic scan and the Go oracle —
    is pinned by tests/test_hoisted.py::TestHoistedSession.

    The template set is fixed at construction: a batch pod whose
    fingerprint is unknown raises KeyError, and the caller falls back to
    a host sync + fresh session (or the generic path).

    Reference frame: this is the assume-cache discipline of the
    reference's scheduler cache (pkg/scheduler/internal/cache/cache.go:361
    AssumePod — mutate the in-memory view, confirm later) applied to the
    device-resident arrays: the device carry IS the assume cache.
    """

    supports_explain = True

    def __init__(
        self,
        cluster: Dict,
        template_arrays_list: List[Dict],
        weights: Optional[Dict[str, int]] = None,
        multipod_k: Optional[int] = None,
        explain_k: int = 0,
    ):
        self._weights_key = tuple(sorted((weights or DEFAULT_WEIGHTS).items()))
        self.explain_k = max(0, int(explain_k or 0))
        self._fps = {
            template_fingerprint(t): i for i, t in enumerate(template_arrays_list)
        }
        self._dyn_ipa = templates_have_terms(template_arrays_list)
        # uniform session-delta interface (tpu_backend classification):
        # dyn_ipa names whether templates carry IPA terms — a foreign pod
        # matching one would perturb prologue STATICS, not just the carry
        self.dyn_ipa = self._dyn_ipa
        self._dyn_ports = templates_have_ports(template_arrays_list)
        port_adds = (
            _port_adds_for(template_arrays_list, cluster)
            if self._dyn_ports else None
        )
        tp = _stack_templates(template_arrays_list)
        S = dict(_session_prologue(cluster, tp, self._dyn_ipa,
                                   self._dyn_ports, self.explain_k > 0))
        # copies: _session_scan donates the carry, and the cluster arrays
        # are also held by the encoder's device-state cache
        self._carry = {
            "requested": jnp.array(cluster["requested"], copy=True),
            "nz_requested": jnp.array(cluster["nz_requested"], copy=True),
            "pod_count": jnp.array(cluster["pod_count"], copy=True),
            "f_cnt": S.pop("f_cnt0"),
            "s_cnt": S.pop("s_cnt0"),
            "h_cnt": S.pop("h_cnt0"),
        }
        _init_dynamic_carries(
            self._carry, cluster, len(template_arrays_list),
            self._dyn_ipa, self._dyn_ports,
        )
        _merge_step_inputs(S, tp, self._dyn_ipa, self._dyn_ports, port_adds)
        self._S = S
        self._tp = tp
        self._c_static = {k: v for k, v in cluster.items() if k not in CARRY_KEYS}
        # host-side numpy snapshots for the session-delta path: match
        # evaluation (match_matrices_np) and the term-match classifier
        # must never block behind the device stream
        self._tp_np = {k: np.asarray(tp[k]) for k in SESSION_TP_NP_KEYS}
        self._term_np = (
            {k: np.asarray(tp[k]) for k in TERM_NP_KEYS}
            if self._dyn_ipa else None
        )
        # multi-pod scan steps (PERF_NOTES round 9): k pods decided per
        # step with exact in-device conflict replay (_step_multi).
        # Port-carrying sessions are pinned to k=1 — the carried NodePorts
        # tables sit outside the conflict algebra (kernel.multipod_k)
        self.multipod_k = K.multipod_k(multipod_k, dyn_ports=self._dyn_ports)
        if self.explain_k:
            # explain mode pins one-pod-per-step: attribution is per
            # decided pod against its exact decision-time carry, which
            # the k-wide speculative evaluation cannot provide for
            # conflicted pods. Decisions are bit-identical either way
            # (the multipod contract).
            self.multipod_k = 1

    # -- incremental device-state deltas -----------------------------------

    def delta_compatible(self, dres, dnz) -> bool:
        """Every int64 utilization delta is exactly representable in this
        session's carry (no rescale on the jnp path)."""
        return True

    def apply_deltas(self, deltas: List[Dict]) -> None:
        """Reconcile the live session with a batch of host-encoding
        mutations WITHOUT a rebuild. Two kinds (classified by the
        backend, tpu_backend._queue_pod_delta):

          kind=pod-add / pod-remove — a batchable pod landed on / left a
          known node: utilization row + PTS pair counts, i.e. exactly
          the scan's carry (the PERF_NOTES session invariant run in
          reverse for removes). One fused launch for the whole batch.

          kind=node-alloc — an allocatable-only node update: patches the
          static alloc/allowed_pods rows (prologue products never read
          alloc, so the carry and every other static stay valid).

        Parity contract: a delta-patched session produces bit-identical
        decisions to a fresh rebuild from the mutated encoding
        (tests/test_session_deltas.py pins it over randomized event
        interleavings)."""
        pods = [d for d in deltas if d["kind"] != "node-alloc"]
        for d in deltas:
            if d["kind"] != "node-alloc":
                continue
            n = d["node"]
            self._c_static["alloc"] = (
                self._c_static["alloc"].at[n].add(jnp.asarray(d["dalloc"]))
            )
            self._c_static["allowed_pods"] = (
                self._c_static["allowed_pods"].at[n].add(d["dallowed"])
            )
        if not pods:
            return
        e = len(pods)
        ep = batch_bucket(e, minimum=4)  # pow2: one compile per bucket
        r = self._carry["requested"].shape[1]
        t_n = self._S["f_pair_cn"].shape[0]
        c_n = self._S["f_pair_cn"].shape[2]
        nodes = np.zeros(ep, np.int32)
        dres = np.zeros((ep, r), np.int64)
        dnz = np.zeros((ep, 2), np.int64)
        dcount = np.zeros(ep, np.int32)
        mf = np.zeros((ep, t_n, c_n), _CNT)
        ms = np.zeros((ep, t_n, c_n), _CNT)
        for i, d in enumerate(pods):
            nodes[i] = d["node"]
            dres[i] = d["dres"]
            dnz[i] = d["dnz"]
            dcount[i] = d["dcount"]
            mf[i] = d["mf"]
            ms[i] = d["ms"]
        self._carry = _session_apply_deltas(
            self._carry, self._S["f_pair_cn"], self._S["s_pair_cn"],
            self._S["s_src"],
            jnp.asarray(nodes), jnp.asarray(dres), jnp.asarray(dnz),
            jnp.asarray(dcount), jnp.asarray(mf), jnp.asarray(ms),
        )

    def schedule(self, pod_arrays_list: List[Dict]) -> Dict:
        """Enqueue one batch; returns ys (device arrays) WITHOUT blocking.

        Call decisions(ys) to synchronize. Raises KeyError on a pod whose
        template was not registered at construction."""
        b = len(pod_arrays_list)
        tmpl_ids = np.zeros(b, np.int32)
        for i, pa in enumerate(pod_arrays_list):
            if bool(np.asarray(pa["has_node_name"])):
                raise ValueError("session pods must be unbound")
            tmpl_ids[i] = self._fps[template_fingerprint(pa)]
        batch_self, xs = _batch_inputs(
            pod_arrays_list, tmpl_ids, pad_to=batch_bucket(b)
        )
        self._carry, ys = _session_scan(
            self._S, self._c_static, self._tp, self._carry,
            batch_self, xs, self._weights_key,
            self._dyn_ipa, self._dyn_ports, self.multipod_k,
            self.explain_k,
        )
        ys = dict(ys)
        ys["_b_real"] = b  # padding rows carry no decision
        return ys

    @staticmethod
    # ktpu: allow-sync(harvest decode: host consumes batch verdicts after the launch completes)
    def decisions(ys: Dict) -> List[int]:
        """Block on a batch's results and return node indices (-1 =
        unschedulable), bucket-padding rows stripped."""
        best = np.asarray(ys["best"])
        return [int(v) for v in best[: ys.get("_b_real", best.shape[0])]]

    @staticmethod
    # ktpu: allow-sync(harvest decode: host reads conflict planes after the launch completes)
    def conflict_stats(ys: Dict):
        """(n_conflicts, replay_suffix_start) for one harvested batch.
        The hoisted scan replays conflicted pods IN-DEVICE (_step_multi
        lax.cond), so every decision is already exact: the suffix is
        always None and the count is observability only
        (scheduler_multipod_conflicts_total)."""
        c = ys.get("conflicts")
        if c is None:
            return 0, None
        arr = np.asarray(c)
        return int(arr[: ys.get("_b_real", arr.shape[0])].sum()), None

    @staticmethod
    # ktpu: allow-sync(harvest decode: explain attribution is read back off the hot path)
    def explain_payload(ys: Dict):
        """Per-pod attribution from an explain-mode batch, or None when
        the batch ran with explain off (any session kind — the keys are
        simply absent then, so the backend can call this unconditionally
        on harvested ys). Padding rows stripped; each entry:

          bits        [N] int32 — bit i set = EXPLAIN_FILTER_PLUGINS[i]
                      passed the node (a rejected node's zero bits name
                      the plugins that filtered it);
          topk_idx    [k] candidate node indices, best first (index 0 is
                      the decision when the pod was placed);
          topk_total  [k] decision totals (-1 = infeasible);
          topk_scores [k, 8] weighted per-plugin split in
                      EXPLAIN_SCORE_KEYS order (rows sum to the total on
                      feasible nodes)."""
        if "expl_bits" not in ys:
            return None
        bits = np.asarray(ys["expl_bits"])
        idx = np.asarray(ys["expl_topk_idx"])
        tot = np.asarray(ys["expl_topk_total"])
        sc = np.asarray(ys["expl_topk_scores"])
        b = ys.get("_b_real", bits.shape[0])
        return [
            {"bits": bits[i], "topk_idx": idx[i], "topk_total": tot[i],
             "topk_scores": sc[i]}
            for i in range(b)
        ]
